"""Fault-tolerant task lifecycle: leases, retries, dead-letter, resume.

The paper's headline number — 18 PB produced on 3600 cloud nodes — rests
on queue-mediated fault tolerance: the fleet runs on preemptible
instances that crash constantly, and the visibility-timeout +
ack-after-write protocol (reference lib/aws/sqs_queue.py) is what makes
the volume converge anyway. ``parallel/queues.py`` gives us the
transport; this module is the supervision layer that turns at-least-once
delivery into exactly-once *effects*:

* **Durable completion ledger** (:func:`open_ledger`): one done-marker
  per bbox string in a ``memory://`` or ``file://`` store. A requeued,
  replayed, or crash-redelivered task whose bbox is already marked is
  acked and skipped without recompute — an interrupted volume run
  resumes from where it died by simply replaying the task queue.
* **Lease heartbeats** (:class:`LeaseRenewer`): a renewal thread extends
  the claim's visibility timeout while the task is in compute, so a
  slow chunk (fat patch, cold compile) is not double-claimed by another
  worker when it outlives the static timeout.
* **Retry accounting + dead-letter**: per-task receive counts
  (``queue.receive_count``) bound retries; the supervisor classifies
  transient vs permanent errors (:func:`classify_error`), applies
  exponential backoff with jitter by re-claiming the task's visibility
  for the backoff window, and moves poison tasks past ``--max-retries``
  to the queue's dead-letter store with their failure reason
  (inspect/requeue via ``chunkflow dead-letter``).
* **Graceful preemption**: SIGTERM (install via
  :func:`install_preemption_handler`) and SIGINT unwind into the
  supervision path, which promptly nacks the in-flight task — immediate
  visibility release, another worker picks it up now instead of after
  the timeout — and flushes its pending async writes before exit,
  modeling preemptible-VM / TPU-preemption behavior.

Integration: ``fetch-task-from-queue --max-retries/--lease-renew/--ledger``
builds a :class:`LifecycleSupervisor`; ``delete-task-in-queue`` calls
:meth:`TaskLifecycle.commit` (the ack-after-durable-write commit point);
``flow/runtime.process_stream`` consults :func:`handle_failure` when the
stage chain dies, releasing every in-flight task and rebuilding the
chain — so the PR 4 adaptive scheduler (whose error path flushes the
survivors downstream first) runs *inside* a supervised worker loop.

Everything is telemetry-instrumented (``tasks/retried``,
``tasks/dead_lettered``, ``lease/renewals``, ``ledger/skips`` counters;
``lifecycle/*`` spans) and fault-injectable at every stage boundary
(``chunkflow_tpu/testing/chaos.py``, ``CHUNKFLOW_CHAOS``). See
docs/fault_tolerance.md for the state diagram and resume cookbook.
"""
from __future__ import annotations

import os
import random
import signal
import sys
import threading
import time
from typing import Dict, Iterator, List, Optional

from chunkflow_tpu.core import telemetry
from chunkflow_tpu.parallel.queues import QueueBase
from chunkflow_tpu.testing import chaos

__all__ = [
    "TransientTaskError", "PermanentTaskError", "classify_error",
    "backoff_delay", "LedgerBase", "MemoryLedger", "FileLedger",
    "open_ledger", "LeaseRenewer", "TaskLifecycle",
    "LifecycleSupervisor", "inflight", "handle_failure", "tag_culprit",
    "surrender_task", "install_preemption_handler",
]


# ---------------------------------------------------------------------------
# error classification
# ---------------------------------------------------------------------------
class TransientTaskError(RuntimeError):
    """Raise to force a retry regardless of the default classification
    (e.g. a storage backend's own throttling error)."""


class PermanentTaskError(RuntimeError):
    """Raise to force a dead-letter regardless of retry budget (the
    task itself is invalid; retrying burns fleet time for nothing)."""


#: poison-task signatures: bad input or a programming error — identical
#: on every retry, so the supervisor dead-letters without burning the
#: retry budget. Everything else (IO, preemption, chaos) is transient.
_PERMANENT_TYPES = (
    PermanentTaskError, ValueError, TypeError, KeyError, IndexError,
    AttributeError, AssertionError, ZeroDivisionError, NotImplementedError,
)


def classify_error(exc: BaseException) -> str:
    """``"transient"`` (retry with backoff) or ``"permanent"``
    (dead-letter now)."""
    if isinstance(exc, (TransientTaskError, chaos.ChaosError)):
        return "transient"
    if isinstance(exc, _PERMANENT_TYPES):
        return "permanent"
    return "transient"


def backoff_delay(attempt: int, base: float = 0.5, cap: float = 60.0,
                  rng: Optional[random.Random] = None) -> float:
    """Exponential backoff with full jitter: uniform in
    ``[0, min(cap, base * 2**(attempt-1))]``. Full jitter (vs. equal
    jitter) maximally decorrelates a fleet retrying the same dependency
    outage — the regime the paper's 3600 nodes live in."""
    ceiling = min(cap, base * (2 ** max(0, attempt - 1)))
    draw = rng.random() if rng is not None else random.random()
    return draw * ceiling


# ---------------------------------------------------------------------------
# completion ledger
# ---------------------------------------------------------------------------
class LedgerBase:
    """Done-markers keyed by task body (bbox string). ``mark_done`` must
    be idempotent and atomic: exactly one marker per key no matter how
    many times a replayed task commits."""

    def is_done(self, key: str) -> bool:
        raise NotImplementedError

    def mark_done(self, key: str) -> None:
        raise NotImplementedError

    def keys(self) -> List[str]:
        raise NotImplementedError

    def __contains__(self, key: str) -> bool:
        return self.is_done(key)

    def __len__(self) -> int:
        return len(self.keys())


class MemoryLedger(LedgerBase):
    """In-process ledger (tests, single-worker runs)."""

    _registry: Dict[str, "MemoryLedger"] = {}

    def __init__(self, name: str = ""):
        self.name = name
        self._done: Dict[str, float] = {}
        self._lock = threading.Lock()

    @classmethod
    def open(cls, name: str) -> "MemoryLedger":
        if name not in cls._registry:
            cls._registry[name] = cls(name)
        return cls._registry[name]

    def is_done(self, key: str) -> bool:
        with self._lock:
            return key in self._done

    def mark_done(self, key: str) -> None:
        with self._lock:
            self._done.setdefault(key, time.time())

    def keys(self) -> List[str]:
        with self._lock:
            return sorted(self._done)


class FileLedger(LedgerBase):
    """One ``<dir>/<key>.done`` file per completed task; atomic
    tmp+rename writes so a marker is never torn. Safe across
    processes/hosts on a shared filesystem — the resume substrate for a
    fleet (same trust model as FileQueue)."""

    SUFFIX = ".done"

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: str) -> str:
        # bbox strings are filename-safe by construction; guard anyway
        return os.path.join(self.dir, key.replace(os.sep, "_") + self.SUFFIX)

    def is_done(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def mark_done(self, key: str) -> None:
        path = self._path(key)
        if os.path.exists(path):
            return  # idempotent: exactly one marker per key
        tmp = os.path.join(self.dir, f".tmp-{os.getpid()}-{id(self)}")
        with open(tmp, "w") as f:
            f.write(str(time.time()))
        os.replace(tmp, path)

    def keys(self) -> List[str]:
        return sorted(
            name[: -len(self.SUFFIX)]
            for name in os.listdir(self.dir)
            if name.endswith(self.SUFFIX)
        )


def open_ledger(spec: str) -> LedgerBase:
    """``memory://name`` or ``file:///dir`` (bare paths mean file://)."""
    if spec.startswith("memory://"):
        return MemoryLedger.open(spec[len("memory://"):])
    if spec.startswith("file://"):
        spec = spec[len("file://"):]
    return FileLedger(spec)


# ---------------------------------------------------------------------------
# lease heartbeats
# ---------------------------------------------------------------------------
def _renew_with_retry(queue: QueueBase, handle: str,
                      timeout: Optional[float] = None,
                      attempts: int = 3, base: float = 0.05) -> bool:
    """One heartbeat renewal, retried in place on transient transport
    errors (SQS throttle, network blip) with short exponential backoff.
    A single raised renew must not cost the whole heartbeat — on a busy
    fleet that silently forfeits every lease this thread guards. Each
    failed attempt counts ``lifecycle/renew_errors``; only giving up
    after ``attempts`` counts ``lease/renew_failures`` (the lease may
    genuinely be lost — another worker owns the task now — and the
    ledger makes the duplicate effect-free)."""
    for attempt in range(1, attempts + 1):
        try:
            with telemetry.span("lifecycle/renew"):
                queue.renew(handle, timeout)
            telemetry.inc("lease/renewals")
            return True
        except Exception:
            telemetry.inc("lifecycle/renew_errors")
            if attempt < attempts:
                time.sleep(base * (2 ** (attempt - 1)))
    telemetry.inc("lease/renew_failures")
    return False


class LeaseRenewer:
    """Daemon thread extending a claimed task's visibility lease every
    ``interval`` seconds while compute runs, so a slow chunk is not
    double-claimed when it outlives the static visibility timeout. A
    failed renewal is counted, not fatal: the lease may already be lost
    (another worker owns the task now), but *this* attempt's commit path
    still runs — the ledger makes the duplicate effect-free."""

    def __init__(self, queue: QueueBase, handle: str, interval: float,
                 timeout: Optional[float] = None):
        self.queue = queue
        self.handle = handle
        self.interval = max(0.05, float(interval))
        self.timeout = timeout
        self.renewals = 0
        # guards the renewals counter: the renewer thread increments it
        # while supervisors/tests read it live (GL010)
        self._count_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"lease-renewer-{handle[:8]}",
        )

    def start(self) -> "LeaseRenewer":
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            if _renew_with_retry(self.queue, self.handle, self.timeout):
                with self._count_lock:
                    self.renewals += 1

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=2.0)


class _Heartbeat:
    """One renewal thread per supervisor (not per task): every
    ``interval`` seconds it renews the lease of every in-flight task the
    supervisor owns. With the adaptive scheduler several tasks ride
    between claim and ack at once — a thread per task would mean a
    thread churn per task at pipeline depth, for no benefit."""

    def __init__(self, supervisor: "LifecycleSupervisor", interval: float):
        self.supervisor = supervisor
        self.interval = max(0.05, float(interval))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="lease-heartbeat",
        )

    def start(self) -> "_Heartbeat":
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                for lc in inflight():
                    if lc.supervisor is not self.supervisor or lc.done:
                        continue
                    # retried in place with backoff: a transient renew
                    # error must not forfeit the whole heartbeat tick,
                    # and nothing here may kill the only renewal thread
                    _renew_with_retry(self.supervisor.queue, lc.handle)
            except Exception:
                # belt-and-braces: an error OUTSIDE the per-lease retry
                # (registry iteration, exotic queue state) would
                # otherwise end this daemon thread silently, losing all
                # lease renewal for the rest of the run
                telemetry.inc("lifecycle/renew_errors")

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=2.0)


# ---------------------------------------------------------------------------
# in-flight registry (module-level: process_stream consults it on failure)
# ---------------------------------------------------------------------------
_INFLIGHT_LOCK = threading.Lock()
_INFLIGHT: Dict[int, "TaskLifecycle"] = {}


def inflight() -> List["TaskLifecycle"]:
    """Claimed-but-unacked supervised tasks, oldest first. With the
    adaptive scheduler several tasks ride between claim and ack at
    once; on a chain failure every one of them is released."""
    with _INFLIGHT_LOCK:
        return list(_INFLIGHT.values())


def _register(lc: "TaskLifecycle") -> None:
    with _INFLIGHT_LOCK:
        _INFLIGHT[id(lc)] = lc


def _unregister(lc: "TaskLifecycle") -> None:
    with _INFLIGHT_LOCK:
        _INFLIGHT.pop(id(lc), None)


# ---------------------------------------------------------------------------
# per-task lifecycle
# ---------------------------------------------------------------------------
class TaskLifecycle:
    """One claimed task's supervision state, attached to the task dict
    as ``task["lifecycle"]``. Terminal transitions (exactly one per
    claim): :meth:`commit` (ack + ledger marker) or :meth:`release`
    (retry with backoff, dead-letter, or preemption nack)."""

    def __init__(self, supervisor: "LifecycleSupervisor", handle: str,
                 body: str, receives: int):
        self.supervisor = supervisor
        self.queue = supervisor.queue
        self.handle = handle
        self.body = body
        self.receives = receives
        # the trace id minted at queue submission rides the claim: every
        # span/event in this task's lifecycle is stamped with it
        # (telemetry.task_context), so retry hops across workers merge
        # into one timeline (docs/observability.md "Fleet view")
        self.trace_id = self.queue.trace_id(handle)
        self.task: Optional[dict] = None
        self.renewer: Optional[LeaseRenewer] = None
        self.done = False

    def _finish(self) -> None:
        self.done = True
        if self.renewer is not None:
            self.renewer.stop()
        _unregister(self)

    def commit(self, task: Optional[dict] = None) -> None:
        """The commit point, in ack-after-durable-write order: drain the
        task's async writes, mark the ledger (a crash after this line
        redelivers the task once and ledger-skips it), then ack. A crash
        *before* the marker redelivers and recomputes — idempotent
        storage writes make that converge to the same bytes."""
        if self.done:
            return
        from chunkflow_tpu.flow.runtime import drain_pending_writes

        with telemetry.task_context(self.trace_id):
            with telemetry.span("lifecycle/commit"):
                drain_pending_writes(task if task is not None else self.task)
                chaos.chaos_point("lifecycle/pre_ledger")
                if self.supervisor.ledger is not None:
                    self.supervisor.ledger.mark_done(self.body)
                chaos.chaos_point("lifecycle/pre_ack")
                self.queue.delete(self.handle)
            telemetry.inc("tasks/committed")
            telemetry.event(
                "task", "lifecycle/committed", body=self.body,
                receives=self.receives,
            )
        self._finish()

    def _flush_writes(self) -> None:
        """Best-effort drain of the task's pending async writes on a
        failure/preemption path: abandoning in-flight futures would race
        process teardown and swallow their errors. The task is being
        retried or dead-lettered anyway, so drain errors are counted,
        not raised."""
        from chunkflow_tpu.flow.runtime import drain_pending_writes

        try:
            drain_pending_writes(self.task)
        except Exception:
            telemetry.inc("lifecycle/flush_failures")

    def release(self, exc: BaseException) -> str:
        """Failure transition. Returns ``"preempted"`` (nacked, worker
        exiting), ``"retried"`` (backoff via visibility re-claim) or
        ``"dead"`` (moved to the dead-letter store)."""
        if self.done:
            return "done"
        self._finish()
        with telemetry.task_context(self.trace_id), \
                telemetry.span("lifecycle/release"):
            if isinstance(exc, (KeyboardInterrupt, SystemExit,
                                GeneratorExit)):
                # preemption: hand the task back *now* (immediate
                # visibility release), then flush writes before exit
                self.queue.nack(self.handle)
                telemetry.inc("tasks/preempted")
                telemetry.event(
                    "task", "lifecycle/preempted", body=self.body,
                    receives=self.receives,
                )
                self._flush_writes()
                return "preempted"
            self._flush_writes()
            reason = f"{type(exc).__name__}: {exc}"
            kind = classify_error(exc)
            if kind == "permanent" or (
                0 <= self.supervisor.max_retries <= self.receives
            ):
                self.queue.dead_letter(
                    self.handle,
                    reason=f"{reason} (receives={self.receives}, "
                           f"classified {kind})",
                )
                telemetry.inc("tasks/dead_lettered")
                telemetry.event(
                    "task", "lifecycle/dead_letter", body=self.body,
                    receives=self.receives, reason=reason[:200],
                )
                return "dead"
            delay = backoff_delay(
                self.receives, base=self.supervisor.backoff_base,
                cap=self.supervisor.backoff_cap, rng=self.supervisor.rng,
            )
            # backoff rides the visibility clock: re-claim for `delay`
            # seconds, leave unacked — the task reappears by itself, and
            # a worker crash during the backoff window changes nothing
            self.queue.renew(self.handle, delay)
            telemetry.inc("tasks/retried")
            telemetry.event(
                "task_retry", "lifecycle/retry", body=self.body,
                receives=self.receives, backoff_s=round(delay, 3),
                error=reason[:200],
            )
            return "retried"

    def surrender(self) -> str:
        """Innocent-bystander transition: *another* task's failure tore
        down the shared stage chain while this one was in flight. Hand
        the claim back immediately (nack, no backoff) and record no
        failure — the only cost is one receive count on redelivery,
        exactly the semantics an SQS fleet pays when a worker holding a
        batch dies."""
        if self.done:
            return "done"
        self._finish()
        self.queue.nack(self.handle)
        self._flush_writes()
        with telemetry.task_context(self.trace_id):
            telemetry.inc("tasks/surrendered")
            telemetry.event(
                "task", "lifecycle/surrendered", body=self.body,
                receives=self.receives,
            )
        return "surrendered"


# ---------------------------------------------------------------------------
# the supervisor
# ---------------------------------------------------------------------------
class LifecycleSupervisor:
    """Policy + claim loop: wraps a queue's (handle, body) iteration
    into supervised :class:`TaskLifecycle` objects.

    ``max_retries``: failed deliveries allowed before dead-letter
    (a task that fails ``max_retries`` times lands in the dead-letter
    store; negative disables the bound). ``lease_renew``: heartbeat
    interval in seconds (0 disables). ``ledger``: a
    :class:`LedgerBase` for idempotent skip/resume, or None.
    """

    def __init__(self, queue: QueueBase, ledger: Optional[LedgerBase] = None,
                 max_retries: int = 3, lease_renew: float = 0.0,
                 backoff_base: float = 0.5, backoff_cap: float = 60.0,
                 seed: Optional[int] = None):
        self.queue = queue
        self.ledger = ledger
        self.max_retries = int(max_retries)
        self.lease_renew = float(lease_renew)
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self.rng = random.Random(seed)

    def claim(self, handle: str, body: str) -> Optional[TaskLifecycle]:
        """One delivery → a supervised lifecycle, or None when the
        delivery is resolved at claim time (ledger skip, crash-loop
        dead-letter)."""
        with telemetry.task_context(self.queue.trace_id(handle)), \
                telemetry.span("lifecycle/claim"):
            if self.ledger is not None and self.ledger.is_done(body):
                # already committed by a previous attempt/run: ack the
                # duplicate delivery, skip the compute — the idempotent
                # resume path
                self.queue.delete(handle)
                telemetry.inc("ledger/skips")
                telemetry.event("task", "lifecycle/ledger_skip", body=body)
                return None
            receives = self.queue.receive_count(handle) or 1
            # the first delivery is always claimable; past that, a
            # redelivery beyond the retry budget means every prior
            # attempt died without even recording a failure
            if self.max_retries >= 0 and receives > max(self.max_retries, 1):
                # redelivered past the budget with no recorded failure:
                # the worker died mid-compute every time (crash loop)
                reason = (f"receive count {receives} exceeds max retries "
                          f"{self.max_retries} with no recorded failure "
                          "(worker crash loop)")
                self.queue.dead_letter(handle, reason=reason)
                telemetry.inc("tasks/dead_lettered")
                telemetry.event(
                    "task", "lifecycle/dead_letter", body=body,
                    receives=receives, reason=reason,
                )
                return None
            lc = TaskLifecycle(self, handle, body, receives)
            _register(lc)
            telemetry.event(
                "task", "lifecycle/claimed", body=body, receives=receives,
            )
            # the kill-able boundary sits after registration so an
            # injected death here is released (fast retry), not leaked
            # to the visibility timeout
            chaos.chaos_point("lifecycle/claim")
            return lc

    def tasks(self, num: int = -1) -> Iterator[TaskLifecycle]:
        """Claim loop: yields supervised lifecycles, at most ``num``
        (< 0: drain). Installs the SIGTERM preemption handler and runs
        the lease heartbeat (``lease_renew`` > 0) for the loop's
        duration. Every ``CHUNKFLOW_TELEMETRY_SNAPSHOT_EVERY`` claimed
        tasks a telemetry snapshot event is flushed, so a worker killed
        mid-run still leaves a counter record for ``log-summary
        --fleet`` (the end-of-run flush alone would die with it)."""
        restore = install_preemption_handler()
        heartbeat = (
            _Heartbeat(self, self.lease_renew).start()
            if self.lease_renew > 0 else None
        )
        snapshot_every = telemetry.snapshot_interval()
        count = 0
        try:
            for handle, body in self.queue:
                lc = self.claim(handle, body)
                if lc is None:
                    continue
                yield lc
                count += 1
                if snapshot_every and count % snapshot_every == 0:
                    telemetry.flush()
                if 0 <= num <= count:
                    return
        finally:
            if heartbeat is not None:
                heartbeat.stop()
            restore()


# ---------------------------------------------------------------------------
# chain-failure + preemption entry points (flow/runtime.process_stream)
# ---------------------------------------------------------------------------
def tag_culprit(exc: BaseException, owner) -> None:
    """Attach the task (dict) or :class:`TaskLifecycle` whose processing
    raised ``exc``. The stage chain is shared by several in-flight tasks
    (prefetch + pipelining), so when it dies, only the tagged culprit
    should be *charged* with the failure — the bystanders merely
    surrender their claims. First tag wins (the innermost frame knows
    the owner best). Call sites: the runtime operator wrapper, the
    adaptive scheduler's dispatch/finalize, the supervised fetch loop."""
    if getattr(exc, "_chunkflow_culprit", None) is None:
        try:
            exc._chunkflow_culprit = owner
        except Exception:
            pass  # exotic exception type refusing attributes


def _resolve_culprit(exc: BaseException,
                     lcs: List["TaskLifecycle"]) -> Optional["TaskLifecycle"]:
    owner = getattr(exc, "_chunkflow_culprit", None)
    if owner is None:
        return None
    for lc in lcs:
        if lc is owner or (lc.task is not None and lc.task is owner):
            return lc
    if isinstance(owner, dict):
        lc = owner.get("lifecycle")
        if lc in lcs:
            return lc
    return None


def handle_failure(exc: BaseException) -> bool:
    """Resolve every in-flight supervised task after the stage chain
    died with ``exc``: preemption nacks them all (immediate visibility
    release) and the worker exits; a task failure charges the tagged
    culprit (retry with backoff, or dead-letter per policy) while the
    innocent bystanders surrender their claims un-failed, and the
    worker rebuilds its chain. An unattributable failure conservatively
    charges every in-flight task.

    Returns True when the caller should rebuild and continue draining
    the queue; False when the failure is not contained (no supervised
    task in flight, or a preemption/exit) and must re-raise."""
    lcs = inflight()
    if not lcs:
        return False
    preempt = isinstance(exc, (KeyboardInterrupt, SystemExit, GeneratorExit))
    culprit = None if preempt else _resolve_culprit(exc, lcs)
    for lc in lcs:
        try:
            if preempt or culprit is None or lc is culprit:
                lc.release(exc)
            else:
                lc.surrender()
        except Exception as release_exc:
            # a broken queue must not mask the original failure
            print(
                f"lifecycle: releasing task {lc.body!r} failed: "
                f"{release_exc!r}", file=sys.stderr,
            )
    return not preempt


def surrender_task(item) -> None:
    """Hand back the queue claim of a task DROPPED between pipeline
    stages during teardown. The prefetch pump threads (flow/scheduler.py
    ``_pump``, flow/runtime.py ``prefetch_stage``) race chain rebuild:
    after a contained failure resolves the in-flight set, the pump can
    pull — and claim — one more task before it notices the consumer is
    gone, and tasks already buffered in the handoff queue may likewise
    have been claimed after the failure snapshot. Dropping such an item
    on the floor leaks its lease until the visibility timeout (observed:
    a 1800 s claim outliving a cleanly-exited worker, losing the task
    for the run). Surrender is the correct resolution — nack with no
    failure recorded, idempotent for already-resolved lifecycles — and a
    no-op for non-task items (chunks, sentinels, unsupervised tasks).
    Best-effort: teardown must not die on a broken queue."""
    lc = item.get("lifecycle") if isinstance(item, dict) else None
    if lc is not None:
        try:
            lc.surrender()
        except Exception:
            pass


def install_preemption_handler():
    """Route SIGTERM into the supervision path: the handler raises
    ``SystemExit(143)`` in the main thread, the chain unwinds,
    :func:`handle_failure` nacks the in-flight tasks and flushes their
    writes, and the worker exits — the preemptible-VM contract. SIGINT
    already arrives as KeyboardInterrupt and takes the same path.
    Returns a zero-arg restore callable; no-op off the main thread
    (signal handlers only install there)."""
    if threading.current_thread() is not threading.main_thread():
        return lambda: None
    previous = signal.getsignal(signal.SIGTERM)

    def _handler(signum, frame):
        raise SystemExit(143)  # 128 + SIGTERM, the fleet convention

    try:
        signal.signal(signal.SIGTERM, _handler)
    except (ValueError, OSError):  # exotic embedding: no signal support
        return lambda: None

    def restore():
        try:
            signal.signal(signal.SIGTERM, previous)
        except (ValueError, OSError, TypeError):
            pass

    return restore
