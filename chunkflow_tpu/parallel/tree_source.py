"""TreeTaskSource: a SpatialTaskTree feeding the EXISTING queue loop.

The flat queue cannot express "the parent merge must wait for both
children" — but nothing about the supervised worker loop
(``fetch-task-from-queue`` + parallel/lifecycle.py) needs to change to
get there. This source keeps the dependency state on the *submit* side:

* the tree's ready frontier is enqueued as ordinary queue bodies
  (leaves first, then interior nodes as their subtrees complete);
* a node counts as done exactly when its body has a **ledger marker**
  — the same durable commit the worker's ``delete-task-in-queue`` ack
  writes — so children's ledger commits are literally what unlocks the
  parent task;
* :meth:`sync` folds the ledger into the tree, then claims-and-enqueues
  every newly runnable node. Run it in a loop (:meth:`run`) and the
  whole reduce schedules itself through the standard machinery: workers
  just drain the queue, retries/lease expiry/dead-letter/exactly-once
  all come from the lifecycle layer unchanged.

Crash story (docs/fault_tolerance.md "Task graphs"): a killed WORKER is
the queue's problem (visibility timeout -> redelivery -> ledger-skip or
idempotent re-execution). A killed COORDINATOR rebuilds the tree from
the plan, folds the ledger (every committed node goes straight to done)
and re-claims the frontier; re-enqueued duplicates of messages still
sitting in the queue are absorbed by the ledger-skip path. Mid-job
serialize/restore of a live tree (``tree.to_dict``) is also supported —
restored ``working on`` nodes are NOT re-enqueued (their messages are
still in flight).

Ready-set ordering is deterministic: ``next_ready_task`` claims in
pre-order walk order, so leaves go out left-to-right along the split
axes and every interior node strictly after both children.
"""
from __future__ import annotations

import time
from typing import Callable, List, Optional

from chunkflow_tpu.parallel.lifecycle import LedgerBase
from chunkflow_tpu.parallel.task_tree import SpatialTaskTree


class TreeTaskSource:
    """Pump a dependency tree into an ordinary task queue.

    ``body`` maps a node to its queue body / ledger key (default: the
    node's bbox string). One coordinator instance drives one tree; the
    instance itself is single-threaded — cross-process safety comes
    from the queue and ledger underneath, not from locks here.
    """

    def __init__(
        self,
        tree: SpatialTaskTree,
        queue,
        ledger: LedgerBase,
        body: Optional[Callable[[SpatialTaskTree], str]] = None,
    ):
        if ledger is None:
            raise ValueError(
                "TreeTaskSource needs a ledger: children's ledger "
                "commits are what unlock the parent task"
            )
        self.tree = tree
        self.queue = queue
        self.ledger = ledger
        self._body = body or (lambda node: node.bbox.string)
        self.enqueued = 0

    def sync(self) -> int:
        """One scheduling round: fold ledger commits into the tree,
        then enqueue every newly runnable node. Returns how many were
        enqueued."""
        for node in self.tree.walk():
            if not node.is_done and self.ledger.is_done(self._body(node)):
                node.set_state_done()
        bodies: List[str] = []
        while True:
            node = self.tree.next_ready_task()
            if node is None:
                break
            bodies.append(self._body(node))
        if bodies:
            # send OUTSIDE any tree claim: queue sends may block on IO
            self.queue.send_messages(bodies)
            self.enqueued += len(bodies)
        return len(bodies)

    @property
    def all_done(self) -> bool:
        return self.tree.all_done

    def pending(self) -> int:
        return sum(1 for node in self.tree.walk() if not node.is_done)

    def run(
        self,
        poll_interval: float = 0.05,
        timeout: Optional[float] = None,
    ) -> int:
        """Pump until the whole tree is done; returns the total number
        of bodies enqueued by this source. Raises TimeoutError when the
        deadline passes with nodes still outstanding (workers dead or
        never started — the queue keeps the claimed work either way)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            self.sync()
            if self.tree.all_done:
                return self.enqueued
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"task tree incomplete after {timeout}s: "
                    f"{self.pending()} nodes outstanding"
                )
            time.sleep(poll_interval)
