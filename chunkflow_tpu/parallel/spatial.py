"""Spatially-sharded fused inference: the chunk itself lives sharded.

``parallel.distributed`` scales the patch *batch* (chunk replicated on every
chip). This module scales the chunk *extent*: the chunk is sharded along y
over the mesh — the spatial analog of sequence/context parallelism — so a
single task can exceed one chip's HBM. Reference analog: SURVEY §5.7 calls
chunkflow's overlap-blend decomposition "structurally the same trick as
blockwise/ring attention"; here the cross-chip halo exchange that trick
implies is explicit, as two ring hops on ICI:

1. input halos: each chip ``ppermute``s its y-edge strips to the neighbor
   chips so every chip can cut all input patches whose *output* start falls
   in its own slab;
2. local fused blend (gather -> forward -> bump multiply -> scatter-add),
   identical to the single-chip program, over the extended slab;
3. output spill: bump-weighted contributions that extend past the slab's
   right edge ride one more ``ppermute`` hop and are added into the right
   neighbor's left edge (and the weight buffer likewise), after which the
   reciprocal normalization is exact everywhere — the identity oracle holds
   across chip boundaries.

Non-periodic boundaries come for free: ``ppermute`` delivers zeros where no
link exists. All shapes are static; the per-chip patch lists are padded to
a common length with zero-validity entries.
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import numpy as np

Triple = Tuple[int, int, int]


def spatial_geometry(y: int, n_devices: int, pin: Triple, pout: Triple):
    """(slab, halo_left, halo_right, spill, padded_y) for y-sharding.

    Single source of the halo math for both Inferencer(--sharding spatial)
    and spatial_sharded_inference. Arbitrary chunk heights are supported
    (parity: the reference decomposes arbitrary sizes everywhere,
    lib/cartesian_coordinate.py:316-347): the slab is rounded up to both
    an even device split and the halo/spill minimum, and callers zero-pad
    y to ``padded_y = slab * n_devices`` then crop back — padded rows get
    zero blend weight, so normalization is exact on the real extent."""
    margin_y = (pin[1] - pout[1]) // 2
    halo_left = margin_y
    halo_right = pin[1] - margin_y
    spill = pout[1]
    slab = max(-(-y // n_devices), halo_left, halo_right, spill)
    padded_y = slab * n_devices
    return slab, halo_left, halo_right, spill, padded_y


def pad_chunk_y(arr, padded_y: int):
    """Zero-pad [C, Z, y, X] on the right of the y axis to ``padded_y``.

    Works on numpy and jax arrays alike (jax arrays pad on device)."""
    y = arr.shape[-2]
    if y == padded_y:
        return arr
    pad = [(0, 0)] * arr.ndim
    pad[-2] = (0, padded_y - y)
    if isinstance(arr, np.ndarray):
        return np.pad(arr, pad)
    import jax.numpy as jnp

    return jnp.pad(arr, pad)


def partition_patches(
    grid,
    n_devices: int,
    slab: int,
    batch_size: int,
    halo_left: int,
):
    """Bucket the global patch grid by output-start y-slab and localize.

    Returns per-device (in_starts, out_starts, valid) arrays of identical
    shape [n_devices, ceil(max_per_dev/batch)*batch, 3] / [..., ] where y
    coordinates are relative to each device's extended input slab
    (in_starts) or extended output slab (out_starts).
    """
    in_starts = np.asarray(grid.input_starts)
    out_starts = np.asarray(grid.output_starts)

    buckets = np.clip(out_starts[:, 1] // slab, 0, n_devices - 1)
    max_count = max(
        int((buckets == d).sum()) for d in range(n_devices)
    )
    padded = -(-max_count // batch_size) * batch_size

    dev_in = np.zeros((n_devices, padded, 3), dtype=np.int32)
    dev_out = np.zeros((n_devices, padded, 3), dtype=np.int32)
    dev_valid = np.zeros((n_devices, padded), dtype=np.float32)
    for d in range(n_devices):
        idx = np.nonzero(buckets == d)[0]
        k = idx.size
        local_in = in_starts[idx].copy()
        local_out = out_starts[idx].copy()
        # both extended slabs start at global y = d*slab - halo_left
        local_in[:, 1] -= d * slab - halo_left
        local_out[:, 1] -= d * slab - halo_left
        dev_in[d, :k] = local_in
        dev_out[d, :k] = local_out
        dev_valid[d, :k] = 1.0
    return dev_in, dev_out, dev_valid


def build_spatial_program(
    engine_apply,
    num_input_channels: int,
    num_output_channels: int,
    input_patch_size: Triple,
    output_patch_size: Triple,
    batch_size: int,
    mesh,
    bump_array: np.ndarray,
    slab: int,
    halo_left: int,
    halo_right: int,
    spill: int,
    out_dtype="float32",
):
    """jit-compiled y-sharded fused inference over ``mesh`` axis 'data'.

    chunk: [C, Z, n_dev*slab, X] sharded on y. Returns the normalized
    output [Co, Z, n_dev*slab, X], same sharding.
    """
    import jax
    from jax import lax
    from chunkflow_tpu.parallel._shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from chunkflow_tpu.ops.blend import build_local_blend, normalize_blend

    axis = mesh.axis_names[0]
    n_dev = mesh.devices.size
    local_blend = build_local_blend(
        engine_apply,
        num_input_channels,
        num_output_channels,
        input_patch_size,
        output_patch_size,
        batch_size,
        bump_array,
    )
    right = [(i, (i + 1) % n_dev) for i in range(n_dev - 1)]
    left = [(i + 1, i) for i in range(n_dev - 1)]

    def device_fn(chunk_slab, in_starts, out_starts, valid, params):
        # chunk_slab: [C, Z, slab, X]; patch lists carry a leading sharded
        # axis of size 1
        in_starts = in_starts[0]
        out_starts = out_starts[0]
        valid = valid[0]

        # ---- 1. input halo exchange (one ring hop each way) ----
        # my right edge -> right neighbor's left halo
        left_halo = lax.ppermute(
            chunk_slab[:, :, slab - halo_left:slab, :], axis, right
        )
        # my left edge -> left neighbor's right halo
        right_halo = lax.ppermute(
            chunk_slab[:, :, :halo_right, :], axis, left
        )
        extended = lax.concatenate(
            [left_halo, chunk_slab, right_halo], dimension=2
        )

        # ---- 2. local fused blend over the extended slab ----
        # local_blend allocates out/weight buffers of the extended slab
        # shape; patch coords were localized to the extended frame, whose
        # y range is [d*slab - halo_left, (d+1)*slab + halo_right).
        out, weight = local_blend(
            extended, in_starts, out_starts, valid, params
        )

        # ---- 3. output spill exchange: bump contributions past my right
        # slab edge are added into the right neighbor's left slab edge ----
        lo = halo_left + slab
        spill_out = lax.ppermute(out[:, :, lo:lo + spill, :], axis, right)
        spill_w = lax.ppermute(weight[:, lo:lo + spill, :], axis, right)
        out = out[:, :, halo_left:lo, :].at[:, :, :spill, :].add(spill_out)
        weight = weight[:, halo_left:lo, :].at[:, :spill, :].add(spill_w)

        return out, weight

    sharded = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(
            P(None, None, axis, None),
            P(axis),
            P(axis),
            P(axis),
            P(),
        ),
        out_specs=(P(None, None, axis, None), P(None, axis, None)),
        check_rep=False,
    )

    # chunk is donated (GL005): dead after the call, may be aliased
    # into the output slab buffers — callers hand over a buffer they own
    @partial(jax.jit, donate_argnums=(0,))
    def program(chunk, dev_in, dev_out, dev_valid, params):
        out, weight = sharded(chunk, dev_in, dev_out, dev_valid, params)
        return normalize_blend(out, weight, out_dtype)

    return program


def spatial_sharded_inference(
    chunk_array: np.ndarray,
    engine,
    input_patch_size: Triple,
    output_patch_size: Triple,
    output_patch_overlap: Triple,
    batch_size: int = 1,
    mesh=None,
):
    """Run fused inference with the chunk sharded along y over the mesh."""
    import jax.numpy as jnp

    from chunkflow_tpu.inference.bump import bump_map
    from chunkflow_tpu.inference.patching import enumerate_patches
    from chunkflow_tpu.parallel.distributed import make_mesh

    if mesh is None:
        mesh = make_mesh()
    n_dev = mesh.devices.size

    arr = np.asarray(chunk_array, dtype=np.float32)
    if arr.ndim == 3:
        arr = arr[None]
    c, z, y, x = arr.shape
    pin = tuple(input_patch_size)
    pout = tuple(output_patch_size)
    slab, halo_left, halo_right, spill, padded_y = spatial_geometry(
        y, n_dev, pin, pout
    )

    # patch grid covers the REAL extent; padded rows stay weight-zero
    grid = enumerate_patches(
        arr.shape, input_patch_size, output_patch_size, output_patch_overlap
    )
    arr = pad_chunk_y(arr, padded_y)
    dev_in, dev_out, dev_valid = partition_patches(
        grid, n_dev, slab, batch_size, halo_left
    )

    program = build_spatial_program(
        engine.apply,
        engine.num_input_channels,
        engine.num_output_channels,
        input_patch_size,
        grid.output_patch_size,
        batch_size,
        mesh,
        bump_map(tuple(grid.output_patch_size)),
        slab,
        halo_left,
        halo_right,
        spill,
    )
    result = program(
        jnp.asarray(arr),
        jnp.asarray(dev_in),
        jnp.asarray(dev_out),
        jnp.asarray(dev_valid),
        engine.params,
    )
    return result[:, :, :y, :]
