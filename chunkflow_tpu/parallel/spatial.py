"""Legacy 1D y-slab sharding — now a shim over the unified engine.

The ring halo/spill program that lived here was subsumed by
:mod:`chunkflow_tpu.parallel.engine` (mesh spec ``y=N``): the chunk still
lives sharded in y slabs with ``ppermute`` halo exchange, but the blend
accumulation is replayed in reference order instead of spill-merged, so
the output is **bitwise identical** to the single-device program rather
than ulp-close (see the engine docstring for the argument). The geometry
helpers remain here for callers that sized slabs with them.
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

Triple = Tuple[int, int, int]


def spatial_geometry(y: int, n_devices: int, pin: Triple, pout: Triple):
    """(slab, halo_left, halo_right, spill, padded_y) for y-sharding.

    The slab is rounded up to an even device split and the halo/spill
    minimum; callers zero-pad y to ``padded_y = slab * n_devices`` and
    crop back (padded rows carry zero blend weight, so normalization is
    exact on the real extent). The unified engine derives the same
    numbers through :func:`chunkflow_tpu.parallel.engine.axis_geometry`.
    """
    margin_y = (pin[1] - pout[1]) // 2
    halo_left = margin_y
    halo_right = pin[1] - margin_y
    spill = pout[1]
    slab = max(-(-y // n_devices), halo_left, halo_right, spill)
    padded_y = slab * n_devices
    return slab, halo_left, halo_right, spill, padded_y


def pad_chunk_y(arr, padded_y: int):
    """Zero-pad [C, Z, y, X] on the right of the y axis to ``padded_y``.

    Works on numpy and jax arrays alike (jax arrays pad on device)."""
    y = arr.shape[-2]
    if y == padded_y:
        return arr
    pad = [(0, 0)] * arr.ndim
    pad[-2] = (0, padded_y - y)
    if isinstance(arr, np.ndarray):
        return np.pad(arr, pad)
    import jax.numpy as jnp

    return jnp.pad(arr, pad)


def spatial_sharded_inference(
    chunk_array: np.ndarray,
    engine,
    input_patch_size: Triple,
    output_patch_size: Triple,
    output_patch_overlap: Triple,
    batch_size: int = 1,
    mesh=None,
):
    """Run fused inference with the chunk sharded along y over the local
    devices — delegates to the unified engine (``y=N`` spec)."""
    import jax

    from chunkflow_tpu.parallel.engine import MeshSpec, sharded_inference

    n_dev = (mesh.devices.size if mesh is not None
             else len(jax.local_devices()))
    # one device degenerates to the trivial 'data' mesh (the engine's
    # program family is identical; a 1-slab spatial mesh is pointless)
    spec = (MeshSpec("spatial", (n_dev, 1)) if n_dev > 1
            else MeshSpec("data", (1,)))
    return sharded_inference(
        chunk_array, engine, input_patch_size, output_patch_size,
        output_patch_overlap, batch_size=batch_size, spec=spec,
    )
