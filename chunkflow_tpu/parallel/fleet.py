"""Elastic, preemption-native fleet supervisor.

The paper's headline capability — 18 PB produced on 3600 cloud nodes in
three regions — is an *elasticity* story: workers are cheap, preemptible
and constantly dying, and the system converges because something keeps
replacing them and the queue protocol keeps their work safe. PRs 3–6
built every input (per-phase stall shares, queue depth / receive
counts, lease state, ledger resume, per-worker ``/healthz`` +
``/metrics``); this module is the component that finally *acts* on
those signals:

* **Spawn + monitor**: each worker is a real subprocess running the
  supervised ``fetch-task-from-queue`` loop (parallel/lifecycle.py)
  with its own ``--metrics-port`` exporter; the supervisor probes
  ``/healthz`` every decision tick and scrapes ``/metrics`` for the
  dominant-stall phase and memory gauges (``restapi.scrape_worker``).
* **Scale from telemetry**: queue ``stats()`` (pending/inflight/dead),
  the fleet's dominant stall phase, and the dead-letter rate drive the
  controller — a deep, compute-bound queue adds a worker per tick up to
  ``max_workers``; a storage-bound fleet holds (more workers would just
  thrash the volume store); a sustained-idle queue drains back to
  ``min_workers``; every scale-up is gated by a host-memory watermark.
* **Preemptible by default**: a worker that misses ``probe_misses``
  consecutive health probes is quarantined — SIGKILLed, and the lease
  handles it last reported over ``/healthz`` are force-nacked
  (``QueueBase.force_release``) so other workers pick up its tasks
  *now* instead of after the visibility timeout. Scale-down is a
  graceful drain: SIGTERM → the worker's preemption handler nacks its
  in-flight task and flushes writes (``install_preemption_handler``) →
  exit 143; a drain that overstays ``term_grace`` is hard-killed. A
  seeded **spot-drill** mode (``drill_rate``) randomly reclaims live
  workers through the same SIGTERM path to prove preemption-recovery
  continuously, the way the paper's fleet lives it.
* **Crash-shaped chaos**: unexpected deaths (SIGKILL, OOM,
  ``testing/chaos.py action=kill``) are detected by reaping, their
  leases force-nacked, and replacements spawned; a crash *loop*
  (``crash_limit`` deaths inside ``crash_window``) backs respawning off
  instead of burning the host.
* **Drain-session workers**: the scheduler pipeline flushes its
  buffered tail when the fetch generator finishes, so a worker that
  long-polls an empty queue would hold its last ``async-depth`` tasks
  claimed-but-unacked (leases dutifully renewed!) for the whole poll
  budget — the fleet would look busy forever. Fleet workers therefore
  run bounded sessions: a moderate ``--retry-times`` (× a small
  ``--poll-interval``) makes an idle worker flush, ack and exit 0, and
  the supervisor — which treats exit 0 as a completion, not a death —
  respawns a fresh session while it still owes the target size. During
  an active volume the queue is rarely empty, so sessions are long; the
  churn only appears at the idle tail, where the idle-drain policy is
  about to shrink the fleet anyway.
* **Operable**: ``chunkflow fleet-run`` drives it from the CLI,
  ``fleet/*`` counters/gauges/events flow into log-summary, Prometheus
  and CloudWatch like every other subsystem, a JSON state file feeds
  ``fleet-status`` (last-seen times and exit codes for dead workers),
  and ``CHUNKFLOW_FLEET=0`` is the kill switch: a static-size fleet
  that bypasses the controller entirely while keeping
  replace-the-dead liveness.

See docs/fault_tolerance.md "Running a fleet" for the runbook.
"""
from __future__ import annotations

import json
import os
import random
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from chunkflow_tpu.core import telemetry
from chunkflow_tpu.parallel.queues import QueueBase, open_queue
from chunkflow_tpu.parallel.restapi import scrape_worker

__all__ = [
    "WorkerHandle", "FleetSupervisor", "fleet_disabled",
    "host_available_gb", "COMPUTE_BOUND_PHASES", "STORAGE_BOUND_PHASES",
]

_OFF_VALUES = ("0", "off", "false", "no")

#: dominant-stall phases that mean "the fleet is limited by per-worker
#: compute/device throughput" — more workers genuinely add throughput
COMPUTE_BOUND_PHASES = (
    "pipeline/stage", "pipeline/dispatch", "pipeline/compute",
    "pipeline/drain", "scheduler/post",
)
#: phases that mean "the fleet is limited by shared storage" — adding
#: workers multiplies pressure on the same volume store for no gain
STORAGE_BOUND_PHASES = ("scheduler/load", "scheduler/write")


def fleet_disabled() -> bool:
    """``CHUNKFLOW_FLEET=0`` (or off/false/no): the kill switch. The
    supervisor still spawns and replaces workers — liveness is not
    optional — but holds a static size and never consults telemetry."""
    return os.environ.get(
        "CHUNKFLOW_FLEET", "1").strip().lower() in _OFF_VALUES


def host_available_gb() -> Optional[float]:
    """``MemAvailable`` from /proc/meminfo in GiB (None where the
    procfs field is missing — macOS, exotic containers — in which case
    the memory watermark simply does not gate)."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) / (1 << 20)
    except (OSError, ValueError, IndexError):
        pass
    return None


def _proc_rss_gb(pid: int) -> Optional[float]:
    """Resident set of one worker process in GiB (procfs; None off
    Linux). Used to estimate what one more worker would cost the
    host before the watermark check."""
    try:
        with open(f"/proc/{pid}/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE") / (1 << 30)
    except (OSError, ValueError, IndexError):
        return None


def _free_port(host: str) -> int:
    """An ephemeral port for a worker's metrics exporter — the FALLBACK
    for fleets running without a --metrics-dir. Bind-and-release is racy
    in principle; real spawns with a metrics dir instead pass
    ``--metrics-port 0`` and discover the actually-bound port from the
    worker's endpoint file (``restapi.write_endpoint_file``), which
    cannot race. A worker that loses the fallback race fails to bind,
    dies, and is replaced — the same recovery path as any other worker
    death."""
    with socket.socket() as s:
        s.bind((host if host != "0.0.0.0" else "", 0))
        return s.getsockname()[1]


class WorkerHandle:
    """One supervised worker process and everything the supervisor
    knows about it. ``state`` transitions::

        starting --first /healthz--> live
        live --SIGTERM (scale-down / spot drill)--> draining --> exited
        live/starting --probe misses--> quarantined (SIGKILL) --> exited
        any --process died--> exited
    """

    def __init__(self, ident: str, port: Optional[int], proc,
                 cmd: List[str]):
        self.ident = ident
        # None until discovered from the worker's endpoint file (the
        # --metrics-port 0 spawn path); probing waits for it
        self.port = port
        self.proc = proc
        self.cmd = cmd
        self.state = "starting"
        self.started = time.time()
        self.last_seen: Optional[float] = None
        self.misses = 0
        self.exit_code: Optional[int] = None
        self.exited_at: Optional[float] = None
        self.handles: List[str] = []
        self.handles_truncated = False
        self.inflight_leases = 0
        self.dominant_stall: Optional[dict] = None
        # last-scraped storage block-cache counters ({"hits", "misses"},
        # None until the worker reports any) — lets a storage-bound
        # hold tell cache-cold from genuinely load-bound
        self.storage_cache: Optional[dict] = None
        # last-scraped firing SLO objectives (restapi.firing_alerts);
        # the supervisor annotates its scale/hold events with these so
        # the ops timeline shows WHAT was out of spec when it decided
        self.slo_firing: List[str] = []
        self.drill = False
        self.drain_deadline: Optional[float] = None

    @property
    def running(self) -> bool:
        return self.exit_code is None and self.proc.poll() is None

    @property
    def active(self) -> bool:
        """Counts toward fleet capacity: running and not on its way
        out (a draining/quarantined worker's slot is already free for
        a replacement)."""
        return self.running and self.state in ("starting", "live")

    def to_record(self) -> dict:
        """The fleet-state JSON record ``fleet-status`` renders: a dead
        worker keeps its last-seen time and exit code — "unreachable"
        alone is useless at 3 a.m."""
        return {
            "worker": self.ident,
            "pid": getattr(self.proc, "pid", None),
            "port": self.port,
            "endpoint": (f"127.0.0.1:{self.port}"
                         if self.port is not None else None),
            "state": self.state,
            "started": self.started,
            "last_seen": self.last_seen,
            "exit_code": self.exit_code,
            "inflight_leases": self.inflight_leases,
        }


class FleetSupervisor:
    """Spawn, monitor, scale and evict a fleet of queue-fed workers.

    ``worker_args`` is the full chunkflow CLI argv of one worker
    *after* the group options — typically ``["fetch-task-from-queue",
    "-q", <queue>, ..., <pipeline stages>..., "delete-task-in-queue"]``
    — the supervisor prepends the interpreter and the per-worker
    ``--metrics-dir``/``--metrics-port`` group options itself.

    Injection points for tests: ``launcher(cmd, env) -> Popen-like``
    (spawn), ``scraper(endpoint, timeout) -> dict``
    (``restapi.scrape_worker``), ``mem_probe() -> GiB|None``
    (:func:`host_available_gb`).
    """

    def __init__(
        self,
        queue_spec: str,
        worker_args: List[str],
        *,
        min_workers: int = 1,
        max_workers: int = 4,
        interval: float = 2.0,
        scale_up_backlog: float = 4.0,
        idle_ticks: int = 2,
        probe_misses: int = 3,
        probe_timeout: float = 1.0,
        startup_grace: float = 30.0,
        term_grace: float = 10.0,
        mem_watermark_gb: float = 2.0,
        worker_mem_est_gb: float = 0.5,
        storage_hold_share: float = 0.5,
        cache_warm_share: float = 0.5,
        dead_letter_surge: int = 3,
        crash_limit: int = 3,
        crash_window: float = 60.0,
        crash_backoff: float = 10.0,
        drill_rate: float = 0.0,
        seed: Optional[int] = None,
        metrics_dir: Optional[str] = None,
        state_path: Optional[str] = None,
        host: str = "127.0.0.1",
        python: Optional[str] = None,
        worker_env: Optional[Dict[str, str]] = None,
        static: Optional[bool] = None,
        launcher: Optional[Callable] = None,
        scraper: Optional[Callable] = None,
        mem_probe: Optional[Callable] = None,
        visibility_timeout: float = 1800.0,
    ):
        if min_workers < 1 or max_workers < min_workers:
            raise ValueError(
                f"need 1 <= min_workers <= max_workers, got "
                f"{min_workers}..{max_workers}"
            )
        self.queue_spec = queue_spec
        self.queue: QueueBase = open_queue(
            queue_spec, visibility_timeout=visibility_timeout)
        self.worker_args = list(worker_args)
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.interval = max(0.05, float(interval))
        self.scale_up_backlog = float(scale_up_backlog)
        self.idle_ticks = int(idle_ticks)
        self.probe_misses = int(probe_misses)
        self.probe_timeout = float(probe_timeout)
        self.startup_grace = float(startup_grace)
        self.term_grace = float(term_grace)
        self.mem_watermark_gb = float(mem_watermark_gb)
        self.worker_mem_est_gb = float(worker_mem_est_gb)
        self.storage_hold_share = float(storage_hold_share)
        self.cache_warm_share = float(cache_warm_share)
        self.dead_letter_surge = int(dead_letter_surge)
        self.crash_limit = int(crash_limit)
        self.crash_window = float(crash_window)
        self.crash_backoff = float(crash_backoff)
        self.drill_rate = float(drill_rate)
        self.rng = random.Random(seed)
        self.metrics_dir = metrics_dir
        self.state_path = state_path or (
            os.path.join(metrics_dir, "fleet-state.json")
            if metrics_dir else None
        )
        self.host = host
        self.python = python or sys.executable
        self.worker_env = dict(worker_env or {})
        self.static = fleet_disabled() if static is None else bool(static)
        self.launcher = launcher or self._spawn_process
        self.scraper = scraper or scrape_worker
        self.mem_probe = mem_probe or host_available_gb
        # probing needs the workers' /metrics listeners, which the
        # telemetry kill switch suppresses (workers inherit our env):
        # with telemetry off, supervision degrades to process liveness
        self.probing = telemetry.enabled()

        self.workers: List[WorkerHandle] = []
        self.target = min_workers
        self._seq = 0
        self._idle_count = 0
        self._last_dead: Optional[int] = None
        self._recent_dead: List[tuple] = []  # (t, delta) dead-letter surges
        self._deaths: List[float] = []       # unexpected-death timestamps
        self._backoff_until = 0.0
        self._drill_requested = 0
        self._stop = threading.Event()
        if "delete-task-in-queue" not in self.worker_args:
            print(
                "fleet: worker_args has no delete-task-in-queue stage — "
                "workers will never ack, the queue will never drain",
                file=sys.stderr,
            )

    # -- spawning -------------------------------------------------------
    def _spawn_process(self, cmd: List[str], env: Dict[str, str]):
        log = subprocess.DEVNULL
        if self.metrics_dir:
            os.makedirs(self.metrics_dir, exist_ok=True)
            log = open(
                os.path.join(
                    self.metrics_dir,
                    f"worker-{env['CHUNKFLOW_WORKER_ID']}.log"),
                "ab",
            )
        try:
            return subprocess.Popen(
                cmd, env=env, stdout=log, stderr=subprocess.STDOUT,
                start_new_session=True,  # our SIGINT must not strafe them
            )
        finally:
            if log is not subprocess.DEVNULL:
                log.close()  # the child holds its own descriptor

    def spawn_worker(self) -> WorkerHandle:
        self._seq += 1
        ident = f"fleet-w{self._seq:03d}"
        # real spawns with a metrics dir bind ephemeral (--metrics-port
        # 0) and publish the bound port in their endpoint file — no
        # pre-pick race, no collisions between workers on one host.
        # Injected launchers (tests) and dir-less fleets keep the
        # legacy pre-picked port, which is the only address the
        # supervisor could know for them.
        discover = (self.metrics_dir is not None
                    and self.launcher == self._spawn_process)
        port = None if discover else _free_port(self.host)
        cmd = [self.python, "-m", "chunkflow_tpu.flow.cli"]
        if self.metrics_dir:
            cmd += ["--metrics-dir", self.metrics_dir]
        cmd += ["--metrics-port", "0" if discover else str(port)]
        cmd += self.worker_args
        env = dict(os.environ)
        env.update(self.worker_env)
        env["CHUNKFLOW_WORKER_ID"] = ident
        env.pop("CHUNKFLOW_METRICS_PORT", None)  # --metrics-port wins
        # the worker must import chunkflow_tpu from wherever WE did
        # (editable checkouts, test trees) regardless of its cwd
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        worker = WorkerHandle(ident, port, self.launcher(cmd, env), cmd)
        self.workers.append(worker)
        telemetry.inc("fleet/spawns")
        telemetry.event(
            "fleet", "fleet/spawn", fleet_worker=ident,
            worker_pid=getattr(worker.proc, "pid", None), port=port,
        )
        return worker

    # -- probing + eviction ---------------------------------------------
    def _discover_port(self, worker: WorkerHandle) -> Optional[int]:
        """Resolve an ephemeral-spawned worker's bound metrics port from
        the endpoint file it publishes once its exporter is up."""
        if worker.port is not None:
            return worker.port
        if not self.metrics_dir:
            return None
        from chunkflow_tpu.parallel.restapi import read_endpoint_file

        record = read_endpoint_file(self.metrics_dir, worker.ident)
        if record and record.get("metrics_port"):
            worker.port = int(record["metrics_port"])
        return worker.port

    def _probe(self, worker: WorkerHandle, now: float) -> None:
        if not worker.running or worker.state not in ("starting", "live"):
            return
        if not self.probing:
            worker.state = "live"  # liveness only: running == healthy
            worker.last_seen = now
            return
        if self._discover_port(worker) is None:
            # no bound port published yet: indistinguishable from "the
            # exporter is not up yet" — same startup grace, then the
            # same probation as a worker that never answers
            if now - worker.started < self.startup_grace:
                return
            worker.misses += 1
            telemetry.inc("fleet/probe_failures")
            if worker.misses >= self.probe_misses:
                self._evict(
                    worker, f"no endpoint published after "
                            f"{now - worker.started:.0f}s")
            return
        sample = self.scraper(
            f"{self.host}:{worker.port}", timeout=self.probe_timeout)
        if sample.get("error") is None:
            health = sample.get("healthz") or {}
            worker.state = "live"
            worker.last_seen = now
            worker.misses = 0
            worker.inflight_leases = int(health.get("inflight_leases", 0))
            worker.handles = list(health.get("inflight_handles") or [])
            worker.handles_truncated = bool(
                health.get("inflight_handles_truncated"))
            worker.dominant_stall = sample.get("dominant_stall")
            metrics = sample.get("metrics") or {}
            hits = metrics.get("chunkflow_storage_hits_total")
            misses = metrics.get("chunkflow_storage_misses_total")
            worker.storage_cache = (
                {"hits": float(hits or 0), "misses": float(misses or 0)}
                if (hits is not None or misses is not None) else None
            )
            worker.slo_firing = list(sample.get("slo_firing") or [])
            return
        if worker.state == "starting" and \
                now - worker.started < self.startup_grace:
            return  # the exporter may simply not be up yet
        worker.misses += 1
        telemetry.inc("fleet/probe_failures")
        if worker.misses >= self.probe_misses:
            self._evict(worker, f"missed {worker.misses} health probes")

    def _evict(self, worker: WorkerHandle, reason: str) -> None:
        """Health probation expired: the worker is sick (wedged runtime,
        dead exporter, livelock) — quarantine it. SIGKILL, because a
        process that stopped answering /healthz cannot be trusted to
        honor SIGTERM either; its last-reported leases are force-nacked
        at reap so the fleet picks the work up immediately."""
        worker.state = "quarantined"
        telemetry.inc("fleet/evictions")
        telemetry.event(
            "fleet", "fleet/evict", fleet_worker=worker.ident,
            reason=reason, leases=len(worker.handles),
        )
        try:
            worker.proc.kill()
        except OSError:
            pass

    # -- graceful drain + spot drill ------------------------------------
    def _drain(self, worker: WorkerHandle, now: float,
               drill: bool = False) -> None:
        worker.state = "draining"
        worker.drill = drill
        worker.drain_deadline = now + self.term_grace
        try:
            worker.proc.send_signal(signal.SIGTERM)
        except OSError:
            pass  # already gone; reap will notice

    def request_drill(self) -> None:
        """Force one spot-drill preemption on the next tick (tests,
        `fleet-run --drill-now`) regardless of ``drill_rate``."""
        self._drill_requested += 1

    def _maybe_drill(self, now: float) -> None:
        due = self._drill_requested > 0 or (
            self.drill_rate > 0 and self.rng.random() < self.drill_rate
        )
        if not due:
            return
        victims = [w for w in self.workers if w.running and w.state == "live"]
        if not victims:
            return
        if self._drill_requested:
            self._drill_requested -= 1
        victim = self.rng.choice(victims)
        telemetry.inc("fleet/drill_preemptions")
        telemetry.event(
            "fleet", "fleet/drill", fleet_worker=victim.ident,
        )
        # the spot contract: a termination notice (SIGTERM), a short
        # deadline, then the hypervisor yanks the plug (reap + SIGKILL
        # via the drain deadline)
        self._drain(victim, now, drill=True)

    def _enforce_drain_deadlines(self, now: float) -> None:
        for worker in self.workers:
            if (worker.state == "draining" and worker.running
                    and worker.drain_deadline is not None
                    and now > worker.drain_deadline):
                try:
                    worker.proc.kill()
                except OSError:
                    pass

    # -- reaping --------------------------------------------------------
    def _reap(self, now: float) -> None:
        for worker in self.workers:
            if worker.exit_code is not None:
                continue
            code = worker.proc.poll()
            if code is None:
                continue
            worker.exit_code = code
            worker.exited_at = now
            # exit 0 is a worker that drained the queue and finished on
            # its own — a completion, not a death
            expected = code == 0 or worker.state in (
                "draining", "quarantined")
            worker.state = "exited"
            # whatever it still held goes back NOW — for an evicted or
            # crashed worker this is the difference between immediate
            # pickup and waiting out the visibility timeout; for a clean
            # drain the worker nacked (with refund) on SIGTERM itself,
            # so these releases are no-ops and count zero. The receive
            # count is NOT refunded here (force_release refund=False):
            # a crash/quarantine delivery must keep counting, or the
            # lifecycle crash-loop bound could never dead-letter a
            # poison task that kills every worker it lands on.
            released = self.queue.force_release(worker.handles)
            if released:
                telemetry.inc("fleet/leases_nacked", released)
            if worker.handles_truncated:
                # /healthz capped the handle list: the leases past the
                # cap were NOT force-nacked and will ride out the full
                # visibility timeout — surface it instead of silently
                # breaking the immediate-pickup guarantee
                telemetry.inc("fleet/handles_truncated")
                telemetry.event(
                    "fleet", "fleet/handles_truncated",
                    fleet_worker=worker.ident, released=released,
                    inflight_leases=worker.inflight_leases,
                )
            worker.handles = []
            worker.handles_truncated = False
            worker.inflight_leases = 0
            telemetry.event(
                "fleet", "fleet/exit", fleet_worker=worker.ident,
                exit_code=code, uptime_s=round(now - worker.started, 3),
                expected=expected,
            )
            if not expected:
                telemetry.inc("fleet/worker_deaths")
                self._deaths.append(now)
        # crash-loop probation: unexpected deaths arriving faster than
        # crash_limit per crash_window back respawning off — a poisoned
        # image or broken volume mount must not spin the host
        self._deaths = [t for t in self._deaths
                        if now - t <= self.crash_window]
        if len(self._deaths) >= self.crash_limit \
                and now >= self._backoff_until:
            self._backoff_until = now + self.crash_backoff
            telemetry.inc("fleet/crash_backoffs")
            telemetry.event(
                "fleet", "fleet/crash_backoff",
                deaths=len(self._deaths), backoff_s=self.crash_backoff,
            )

    # -- the controller -------------------------------------------------
    def _fleet_dominant(self) -> Optional[dict]:
        """Share-weighted dominant stall phase across the last probes
        (None until any worker reports one)."""
        totals: Dict[str, float] = {}
        for worker in self.workers:
            if worker.active and worker.dominant_stall:
                phase = worker.dominant_stall.get("phase")
                share = float(worker.dominant_stall.get("share", 0.0))
                if phase:
                    totals[phase] = totals.get(phase, 0.0) + share
        if not totals:
            return None
        phase = max(totals, key=totals.get)
        n = sum(1 for w in self.workers
                if w.active and w.dominant_stall)
        return {"phase": phase, "share": totals[phase] / n}

    def _storage_hit_rate(self) -> Optional[float]:
        """Fleet-wide storage block-cache hit rate from the last worker
        scrapes; None when no active worker reports storage counters
        (pre-storage-plane workers, telemetry off)."""
        hits = misses = 0.0
        seen = False
        for worker in self.workers:
            if worker.active and worker.storage_cache is not None:
                seen = True
                hits += worker.storage_cache.get("hits", 0.0)
                misses += worker.storage_cache.get("misses", 0.0)
        if not seen or hits + misses <= 0:
            return None
        return hits / (hits + misses)

    def _mem_ok(self) -> bool:
        available = self.mem_probe()
        if available is None:
            return True  # no procfs: the watermark cannot gate
        telemetry.gauge("fleet/host_available_gb", round(available, 3))
        est = self.worker_mem_est_gb
        rss = [r for r in (_proc_rss_gb(getattr(w.proc, "pid", -1))
                           for w in self.workers if w.active)
               if r is not None]
        if rss:
            est = max(est, sum(rss) / len(rss))
        return available - est >= self.mem_watermark_gb

    def _dead_letter_surging(self, stats: dict, now: float) -> bool:
        dead = stats.get("dead")
        if dead is None:
            return False
        if self._last_dead is not None and dead > self._last_dead:
            self._recent_dead.append((now, dead - self._last_dead))
        self._last_dead = dead
        window = self.interval * 5
        self._recent_dead = [(t, d) for t, d in self._recent_dead
                             if now - t <= window]
        return sum(d for _, d in self._recent_dead) >= self.dead_letter_surge

    def _fleet_slo_firing(self) -> List[str]:
        """Union of the firing SLO objectives across the last active
        worker scrapes (restapi.firing_alerts) — the annotation every
        scale/hold decision carries. Annotation ONLY in this PR: the
        controller does not yet act on it (the policy half of the SLO
        closed loop is a later PR), but the ops timeline already shows
        what was out of spec at each decision."""
        firing: set = set()
        for worker in self.workers:
            if worker.active:
                firing.update(worker.slo_firing)
        return sorted(firing)

    def _slo_attrs(self) -> dict:
        firing = self._fleet_slo_firing()
        return {"slo_firing": firing} if firing else {}

    def _hold(self, reason: str) -> None:
        telemetry.inc("fleet/holds")
        telemetry.event("fleet", "fleet/hold", reason=reason,
                        **self._slo_attrs())

    def _decide(self, stats: dict, now: float) -> None:
        """One controller tick: move ``self.target`` by at most one,
        from live signals. Static mode bypasses all of it."""
        if self.static:
            self.target = self.min_workers
            return
        active = sum(1 for w in self.workers if w.active)
        pending = stats.get("pending")
        inflight = stats.get("inflight")
        dead_surge = self._dead_letter_surging(stats, now)

        # scale DOWN: a queue idle for idle_ticks straight means the
        # volume is drained (or starved upstream) — fall back to min
        if pending == 0 and inflight == 0:
            self._idle_count += 1
        else:
            self._idle_count = 0
        if self._idle_count >= self.idle_ticks \
                and self.target > self.min_workers:
            telemetry.inc("fleet/scale_down")
            telemetry.event(
                "fleet", "fleet/scale", direction="down",
                target=self.min_workers, reason="idle-queue",
                **self._slo_attrs(),
            )
            self.target = self.min_workers
            return

        # scale UP: deep queue, one worker per tick, gated on
        # compute-boundness, memory headroom and dead-letter sanity
        if pending is None or self.target >= self.max_workers:
            return
        if pending <= self.scale_up_backlog * max(1, active):
            return
        if dead_surge:
            self._hold("dead-letter-surge")
            return
        dominant = self._fleet_dominant()
        if dominant and dominant["phase"] in STORAGE_BOUND_PHASES \
                and dominant["share"] >= self.storage_hold_share:
            # qualify the hold with the block-cache hit rate when the
            # workers report one (volume/storage.py): a cold cache means
            # the stall is transient re-fetch traffic the warming LRU
            # will absorb; a warm cache still storage-bound means the
            # shared store genuinely is the limit — different 3 a.m.
            # responses (wait vs. shard the volume / add bandwidth)
            reason = f"storage-bound:{dominant['phase']}"
            hit_rate = self._storage_hit_rate()
            if hit_rate is not None:
                reason += (":cold-cache"
                           if hit_rate < self.cache_warm_share
                           else ":load-bound")
            self._hold(reason)
            return
        if not self._mem_ok():
            self._hold("memory-watermark")
            return
        if now < self._backoff_until:
            self._hold("crash-backoff")
            return
        self.target += 1
        telemetry.inc("fleet/scale_up")
        telemetry.event(
            "fleet", "fleet/scale", direction="up", target=self.target,
            reason="deep-queue", pending=pending,
            dominant=(dominant or {}).get("phase"),
            **self._slo_attrs(),
        )

    def _enact(self, now: float) -> None:
        active = [w for w in self.workers if w.active]
        if len(active) > self.target:
            # drain newest-first: the eldest workers have warm compile
            # caches and deserve to keep them
            for worker in sorted(active, key=lambda w: w.started,
                                 reverse=True)[: len(active) - self.target]:
                telemetry.inc("fleet/scale_down_drains")
                self._drain(worker, now)
        elif len(active) < self.target and now >= self._backoff_until:
            for _ in range(self.target - len(active)):
                self.spawn_worker()

    # -- state + the loop -----------------------------------------------
    def write_state(self) -> Optional[str]:
        """Atomic fleet-state JSON for ``fleet-status``: every worker
        this supervisor ever owned, with last-seen and exit codes."""
        if self.state_path is None:
            return None
        payload = {
            "t": time.time(),
            "queue": self.queue_spec,
            "static": self.static,
            "target": self.target,
            "min_workers": self.min_workers,
            "max_workers": self.max_workers,
            "supervisor_pid": os.getpid(),
            "workers": [w.to_record() for w in self.workers],
        }
        os.makedirs(os.path.dirname(self.state_path) or ".", exist_ok=True)
        tmp = f"{self.state_path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, self.state_path)
        return self.state_path

    def step(self) -> dict:
        """One decision interval: reap, probe, drill, decide, enact,
        publish. Returns the queue stats the decision used."""
        now = time.time()
        self._reap(now)
        self._enforce_drain_deadlines(now)
        for worker in self.workers:
            self._probe(worker, now)
        self._maybe_drill(now)
        try:
            stats = self.queue.stats()
        except Exception:  # a flaky queue tick must not kill the fleet
            stats = {"pending": None, "inflight": None, "dead": None,
                     "receives": None}
        self._decide(stats, now)
        self._enact(now)
        active = sum(1 for w in self.workers if w.active)
        telemetry.gauge("fleet/workers", active)
        telemetry.gauge("fleet/target", self.target)
        if stats.get("pending") is not None:
            telemetry.gauge("fleet/pending", stats["pending"])
        if stats.get("inflight") is not None:
            telemetry.gauge("fleet/inflight", stats["inflight"])
        self.write_state()
        return stats

    def _drained(self, stats: dict) -> bool:
        pending = stats.get("pending")
        inflight = stats.get("inflight")
        if inflight is None:  # backend can't say: use the probed leases
            if not self.probing:
                # telemetry off AND a blind backend: claimed-but-unacked
                # tasks are invisible to us entirely, so pending == 0 is
                # a guess — run() demands it persist for extra ticks
                # (_settle_target) instead of assuming zero leases
                return pending == 0
            # draining/quarantined workers keep their last probed lease
            # count until reaped, so sum over every running worker, not
            # just the active ones
            inflight = sum(w.inflight_leases for w in self.workers
                           if w.running)
        return pending == 0 and inflight == 0

    def _settle_target(self, stats: dict, settle_ticks: int) -> int:
        """Consecutive drained ticks required before declaring the
        queue done. When the backend cannot report inflight and probing
        is off, in-flight leases are invisible — pending hits 0 the
        moment the LAST tasks are claimed, not when they finish — so
        demand a much longer quiet period before SIGTERMing workers
        that may still be mid-compute."""
        if stats.get("inflight") is not None or self.probing:
            return settle_ticks
        return max(3 * settle_ticks, settle_ticks + 3)

    def run(self, max_runtime: float = 3600.0, settle_ticks: int = 2,
            shutdown_on_drain: bool = True) -> dict:
        """Supervise until the queue drains (``pending == inflight ==
        0`` for ``settle_ticks`` consecutive ticks), ``stop()`` is
        called, or ``max_runtime`` elapses. With
        ``shutdown_on_drain=False`` the fleet is left running at target
        size for the caller to inspect (the acceptance test asserts the
        survivor count) — call :meth:`shutdown` afterwards."""
        deadline = time.time() + max_runtime
        settled = 0
        telemetry.event(
            "fleet", "fleet/start", queue=self.queue_spec,
            static=self.static, min=self.min_workers, max=self.max_workers,
        )
        try:
            while not self._stop.is_set() and time.time() < deadline:
                stats = self.step()
                settled = settled + 1 if self._drained(stats) else 0
                if settled >= self._settle_target(stats, settle_ticks):
                    break
                self._stop.wait(self.interval)
        except BaseException:
            self.shutdown()  # never leave orphan workers behind
            raise
        if shutdown_on_drain:
            self.shutdown()
        else:
            self.write_state()
        return self.summary()

    def stop(self) -> None:
        self._stop.set()

    def shutdown(self) -> None:
        """Graceful fleet teardown: SIGTERM everyone (their preemption
        handlers nack + flush), hard-kill stragglers past
        ``term_grace``, reap, and write the final state file."""
        now = time.time()
        for worker in self.workers:
            if worker.running and worker.state != "draining":
                self._drain(worker, now)
        deadline = now + self.term_grace
        while time.time() < deadline and any(
                w.running for w in self.workers):
            time.sleep(0.05)
        for worker in self.workers:
            if worker.running:
                try:
                    worker.proc.kill()
                except OSError:
                    pass
        for worker in self.workers:
            if worker.exit_code is None:
                try:
                    worker.proc.wait(timeout=5.0)
                except Exception:
                    pass
        self._reap(time.time())
        self.write_state()
        telemetry.event("fleet", "fleet/stop")

    def summary(self) -> dict:
        counters = telemetry.snapshot()["counters"]
        return {
            "target": self.target,
            "alive": sum(1 for w in self.workers if w.active),
            "spawned": self._seq,
            "scale_ups": counters.get("fleet/scale_up", 0),
            "scale_downs": counters.get("fleet/scale_down", 0),
            "evictions": counters.get("fleet/evictions", 0),
            "worker_deaths": counters.get("fleet/worker_deaths", 0),
            "drill_preemptions": counters.get("fleet/drill_preemptions", 0),
            "leases_nacked": counters.get("fleet/leases_nacked", 0),
            "holds": counters.get("fleet/holds", 0),
            "static": self.static,
        }
