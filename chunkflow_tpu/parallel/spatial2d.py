"""Legacy 2D (y, x) sharding — now a shim over the unified engine.

The two-phase halo + reverse-spill program that lived here was subsumed
by :mod:`chunkflow_tpu.parallel.engine` (mesh spec ``y=A,x=B``): the
chunk still lives sharded over a (y, x) device grid with two-phase
``ppermute`` halo exchange (corner strips ride the x phase of the
y-extended block, no diagonal sends), but the blend accumulation is
replayed in reference order instead of spill-merged, so the output is
**bitwise identical** to the single-device program rather than
ulp-close. Only the mesh-shape helper remains.
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

Triple = Tuple[int, int, int]


def near_square_shape(n: int) -> Tuple[int, int]:
    """The default (ny, nx) factorization of ``n`` devices: the most
    square split with ny <= sqrt(n) (the legacy ``make_mesh_2d``
    layout, kept as the ``sharding='spatial2d'`` alias's shape)."""
    ny = int(np.floor(np.sqrt(n)))
    while n % ny:
        ny -= 1
    return ny, n // ny


def spatial2d_sharded_inference(
    chunk_array: np.ndarray,
    engine,
    input_patch_size: Triple,
    output_patch_size: Triple,
    output_patch_overlap: Triple,
    batch_size: int = 1,
    mesh=None,
    shape: Tuple[int, int] = None,
):
    """Fused inference with the chunk sharded over a (y, x) grid —
    delegates to the unified engine (``y=A,x=B`` spec)."""
    import jax

    from chunkflow_tpu.parallel.engine import MeshSpec, sharded_inference

    if shape is None:
        n = (mesh.devices.size if mesh is not None
             else len(jax.local_devices()))
        shape = near_square_shape(n)
    ny, nx = shape
    spec = (MeshSpec("spatial", (ny, nx)) if ny * nx > 1
            else MeshSpec("data", (1,)))
    return sharded_inference(
        chunk_array, engine, input_patch_size, output_patch_size,
        output_patch_overlap, batch_size=batch_size, spec=spec,
    )
