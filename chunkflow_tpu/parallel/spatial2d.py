"""2D spatially-sharded fused inference: the chunk sharded over (y, x).

Extends :mod:`parallel.spatial` (y-only ring) to a 2D device mesh
``('dy', 'dx')`` so a single task's spatial extent can exceed what a 1D
slab split supports (e.g. 2048x2048 xy planes over a pod slice). The
halo/spill pattern is the classic two-phase 2D exchange, expressed as XLA
``ppermute`` collectives on ICI:

1. input halos, phase y then phase x — the x phase moves the already
   y-extended strips, so corner data arrives with no diagonal sends;
2. the unchanged local fused blend over the doubly-extended block;
3. output spill in the REVERSE order (x then y): bump contributions past
   a slab's +x edge hop right along 'dx' (all extended-y rows ride
   along), then after the x crop the +y spill hops along 'dy' — a corner
   contribution reaches its diagonal owner in the two hops.

Output patches only ever spill toward +y/+x: patches are bucketed by
their output START slab, so outputs extend at most ``pout`` past the
slab's far edge and never before its near edge (same invariant as the 1D
module). The identity oracle across both chip-boundary directions is the
test (tests/parallel/test_spatial2d.py).
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import numpy as np

from chunkflow_tpu.parallel.spatial import spatial_geometry

Triple = Tuple[int, int, int]


def make_mesh_2d(shape: Tuple[int, int] = None, devices=None):
    """A ('dy', 'dx') mesh over the local devices (default: near-square)."""
    import jax
    from jax.sharding import Mesh

    devices = np.asarray(devices if devices is not None else jax.devices())
    n = devices.size
    if shape is None:
        ny = int(np.floor(np.sqrt(n)))
        while n % ny:
            ny -= 1
        shape = (ny, n // ny)
    if shape[0] * shape[1] != n:
        raise ValueError(f"mesh shape {shape} != {n} devices")
    return Mesh(devices.reshape(shape), ("dy", "dx"))


def spatial2d_geometry(y: int, x: int, mesh, pin: Triple, pout: Triple):
    """Per-axis slab geometry: ((yslab, hl_y, hr_y, spill_y, padded_y),
    (xslab, hl_x, hr_x, spill_x, padded_x))."""
    ny, nx = mesh.devices.shape
    gy = spatial_geometry(y, ny, pin, pout)
    # reuse the same math for x by presenting x as the "y" axis
    pin_x = (pin[0], pin[2], pin[1])
    pout_x = (pout[0], pout[2], pout[1])
    gx = spatial_geometry(x, nx, pin_x, pout_x)
    return gy, gx


def pad_chunk_yx(arr, padded_y: int, padded_x: int):
    """Zero-pad [C, Z, y, x] up to (padded_y, padded_x) on the high side."""
    pad = [(0, 0)] * arr.ndim
    pad[-2] = (0, padded_y - arr.shape[-2])
    pad[-1] = (0, padded_x - arr.shape[-1])
    if not any(p != (0, 0) for p in pad):
        return arr
    if isinstance(arr, np.ndarray):
        return np.pad(arr, pad)
    import jax.numpy as jnp

    return jnp.pad(arr, pad)


def partition_patches_2d(
    grid, mesh, yslab: int, xslab: int, batch_size: int,
    halo_left_y: int, halo_left_x: int,
):
    """Bucket the global patch grid by (y, x) output-start slab.

    Returns per-device arrays [ny, nx, P, 3] / [ny, nx, P] with y/x patch
    coordinates localized to each device's doubly-extended block frame.
    """
    ny, nx = mesh.devices.shape
    in_starts = np.asarray(grid.input_starts)
    out_starts = np.asarray(grid.output_starts)
    by = np.clip(out_starts[:, 1] // yslab, 0, ny - 1)
    bx = np.clip(out_starts[:, 2] // xslab, 0, nx - 1)

    max_count = max(
        int(((by == dy) & (bx == dx)).sum())
        for dy in range(ny) for dx in range(nx)
    )
    padded = max(-(-max_count // batch_size) * batch_size, batch_size)

    dev_in = np.zeros((ny, nx, padded, 3), dtype=np.int32)
    dev_out = np.zeros((ny, nx, padded, 3), dtype=np.int32)
    dev_valid = np.zeros((ny, nx, padded), dtype=np.float32)
    for dy in range(ny):
        for dx in range(nx):
            idx = np.nonzero((by == dy) & (bx == dx))[0]
            k = idx.size
            li = in_starts[idx].copy()
            lo = out_starts[idx].copy()
            for arr_ in (li, lo):
                arr_[:, 1] -= dy * yslab - halo_left_y
                arr_[:, 2] -= dx * xslab - halo_left_x
            dev_in[dy, dx, :k] = li
            dev_out[dy, dx, :k] = lo
            dev_valid[dy, dx, :k] = 1.0
    return dev_in, dev_out, dev_valid


def build_spatial2d_program(
    engine_apply,
    num_input_channels: int,
    num_output_channels: int,
    input_patch_size: Triple,
    output_patch_size: Triple,
    batch_size: int,
    mesh,
    bump_array: np.ndarray,
    geometry,
    out_dtype="float32",
):
    """jit-compiled (y, x)-sharded fused inference over mesh ('dy', 'dx')."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from chunkflow_tpu.ops.blend import build_local_blend, normalize_blend
    from chunkflow_tpu.parallel._shard_map import shard_map

    (yslab, hl_y, hr_y, spill_y, _), (xslab, hl_x, hr_x, spill_x, _) = geometry
    ny, nx = mesh.devices.shape
    local_blend = build_local_blend(
        engine_apply,
        num_input_channels,
        num_output_channels,
        input_patch_size,
        output_patch_size,
        batch_size,
        bump_array,
    )
    fwd_y = [(i, i + 1) for i in range(ny - 1)]
    bwd_y = [(i + 1, i) for i in range(ny - 1)]
    fwd_x = [(i, i + 1) for i in range(nx - 1)]
    bwd_x = [(i + 1, i) for i in range(nx - 1)]

    def device_fn(chunk_slab, in_starts, out_starts, valid, params):
        # chunk_slab: [C, Z, yslab, xslab]; patch lists carry two leading
        # sharded axes of size 1 each
        in_starts = in_starts[0, 0]
        out_starts = out_starts[0, 0]
        valid = valid[0, 0]

        # ---- 1a. y halo exchange ----
        top = lax.ppermute(
            chunk_slab[:, :, yslab - hl_y:, :], "dy", fwd_y
        )
        bottom = lax.ppermute(chunk_slab[:, :, :hr_y, :], "dy", bwd_y)
        ext_y = lax.concatenate([top, chunk_slab, bottom], dimension=2)
        # ---- 1b. x halo exchange of the y-extended block (corners ride) --
        left = lax.ppermute(ext_y[:, :, :, xslab - hl_x:], "dx", fwd_x)
        right = lax.ppermute(ext_y[:, :, :, :hr_x], "dx", bwd_x)
        extended = lax.concatenate([left, ext_y, right], dimension=3)

        # ---- 2. local fused blend over the doubly-extended block ----
        out, weight = local_blend(
            extended, in_starts, out_starts, valid, params
        )

        # ---- 3a. x spill (reverse of 1b): all extended-y rows ride ----
        xe = hl_x + xslab
        spill_o = lax.ppermute(out[:, :, :, xe:xe + spill_x], "dx", fwd_x)
        spill_w = lax.ppermute(weight[:, :, xe:xe + spill_x], "dx", fwd_x)
        out = out[:, :, :, hl_x:xe].at[:, :, :, :spill_x].add(spill_o)
        weight = weight[:, :, hl_x:xe].at[:, :, :spill_x].add(spill_w)
        # ---- 3b. y spill (reverse of 1a): corner spills complete here ----
        ye = hl_y + yslab
        spill_o = lax.ppermute(out[:, :, ye:ye + spill_y, :], "dy", fwd_y)
        spill_w = lax.ppermute(weight[:, ye:ye + spill_y, :], "dy", fwd_y)
        out = out[:, :, hl_y:ye, :].at[:, :, :spill_y, :].add(spill_o)
        weight = weight[:, hl_y:ye, :].at[:, :spill_y, :].add(spill_w)

        return out, weight

    sharded = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(
            P(None, None, "dy", "dx"),
            P("dy", "dx"),
            P("dy", "dx"),
            P("dy", "dx"),
            P(),
        ),
        out_specs=(
            P(None, None, "dy", "dx"),
            P(None, "dy", "dx"),
        ),
        check_rep=False,
    )

    # chunk is donated (GL005): dead after the call, may be aliased
    # into the output slab buffers — callers hand over a buffer they own
    @partial(jax.jit, donate_argnums=(0,))
    def program(chunk, dev_in, dev_out, dev_valid, params):
        out, weight = sharded(chunk, dev_in, dev_out, dev_valid, params)
        return normalize_blend(out, weight, out_dtype)

    return program


def spatial2d_sharded_inference(
    chunk_array: np.ndarray,
    engine,
    input_patch_size: Triple,
    output_patch_size: Triple,
    output_patch_overlap: Triple,
    batch_size: int = 1,
    mesh=None,
):
    """Fused inference with the chunk sharded over a ('dy', 'dx') mesh."""
    import jax.numpy as jnp

    from chunkflow_tpu.inference.bump import bump_map
    from chunkflow_tpu.inference.patching import enumerate_patches

    if mesh is None:
        mesh = make_mesh_2d()

    arr = np.asarray(chunk_array, dtype=np.float32)
    if arr.ndim == 3:
        arr = arr[None]
    _, _, y, x = arr.shape
    geometry = spatial2d_geometry(
        y, x, mesh, tuple(input_patch_size), tuple(output_patch_size)
    )
    (yslab, hl_y, _, _, padded_y), (xslab, hl_x, _, _, padded_x) = geometry

    # patch grid covers the REAL extent; padded rows/cols stay weight-zero
    grid = enumerate_patches(
        arr.shape, input_patch_size, output_patch_size, output_patch_overlap
    )
    arr = pad_chunk_yx(arr, padded_y, padded_x)
    dev_in, dev_out, dev_valid = partition_patches_2d(
        grid, mesh, yslab, xslab, batch_size, hl_y, hl_x
    )

    program = build_spatial2d_program(
        engine.apply,
        engine.num_input_channels,
        engine.num_output_channels,
        input_patch_size,
        grid.output_patch_size,
        batch_size,
        mesh,
        bump_map(tuple(grid.output_patch_size)),
        geometry,
    )
    result = program(
        jnp.asarray(arr),
        jnp.asarray(dev_in),
        jnp.asarray(dev_out),
        jnp.asarray(dev_valid),
        engine.params,
    )
    return result[:, :, :y, :x]
