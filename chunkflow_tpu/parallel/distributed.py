"""Patch-parallel psum program — the CROSS-HOST leg of the mesh engine.

The single-process patch-parallel path was subsumed by
:mod:`chunkflow_tpu.parallel.engine` (mesh spec ``data=N``), whose
forward-sharded + replayed-accumulation design is bitwise identical to
the single-device program. What remains here is the psum-merge variant
that the *multi-host* recipe still runs (``multihost.run_global``): when
one program spans processes, gathering every chip's weighted stack to
every host costs DCN bandwidth for data no host needs — the psum of
partial blend buffers is the right collective there, at ulp-level (not
bitwise) parity, which is exactly what the cross-host tests assert.

Cross-host: workers keep pulling independent chunk tasks from the queue
(communication-free task parallelism, deliberately preserved); this
module scales the single-task hot loop across the chips of a slice.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import numpy as np

from chunkflow_tpu.core.compile_cache import ProgramCache


def make_mesh(n_devices: Optional[int] = None, axis: str = "data"):
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis,))


def build_sharded_program(
    engine_apply,
    num_input_channels: int,
    num_output_channels: int,
    input_patch_size,
    output_patch_size,
    batch_size: int,
    mesh,
    bump_array: np.ndarray,
    out_dtype="float32",
):
    """jit-compiled multi-chip fused inference: chunk + patch coords -> output.

    Patch arrays must be padded so N is divisible by (n_devices * batch_size)
    (use patching.pad_to_batch with that product). The chunk is replicated;
    each device scans its N/n_devices patches and psums partial buffers.
    The result is cast to ``out_dtype`` inside the program (accumulation
    stays float32).
    """
    import jax
    from jax import lax
    from chunkflow_tpu.parallel._shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from chunkflow_tpu.ops.blend import build_local_blend, normalize_blend

    local_blend = build_local_blend(
        engine_apply,
        num_input_channels,
        num_output_channels,
        input_patch_size,
        output_patch_size,
        batch_size,
        bump_array,
    )

    def device_blend(chunk, in_starts, out_starts, valid, params):
        """Runs per device on its shard of the patch list; merges over ICI."""
        out, weight = local_blend(chunk, in_starts, out_starts, valid, params)
        out = lax.psum(out, "data")
        weight = lax.psum(weight, "data")
        return out, weight

    sharded = shard_map(
        device_blend,
        mesh=mesh,
        in_specs=(P(), P("data"), P("data"), P("data"), P()),
        out_specs=(P(), P()),
        check_rep=False,
    )

    # chunk is donated (GL005): dead after the call, may be aliased into
    # the psum-merged output buffer — callers hand over a buffer they own
    @partial(jax.jit, donate_argnums=(0,))
    def program(chunk, in_starts, out_starts, valid, params):
        out, weight = sharded(chunk, in_starts, out_starts, valid, params)
        return normalize_blend(out, weight, out_dtype)

    return program


# compiled-program reuse across chunk tasks with identical geometry: a
# worker loop must pay the (multi-minute on a pod) XLA compile once, not
# per chunk. Keyed on engine identity + every shape that feeds tracing.
# A real ProgramCache (not the bare dict this module used to carry), so
# the cross-host programs get the same instrumentation — compile-time
# ledger, roofline accounting in programs.json — as every other family.
# Engines are pinned alive alongside their entry via _ENGINE_PINS so the
# id(engine) in the key cannot be recycled while the entry lives.
_PROGRAMS = ProgramCache(maxsize=16, label="distributed")
_ENGINE_PINS: dict = {}


def prepare_sharded(
    chunk_shape,
    engine,
    input_patch_size,
    output_patch_size,
    output_patch_overlap,
    batch_size: int,
    mesh,
):
    """Shared plumbing for the multi-host wrapper: patch grid + padded
    coordinate arrays + the (cached) compiled psum program. Returns
    (program, in_starts, out_starts, valid)."""
    from chunkflow_tpu.inference.bump import bump_map
    from chunkflow_tpu.inference.patching import enumerate_patches, pad_to_batch

    grid = enumerate_patches(
        tuple(chunk_shape), input_patch_size, output_patch_size,
        output_patch_overlap,
    )
    in_starts, out_starts, valid = pad_to_batch(
        grid, batch_size * mesh.devices.size
    )
    key = (
        id(engine), tuple(chunk_shape), tuple(input_patch_size),
        tuple(grid.output_patch_size), tuple(output_patch_overlap),
        batch_size, tuple(mesh.axis_names),
        tuple(d.id for d in mesh.devices.flat),
    )
    program = _PROGRAMS.get(
        key,
        lambda: build_sharded_program(
            engine.apply,
            engine.num_input_channels,
            engine.num_output_channels,
            input_patch_size,
            grid.output_patch_size,
            batch_size,
            mesh,
            bump_map(tuple(grid.output_patch_size)),
        ),
    )
    _ENGINE_PINS[key] = engine
    while len(_ENGINE_PINS) > 2 * _PROGRAMS.maxsize:
        _ENGINE_PINS.pop(next(iter(_ENGINE_PINS)))
    return program, in_starts, out_starts, valid


def sharded_inference(
    chunk_array: np.ndarray,
    engine,
    input_patch_size,
    output_patch_size,
    output_patch_overlap,
    batch_size: int = 1,
    mesh=None,
):
    """Single-process multi-chip inference — delegates to the unified
    engine (``data=N`` spec, bitwise identical to single-device)."""
    import jax

    from chunkflow_tpu.parallel.engine import (
        MeshSpec,
        sharded_inference as unified,
    )

    n_dev = (mesh.devices.size if mesh is not None
             else len(jax.local_devices()))
    return unified(
        chunk_array, engine, input_patch_size, output_patch_size,
        output_patch_overlap, batch_size=batch_size,
        spec=MeshSpec("data", (max(n_dev, 1),)),
    )
