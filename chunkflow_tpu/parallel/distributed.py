"""Multi-chip patch-parallel inference via shard_map over a device mesh.

SURVEY §2.10 mapping: the reference's only intra-worker parallelism is the
patch batch (single GPU, DataParallel commented out). Here patch batches
shard across TPU chips on a ('data',) mesh axis: every chip gathers and
forwards its own subset of patches from the (replicated) input chunk,
blends locally, and one psum over ICI merges the weighted partial outputs
before reciprocal normalization. No host round trips, no NCCL-style
point-to-point — just XLA collectives.

Cross-host: workers keep pulling independent chunk tasks from the queue
(communication-free task parallelism, deliberately preserved); this module
scales the single-task hot loop across the chips of one slice.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import numpy as np


def make_mesh(n_devices: Optional[int] = None, axis: str = "data"):
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis,))


def build_sharded_program(
    engine_apply,
    num_input_channels: int,
    num_output_channels: int,
    input_patch_size,
    output_patch_size,
    batch_size: int,
    mesh,
    bump_array: np.ndarray,
    out_dtype="float32",
):
    """jit-compiled multi-chip fused inference: chunk + patch coords -> output.

    Patch arrays must be padded so N is divisible by (n_devices * batch_size)
    (use patching.pad_to_batch with that product). The chunk is replicated;
    each device scans its N/n_devices patches and psums partial buffers.
    The result is cast to ``out_dtype`` inside the program (accumulation
    stays float32).
    """
    import jax
    from jax import lax
    from chunkflow_tpu.parallel._shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from chunkflow_tpu.ops.blend import build_local_blend, normalize_blend

    local_blend = build_local_blend(
        engine_apply,
        num_input_channels,
        num_output_channels,
        input_patch_size,
        output_patch_size,
        batch_size,
        bump_array,
    )

    def device_blend(chunk, in_starts, out_starts, valid, params):
        """Runs per device on its shard of the patch list; merges over ICI."""
        out, weight = local_blend(chunk, in_starts, out_starts, valid, params)
        out = lax.psum(out, "data")
        weight = lax.psum(weight, "data")
        return out, weight

    sharded = shard_map(
        device_blend,
        mesh=mesh,
        in_specs=(P(), P("data"), P("data"), P("data"), P()),
        out_specs=(P(), P()),
        check_rep=False,
    )

    # chunk is donated (GL005): dead after the call, may be aliased into
    # the psum-merged output buffer — callers hand over a buffer they own
    @partial(jax.jit, donate_argnums=(0,))
    def program(chunk, in_starts, out_starts, valid, params):
        out, weight = sharded(chunk, in_starts, out_starts, valid, params)
        return normalize_blend(out, weight, out_dtype)

    return program


# compiled-program reuse across chunk tasks with identical geometry: a
# worker loop must pay the (multi-minute on a pod) XLA compile once, not
# per chunk. Keyed on engine identity + every shape that feeds tracing.
# Bounded FIFO: each entry's closure pins its engine (and params) alive,
# so an unbounded cache would grow without limit across edge-chunk shapes.
_PROGRAM_CACHE: dict = {}
_PROGRAM_CACHE_MAX = 16


def prepare_sharded(
    chunk_shape,
    engine,
    input_patch_size,
    output_patch_size,
    output_patch_overlap,
    batch_size: int,
    mesh,
):
    """Shared plumbing for the single-host and multi-host wrappers:
    patch grid + padded coordinate arrays + the (cached) compiled
    program. Returns (program, in_starts, out_starts, valid)."""
    from chunkflow_tpu.inference.bump import bump_map
    from chunkflow_tpu.inference.patching import enumerate_patches, pad_to_batch

    grid = enumerate_patches(
        tuple(chunk_shape), input_patch_size, output_patch_size,
        output_patch_overlap,
    )
    in_starts, out_starts, valid = pad_to_batch(
        grid, batch_size * mesh.devices.size
    )
    key = (
        id(engine), tuple(chunk_shape), tuple(input_patch_size),
        tuple(grid.output_patch_size), tuple(output_patch_overlap),
        batch_size, tuple(mesh.axis_names),
        tuple(d.id for d in mesh.devices.flat),
    )
    entry = _PROGRAM_CACHE.get(key)
    # the strong engine reference in the entry guarantees id(engine) in
    # the key cannot be recycled while the entry lives
    if entry is None or entry[0] is not engine:
        program = build_sharded_program(
            engine.apply,
            engine.num_input_channels,
            engine.num_output_channels,
            input_patch_size,
            grid.output_patch_size,
            batch_size,
            mesh,
            bump_map(tuple(grid.output_patch_size)),
        )
        _PROGRAM_CACHE[key] = (engine, program)
        while len(_PROGRAM_CACHE) > _PROGRAM_CACHE_MAX:
            _PROGRAM_CACHE.pop(next(iter(_PROGRAM_CACHE)))
    else:
        program = entry[1]
    return program, in_starts, out_starts, valid


def sharded_inference(
    chunk_array: np.ndarray,
    engine,
    input_patch_size,
    output_patch_size,
    output_patch_overlap,
    batch_size: int = 1,
    mesh=None,
):
    """Convenience wrapper: run multi-chip fused inference on an array."""
    import jax.numpy as jnp

    if mesh is None:
        mesh = make_mesh()
    program, in_starts, out_starts, valid = prepare_sharded(
        chunk_array.shape, engine, input_patch_size, output_patch_size,
        output_patch_overlap, batch_size, mesh,
    )
    arr = jnp.asarray(chunk_array, dtype=jnp.float32)
    if arr.ndim == 3:
        arr = arr[None]
    if arr is chunk_array:
        # the program donates its chunk argument; never hand it the
        # caller's own (already float32, already device) buffer
        arr = arr.copy()
    return program(
        arr,
        jnp.asarray(in_starts),
        jnp.asarray(out_starts),
        jnp.asarray(valid),
        engine.params,
    )
