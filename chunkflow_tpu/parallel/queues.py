"""Task queues: the distributed communication backend.

Parity target: reference lib/aws/sqs_queue.py — a queue of bbox strings with
visibility timeout, ack-after-write commit, and batch send. Workers never
talk to each other; the queue plus object storage is the whole protocol
(communication-free task parallelism — the right design for chunked
inference, kept here deliberately instead of collectives).

Backends:
- ``memory://name``  — in-process, for tests (fixes the reference's
  untestable-SQS gap);
- ``file:///dir``    — a directory of task files with atomic rename claims
  and mtime-based visibility timeout; safe across processes/hosts on a
  shared filesystem (SLURM-style clusters);
- ``sqs://name``     — AWS SQS via boto3, gated on the library being
  importable (not baked into this image).
"""
from __future__ import annotations

import os
import time
import uuid
from typing import Dict, Iterator, List, Optional, Tuple


class QueueBase:
    """handle/body iteration + ack protocol shared by all backends."""

    def send_messages(self, bodies: List[str]) -> None:
        raise NotImplementedError

    def receive(self) -> Optional[Tuple[str, str]]:
        """One (handle, body) or None when empty."""
        raise NotImplementedError

    def delete(self, handle: str) -> None:
        """Ack: permanently remove a claimed task (the commit point)."""
        raise NotImplementedError

    # polling iteration with bounded retries on empty
    max_empty_retries = 3
    retry_sleep = 1.0

    def __iter__(self) -> Iterator[Tuple[str, str]]:
        empty = 0
        while True:
            item = self.receive()
            if item is None:
                empty += 1
                if empty > self.max_empty_retries:
                    return
                time.sleep(self.retry_sleep)
                continue
            empty = 0
            yield item


class MemoryQueue(QueueBase):
    """In-process queue with visibility timeout semantics."""

    _registry: Dict[str, "MemoryQueue"] = {}

    def __init__(self, name: str, visibility_timeout: float = 1800.0):
        self.name = name
        self.visibility_timeout = visibility_timeout
        self.pending: Dict[str, str] = {}
        self.invisible: Dict[str, Tuple[str, float]] = {}
        self.retry_sleep = 0.01

    @classmethod
    def open(cls, name: str, visibility_timeout: float = 1800.0) -> "MemoryQueue":
        if name not in cls._registry:
            cls._registry[name] = cls(name, visibility_timeout)
        return cls._registry[name]

    def send_messages(self, bodies: List[str]) -> None:
        for body in bodies:
            self.pending[uuid.uuid4().hex] = body

    def _requeue_expired(self) -> None:
        now = time.time()
        expired = [h for h, (_, t) in self.invisible.items()
                   if now - t > self.visibility_timeout]
        for h in expired:
            body, _ = self.invisible.pop(h)
            self.pending[h] = body

    def receive(self) -> Optional[Tuple[str, str]]:
        self._requeue_expired()
        if not self.pending:
            return None
        handle, body = next(iter(self.pending.items()))
        del self.pending[handle]
        self.invisible[handle] = (body, time.time())
        return handle, body

    def delete(self, handle: str) -> None:
        self.invisible.pop(handle, None)
        self.pending.pop(handle, None)

    def __len__(self) -> int:
        self._requeue_expired()
        return len(self.pending)


class FileQueue(QueueBase):
    """Directory-backed queue; atomic rename is the claim operation.

    Layout: ``<dir>/pending/<id>`` holds the body; claiming renames it to
    ``<dir>/claimed/<id>``; delete removes the claimed file. A janitor pass
    returns claimed files older than the visibility timeout to pending —
    so crashed workers' tasks reappear, same as SQS.
    """

    def __init__(self, directory: str, visibility_timeout: float = 1800.0):
        self.dir = directory
        self.pending_dir = os.path.join(directory, "pending")
        self.claimed_dir = os.path.join(directory, "claimed")
        os.makedirs(self.pending_dir, exist_ok=True)
        os.makedirs(self.claimed_dir, exist_ok=True)
        self.visibility_timeout = visibility_timeout

    def send_messages(self, bodies: List[str]) -> None:
        for body in bodies:
            name = uuid.uuid4().hex
            tmp = os.path.join(self.dir, f".tmp-{name}")
            with open(tmp, "w") as f:
                f.write(body)
            os.rename(tmp, os.path.join(self.pending_dir, name))

    def _requeue_expired(self) -> None:
        now = time.time()
        for name in os.listdir(self.claimed_dir):
            path = os.path.join(self.claimed_dir, name)
            try:
                if now - os.path.getmtime(path) > self.visibility_timeout:
                    os.rename(path, os.path.join(self.pending_dir, name))
            except OSError:
                pass  # another janitor/worker won the race

    def receive(self) -> Optional[Tuple[str, str]]:
        self._requeue_expired()
        for name in sorted(os.listdir(self.pending_dir)):
            src = os.path.join(self.pending_dir, name)
            dst = os.path.join(self.claimed_dir, name)
            try:
                os.rename(src, dst)  # atomic claim
            except OSError:
                continue  # raced with another worker
            os.utime(dst)
            with open(dst) as f:
                return name, f.read()
        return None

    def delete(self, handle: str) -> None:
        try:
            os.remove(os.path.join(self.claimed_dir, handle))
        except FileNotFoundError:
            pass

    def __len__(self) -> int:
        return len(os.listdir(self.pending_dir))


class SQSQueue(QueueBase):
    """AWS SQS backend (requires boto3 + credentials; not in this image)."""

    def __init__(self, name: str, visibility_timeout: int = 1800):
        try:
            import boto3
        except ImportError as e:
            raise RuntimeError(
                "sqs:// queues need boto3, which is not installed; "
                "use file:// or memory:// queues instead"
            ) from e
        self.client = boto3.client("sqs")
        resp = self.client.create_queue(
            QueueName=name,
            Attributes={"VisibilityTimeout": str(visibility_timeout)},
        )
        self.queue_url = resp["QueueUrl"]

    def send_messages(self, bodies: List[str]) -> None:
        for i in range(0, len(bodies), 10):  # SQS batch limit
            entries = [
                {"Id": str(j), "MessageBody": body}
                for j, body in enumerate(bodies[i : i + 10])
            ]
            self.client.send_message_batch(
                QueueUrl=self.queue_url, Entries=entries
            )

    def receive(self) -> Optional[Tuple[str, str]]:
        resp = self.client.receive_message(
            QueueUrl=self.queue_url, MaxNumberOfMessages=1, WaitTimeSeconds=20
        )
        messages = resp.get("Messages", [])
        if not messages:
            return None
        msg = messages[0]
        # transport integrity check (reference sqs_queue.py:95-100)
        expected = msg.get("MD5OfBody")
        if expected:
            import hashlib

            got = hashlib.md5(msg["Body"].encode()).hexdigest()
            if got != expected:
                raise IOError(
                    f"SQS body md5 mismatch: got {got}, expected {expected}"
                )
        return msg["ReceiptHandle"], msg["Body"]

    def delete(self, handle: str) -> None:
        self.client.delete_message(QueueUrl=self.queue_url, ReceiptHandle=handle)


def open_queue(spec: str, visibility_timeout: float = 1800.0) -> QueueBase:
    """Open a queue from a ``scheme://name`` spec (bare paths mean file://)."""
    if spec.startswith("memory://"):
        return MemoryQueue.open(spec[len("memory://"):], visibility_timeout)
    if spec.startswith("sqs://"):
        return SQSQueue(spec[len("sqs://"):], int(visibility_timeout))
    if spec.startswith("file://"):
        spec = spec[len("file://"):]
    return FileQueue(spec, visibility_timeout)
