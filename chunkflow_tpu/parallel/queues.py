"""Task queues: the distributed communication backend.

Parity target: reference lib/aws/sqs_queue.py — a queue of bbox strings with
visibility timeout, ack-after-write commit, and batch send. Workers never
talk to each other; the queue plus object storage is the whole protocol
(communication-free task parallelism — the right design for chunked
inference, kept here deliberately instead of collectives).

Beyond the reference's happy path, every backend speaks the full task
lifecycle protocol consumed by ``parallel/lifecycle.py``
(docs/fault_tolerance.md):

* :meth:`QueueBase.renew` — lease heartbeat: extend a claimed task's
  visibility timeout so a slow chunk is not double-claimed mid-compute
  (SQS ``ChangeMessageVisibility``);
* :meth:`QueueBase.nack` — immediate visibility release of a claimed
  task (graceful preemption: a SIGTERM'd worker hands its task back
  instead of letting the timeout expire);
* :meth:`QueueBase.receive_count` — per-task delivery count (memory:
  dict; file: sidecar count next to the claimed entry; SQS:
  ``ApproximateReceiveCount``), the retry accounting substrate;
* :meth:`QueueBase.dead_letter` / :meth:`dead_letters` /
  :meth:`requeue_dead` — a poison task that keeps failing moves to a
  dead-letter store carrying its failure reason, inspectable and
  requeueable via the CLI (``chunkflow dead-letter``).

Backends:
- ``memory://name``  — in-process, for tests (fixes the reference's
  untestable-SQS gap);
- ``file:///dir``    — a directory of task files with atomic rename claims
  and mtime-based visibility timeout; safe across processes/hosts on a
  shared filesystem (SLURM-style clusters);
- ``sqs://name``     — AWS SQS via boto3, gated on the library being
  importable (not baked into this image).

Distributed tracing (docs/observability.md "Fleet view"): every task
submitted through :meth:`QueueBase.send_messages` is wrapped in a JSON
envelope carrying a freshly minted ``trace_id``. The envelope is the
*wire* format only — :meth:`receive` unwraps it, so consumers keep
seeing the plain bbox-string body — and it survives every lifecycle
hop: claim, nack, janitor requeue, dead-letter, ``requeue_dead``
(:func:`pack_task` is idempotent, so a requeued envelope keeps its
original id). :meth:`QueueBase.trace_id` exposes the claimed task's id
so the lifecycle layer can stamp telemetry with it
(``telemetry.task_context``). Pre-envelope bodies (an old queue on
disk) still work: they unwrap to themselves with no trace id.
"""
from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Dict, Iterator, List, Optional, Tuple

from chunkflow_tpu.core import telemetry


def new_trace_id() -> str:
    """A fresh 32-hex trace id, minted once per task submission."""
    return uuid.uuid4().hex


_ENVELOPE_PREFIX = '{"chunkflow"'


def pack_task(body: str, trace_id: Optional[str] = None) -> str:
    """Wrap a task body in the traced wire envelope. Idempotent: a body
    that is already an envelope is returned unchanged, preserving its
    original trace id across requeue/dead-letter round trips."""
    if unpack_task(body)[1] is not None:
        return body
    if trace_id is None:
        trace_id = new_trace_id()
    return json.dumps({"chunkflow": 1, "body": body, "trace_id": trace_id})


def unpack_task(raw: str) -> Tuple[str, Optional[str]]:
    """``(body, trace_id)`` from a wire payload; a non-envelope payload
    (pre-tracing queue contents) unwraps to ``(raw, None)``."""
    if raw.startswith(_ENVELOPE_PREFIX):
        try:
            env = json.loads(raw)
        except ValueError:
            return raw, None
        if isinstance(env, dict) and "body" in env:
            return str(env["body"]), env.get("trace_id")
    return raw, None


class QueueBase:
    """handle/body iteration + ack/lease/dead-letter protocol shared by
    all backends."""

    visibility_timeout: float = 1800.0

    def send_messages(self, bodies: List[str]) -> None:
        raise NotImplementedError

    def receive(self) -> Optional[Tuple[str, str]]:
        """One (handle, body) or None when empty."""
        raise NotImplementedError

    # -- distributed tracing --------------------------------------------
    def _pack_bodies(self, bodies: List[str]) -> List[str]:
        """Envelope each outgoing body (idempotent) and emit one
        ``queue/submit`` event per task — submission is where a trace
        begins, so the submitter's JSONL anchors every timeline."""
        packed = []
        for body in bodies:
            wire = pack_task(body)
            packed.append(wire)
            plain, trace_id = unpack_task(wire)
            telemetry.inc("queue/sent")
            telemetry.event(
                "task", "queue/submit", queue=self.describe(),
                body=plain, trace_id=trace_id,
            )
        return packed

    def _note_receive(self, handle: str, trace_id: Optional[str]) -> None:
        if not hasattr(self, "_traces"):
            self._traces: Dict[str, Optional[str]] = {}
        self._traces[handle] = trace_id
        telemetry.inc("queue/receives")

    def trace_id(self, handle: str) -> Optional[str]:
        """Trace id of a claimed task (None when the delivery carried
        no envelope)."""
        return getattr(self, "_traces", {}).get(handle)

    @staticmethod
    def _present(entry: dict) -> dict:
        """Dead-letter entry for display: the stored body stays in wire
        format (so requeue preserves the trace), the listed copy shows
        the plain body plus its trace id."""
        body, trace_id = unpack_task(entry.get("body", ""))
        shown = dict(entry)
        shown["body"] = body
        if trace_id is not None:
            shown.setdefault("trace_id", trace_id)
        return shown

    def describe(self) -> str:
        """Human-readable queue identity for events and fleet-status."""
        return getattr(self, "name", None) or getattr(self, "dir", "") \
            or type(self).__name__

    def stats(self) -> dict:
        """Live queue state for the fleet-status dashboard:
        ``{"pending", "inflight", "dead", "receives"}``; None for a
        field the backend cannot report cheaply."""
        try:
            pending: Optional[int] = len(self)  # type: ignore[arg-type]
        except (TypeError, NotImplementedError):
            pending = None
        return {"pending": pending, "inflight": None, "dead": None,
                "receives": None}

    def delete(self, handle: str) -> None:
        """Ack: permanently remove a claimed task (the commit point)."""
        raise NotImplementedError

    # -- lifecycle protocol (parallel/lifecycle.py) ---------------------
    def renew(self, handle: str, timeout: Optional[float] = None) -> None:
        """Extend the claim on ``handle`` so it stays invisible for
        another ``timeout`` seconds (default: the queue's visibility
        timeout) from now. The lease heartbeat for in-compute tasks."""
        raise NotImplementedError

    def nack(self, handle: str, refund: bool = True) -> bool:
        """Release the claim immediately: the task becomes visible to
        other workers right away (preemption / fast retry) instead of
        after the visibility timeout. Returns whether a claim was
        actually released (False when the handle already expired, was
        acked, or was janitored back — the work is safe elsewhere).

        With ``refund=True`` (the default, for *first-party* nacks) a
        nacked delivery is a *handback*, not a failure: backends that
        can (memory, file) decrement the receive count so preemption /
        bystander-surrender hops do not burn the retry budget — under
        frequent spot preemption a healthy task would otherwise be
        dead-lettered as a "crash loop" without ever failing.
        ``refund=False`` requeues while *preserving* the count
        (janitor-style): the third-party release path for workers that
        died or wedged, whose deliveries must keep counting toward the
        crash-loop bound. SQS cannot decrement
        ``ApproximateReceiveCount`` either way; size ``--max-retries``
        generously there (the SQS redrive-policy convention)."""
        raise NotImplementedError

    def force_release(self, handles, refund: bool = False) -> int:
        """Third-party nack: release claims a DEAD worker is still
        holding, by handle, so its tasks reappear now instead of after
        the visibility timeout. The fleet supervisor calls this when it
        evicts or reaps a worker, using the lease handles the worker
        last reported over ``/healthz`` (parallel/fleet.py).

        ``refund`` defaults to False: an unexpected or quarantined exit
        is a crash-shaped delivery, and refunding its receive count
        would make the crash-loop bound (lifecycle: ``receives >
        max_retries``) unreachable — a poison task that kills every
        worker it lands on would be redelivered forever. Keep the
        refund for first-party preemption/surrender nacks only.

        Per-handle errors are swallowed — a handle may have expired,
        been janitored back, or belong to a re-claimed task, all of
        which mean the work is already safe. Returns how many claims
        were actually released (no-op nacks are not counted)."""
        released = 0
        for handle in handles or ():
            try:
                if self.nack(handle, refund=refund):
                    released += 1
            except Exception:
                continue
        return released

    def receive_count(self, handle: str) -> int:
        """How many times the claimed task has been delivered, this
        delivery included. 1 on first claim; best-effort (0 when the
        backend cannot tell)."""
        return 0

    def dead_letter(self, handle: str, reason: str = "") -> None:
        """Move a claimed poison task to the dead-letter store with its
        failure reason; it will never be delivered again until an
        operator requeues it."""
        raise NotImplementedError

    def dead_letters(self) -> List[dict]:
        """List dead-letter entries as ``{"body", "reason", "receives",
        "t"}`` dicts (non-destructive where the backend allows)."""
        raise NotImplementedError

    def requeue_dead(self) -> int:
        """Move every dead-letter entry back to pending with a fresh
        retry budget; returns how many were requeued."""
        raise NotImplementedError

    # polling iteration with bounded retries on empty
    max_empty_retries = 3
    retry_sleep = 1.0

    def __iter__(self) -> Iterator[Tuple[str, str]]:
        empty = 0
        while True:
            item = self.receive()
            if item is None:
                empty += 1
                if empty > self.max_empty_retries:
                    return
                time.sleep(self.retry_sleep)
                continue
            empty = 0
            yield item


class MemoryQueue(QueueBase):
    """In-process queue with visibility timeout semantics.

    Thread-safe: one MemoryQueue is drained by several worker THREADS at
    once (the serving front-end's LocalBackend runs a claim loop per
    worker thread, the lifecycle heartbeat renews leases from its own
    thread). ``receive`` in particular is a compound
    claim-and-make-invisible — unlocked, two threads could claim the
    same handle (double execution) or crash on the second ``del``, so
    every compound state transition holds ``_lock``.
    """

    _registry: Dict[str, "MemoryQueue"] = {}
    _registry_lock = threading.Lock()

    def __init__(self, name: str, visibility_timeout: float = 1800.0):
        self.name = name
        self.visibility_timeout = visibility_timeout
        self.pending: Dict[str, str] = {}
        # handle -> (body, visibility deadline): invisible until deadline
        self.invisible: Dict[str, Tuple[str, float]] = {}
        self.receives: Dict[str, int] = {}
        self.dead: Dict[str, dict] = {}
        self.retry_sleep = 0.01
        self._lock = threading.Lock()

    @classmethod
    def open(cls, name: str, visibility_timeout: float = 1800.0) -> "MemoryQueue":
        with cls._registry_lock:
            if name not in cls._registry:
                cls._registry[name] = cls(name, visibility_timeout)
            else:
                # a reopen with a different timeout is a reconfiguration,
                # not a no-op: silently keeping the first value would give
                # lease renewal / requeue tests (and real workers) a
                # different timeout than they asked for
                cls._registry[name].visibility_timeout = visibility_timeout
            return cls._registry[name]

    def send_messages(self, bodies: List[str]) -> None:
        packed = self._pack_bodies(bodies)  # telemetry outside the lock
        with self._lock:
            for body in packed:
                self.pending[uuid.uuid4().hex] = body

    def _requeue_expired(self) -> None:
        """Caller holds ``_lock``."""
        now = time.time()
        expired = [h for h, (_, deadline) in self.invisible.items()
                   if now > deadline]
        for h in expired:
            body, _ = self.invisible.pop(h)
            self.pending[h] = body

    def receive(self) -> Optional[Tuple[str, str]]:
        with self._lock:
            self._requeue_expired()
            if not self.pending:
                return None
            handle, wire = next(iter(self.pending.items()))
            del self.pending[handle]
            self.invisible[handle] = (
                wire, time.time() + self.visibility_timeout
            )
            self.receives[handle] = self.receives.get(handle, 0) + 1
        body, trace_id = unpack_task(wire)
        self._note_receive(handle, trace_id)
        return handle, body

    def delete(self, handle: str) -> None:
        with self._lock:
            self.invisible.pop(handle, None)
            self.pending.pop(handle, None)
            self.receives.pop(handle, None)
            getattr(self, "_traces", {}).pop(handle, None)

    def renew(self, handle: str, timeout: Optional[float] = None) -> None:
        with self._lock:
            entry = self.invisible.get(handle)
            if entry is None:
                return  # already expired/acked: nothing to extend
            timeout = self.visibility_timeout if timeout is None else timeout
            self.invisible[handle] = (entry[0], time.time() + timeout)

    def nack(self, handle: str, refund: bool = True) -> bool:
        with self._lock:
            entry = self.invisible.pop(handle, None)
            if entry is None:
                return False  # already acked or expired: nothing to release
            self.pending[handle] = entry[0]
            if refund:
                # a first-party handback is not a failed attempt (see
                # QueueBase.nack); third-party force_release preserves the
                # count so crash deliveries accrue
                count = self.receives.get(handle, 0)
                if count > 0:
                    self.receives[handle] = count - 1
            return True

    def receive_count(self, handle: str) -> int:
        with self._lock:
            return self.receives.get(handle, 0)

    def dead_letter(self, handle: str, reason: str = "") -> None:
        with self._lock:
            entry = self.invisible.pop(handle, None)
            body = entry[0] if entry else self.pending.pop(handle, None)
            if body is None:
                return
            self.dead[handle] = {
                "body": body, "reason": reason,
                "receives": self.receives.pop(handle, 0), "t": time.time(),
            }

    def dead_letters(self) -> List[dict]:
        with self._lock:
            return [self._present(entry) for entry in self.dead.values()]

    def requeue_dead(self) -> int:
        with self._lock:
            count = 0
            for handle, entry in list(self.dead.items()):
                del self.dead[handle]
                # the stored body is still the wire envelope: the requeued
                # task keeps its original trace id, fresh retry budget
                self.pending[handle] = entry["body"]
                count += 1
            return count

    def stats(self) -> dict:
        with self._lock:
            self._requeue_expired()
            return {
                "pending": len(self.pending),
                "inflight": len(self.invisible),
                "dead": len(self.dead),
                "receives": sum(self.receives.values()),
            }

    def __len__(self) -> int:
        with self._lock:
            self._requeue_expired()
            return len(self.pending)


class FileQueue(QueueBase):
    """Directory-backed queue; atomic rename is the claim operation.

    Layout: ``<dir>/pending/<id>`` holds the body; claiming renames it to
    ``<dir>/claimed/<id>``; delete removes the claimed file. A janitor pass
    returns claimed files older than the visibility timeout to pending —
    so crashed workers' tasks reappear, same as SQS. The lifecycle
    extensions ride the same layout: ``<dir>/counts/<id>`` is the
    delivery-count sidecar of a claimed entry (it survives janitor
    requeues, so retry accounting sees crashed attempts too) and
    ``<dir>/dead/<id>`` holds dead-lettered tasks as JSON
    ``{body, reason, receives, t}``.
    """

    def __init__(self, directory: str, visibility_timeout: float = 1800.0):
        self.dir = directory
        self.pending_dir = os.path.join(directory, "pending")
        self.claimed_dir = os.path.join(directory, "claimed")
        self.counts_dir = os.path.join(directory, "counts")
        self.dead_dir = os.path.join(directory, "dead")
        for d in (self.pending_dir, self.claimed_dir,
                  self.counts_dir, self.dead_dir):
            os.makedirs(d, exist_ok=True)
        self.visibility_timeout = visibility_timeout

    def send_messages(self, bodies: List[str]) -> None:
        for body in self._pack_bodies(bodies):
            name = uuid.uuid4().hex
            tmp = os.path.join(self.dir, f".tmp-{name}")
            with open(tmp, "w") as f:
                f.write(body)
            os.rename(tmp, os.path.join(self.pending_dir, name))

    def _requeue_expired(self) -> None:
        now = time.time()
        for name in os.listdir(self.claimed_dir):
            path = os.path.join(self.claimed_dir, name)
            try:
                if now - os.path.getmtime(path) > self.visibility_timeout:
                    os.rename(path, os.path.join(self.pending_dir, name))
            except OSError:
                pass  # another janitor/worker won the race
        # a writer that crashed mid-stage leaves .tmp-* files behind
        # forever (queue root: send_messages; counts dir: _write_count);
        # sweep the stale ones (older than the visibility timeout, so
        # an in-progress write is safe)
        for d in (self.dir, self.counts_dir):
            for name in os.listdir(d):
                if not name.startswith(".tmp-"):
                    continue
                path = os.path.join(d, name)
                try:
                    if now - os.path.getmtime(path) > self.visibility_timeout:
                        os.remove(path)
                except OSError:
                    pass

    def _write_count(self, name: str, count: int) -> bool:
        """Atomically (re)write a delivery-count sidecar — staged to a
        temp file then renamed, so a concurrent reader never sees a
        half-written (empty) count."""
        tmp = os.path.join(self.counts_dir, f".tmp-{uuid.uuid4().hex}")
        try:
            with open(tmp, "w") as f:
                f.write(str(count))
            os.rename(tmp, os.path.join(self.counts_dir, name))
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False
        return True

    def _bump_count(self, name: str) -> int:
        count = self._read_count(name) + 1
        self._write_count(name, count)
        return count

    def _read_count(self, name: str) -> int:
        try:
            with open(os.path.join(self.counts_dir, name)) as f:
                return int(f.read().strip() or 0)
        except (OSError, ValueError):
            return 0

    def receive(self) -> Optional[Tuple[str, str]]:
        self._requeue_expired()
        for name in sorted(os.listdir(self.pending_dir)):
            src = os.path.join(self.pending_dir, name)
            dst = os.path.join(self.claimed_dir, name)
            try:
                os.rename(src, dst)  # atomic claim
            except OSError:
                continue  # raced with another worker
            os.utime(dst)
            self._bump_count(name)
            with open(dst) as f:
                body, trace_id = unpack_task(f.read())
            self._note_receive(name, trace_id)
            return name, body
        return None

    def delete(self, handle: str) -> None:
        for path in (os.path.join(self.claimed_dir, handle),
                     os.path.join(self.counts_dir, handle)):
            try:
                os.remove(path)
            except FileNotFoundError:
                pass
        getattr(self, "_traces", {}).pop(handle, None)

    def renew(self, handle: str, timeout: Optional[float] = None) -> None:
        timeout = self.visibility_timeout if timeout is None else timeout
        path = os.path.join(self.claimed_dir, handle)
        # expiry is mtime + visibility_timeout: place the mtime so the
        # claim lives exactly `timeout` seconds from now
        stamp = time.time() + timeout - self.visibility_timeout
        try:
            os.utime(path, (stamp, stamp))
        except OSError:
            pass  # expired and re-claimed elsewhere: lease is lost

    def nack(self, handle: str, refund: bool = True) -> bool:
        # a first-party handback is not a failed attempt (see
        # QueueBase.nack); janitor requeues after a CRASH never pass
        # here, and third-party force_release passes refund=False, so
        # crash deliveries keep counting toward the crash-loop bound.
        # The refund lands BEFORE the rename makes the task visible
        # again: while the claim file exists no other worker can
        # re-claim and bump, so this read-modify-write cannot overwrite
        # a newer delivery's count (decrement-after-rename raced
        # exactly that way).
        refunded = False
        if refund:
            count = self._read_count(handle)
            if count > 0:
                refunded = self._write_count(handle, count - 1)
        try:
            os.rename(os.path.join(self.claimed_dir, handle),
                      os.path.join(self.pending_dir, handle))
        except OSError:
            if refunded:  # the janitor beat us to it: the count stands
                self._bump_count(handle)
            return False
        return True

    def receive_count(self, handle: str) -> int:
        return self._read_count(handle)

    def dead_letter(self, handle: str, reason: str = "") -> None:
        claimed = os.path.join(self.claimed_dir, handle)
        try:
            with open(claimed) as f:
                body = f.read()
        except OSError:
            return  # lost the claim: someone else owns the task now
        entry = {"body": body, "reason": reason,
                 "receives": self._read_count(handle), "t": time.time()}
        tmp = os.path.join(self.dir, f".tmp-dead-{handle}")
        with open(tmp, "w") as f:
            json.dump(entry, f)
        os.rename(tmp, os.path.join(self.dead_dir, handle))
        self.delete(handle)

    def dead_letters(self) -> List[dict]:
        entries = []
        for name in sorted(os.listdir(self.dead_dir)):
            try:
                with open(os.path.join(self.dead_dir, name)) as f:
                    entries.append(self._present(json.load(f)))
            except (OSError, ValueError):
                continue
        return entries

    def requeue_dead(self) -> int:
        count = 0
        for name in sorted(os.listdir(self.dead_dir)):
            path = os.path.join(self.dead_dir, name)
            try:
                with open(path) as f:
                    entry = json.load(f)
            except (OSError, ValueError):
                continue
            # the stored body is the wire envelope; pack_task inside
            # send_messages is idempotent, so the trace id survives
            self.send_messages([entry["body"]])
            try:
                os.remove(path)
            except OSError:
                continue
            count += 1
        return count

    def stats(self) -> dict:
        self._requeue_expired()
        receives = 0
        for name in os.listdir(self.counts_dir):
            if name.startswith(".tmp-"):  # a writer died mid-stage
                continue
            receives += self._read_count(name)
        return {
            "pending": len(os.listdir(self.pending_dir)),
            "inflight": len(os.listdir(self.claimed_dir)),
            "dead": len(os.listdir(self.dead_dir)),
            "receives": receives,
        }

    def __len__(self) -> int:
        return len(os.listdir(self.pending_dir))


class SQSQueue(QueueBase):
    """AWS SQS backend (requires boto3 + credentials; not in this image).

    ``client`` injection exists for tests: the lifecycle/batch-send
    surfaces are exercised against a fake client without boto3."""

    def __init__(self, name: str, visibility_timeout: int = 1800,
                 client=None):
        if client is None:
            try:
                import boto3
            except ImportError as e:
                raise RuntimeError(
                    "sqs:// queues need boto3, which is not installed; "
                    "use file:// or memory:// queues instead"
                ) from e
            client = boto3.client("sqs")
        self.client = client
        self.name = name
        self.visibility_timeout = visibility_timeout
        resp = self.client.create_queue(
            QueueName=name,
            Attributes={"VisibilityTimeout": str(visibility_timeout)},
        )
        self.queue_url = resp["QueueUrl"]
        self._dead_url: Optional[str] = None
        self._receive_counts: Dict[str, int] = {}

    def _send_batch(self, entries: List[dict]) -> None:
        resp = self.client.send_message_batch(
            QueueUrl=self.queue_url, Entries=entries
        )
        failed = resp.get("Failed") or []
        if not failed:
            return
        # partial-batch failure is a *success* response carrying Failed
        # entries — dropping them silently loses tasks. Retry the failed
        # subset once (throttling is transient), then raise.
        failed_ids = {f["Id"] for f in failed}
        retry = [e for e in entries if e["Id"] in failed_ids]
        resp = self.client.send_message_batch(
            QueueUrl=self.queue_url, Entries=retry
        )
        failed = resp.get("Failed") or []
        if failed:
            raise IOError(
                f"SQS send_message_batch failed for {len(failed)} "
                f"message(s) after retry: "
                + "; ".join(
                    f"{f.get('Id')}: {f.get('Code')} {f.get('Message', '')}"
                    for f in failed
                )
            )

    def send_messages(self, bodies: List[str]) -> None:
        bodies = self._pack_bodies(bodies)
        for i in range(0, len(bodies), 10):  # SQS batch limit
            entries = [
                {"Id": str(j), "MessageBody": body}
                for j, body in enumerate(bodies[i : i + 10])
            ]
            self._send_batch(entries)

    def receive(self) -> Optional[Tuple[str, str]]:
        resp = self.client.receive_message(
            QueueUrl=self.queue_url, MaxNumberOfMessages=1,
            WaitTimeSeconds=20,
            AttributeNames=["ApproximateReceiveCount"],
        )
        messages = resp.get("Messages", [])
        if not messages:
            return None
        msg = messages[0]
        # transport integrity check (reference sqs_queue.py:95-100)
        expected = msg.get("MD5OfBody")
        if expected:
            import hashlib

            got = hashlib.md5(msg["Body"].encode()).hexdigest()
            if got != expected:
                raise IOError(
                    f"SQS body md5 mismatch: got {got}, expected {expected}"
                )
        handle = msg["ReceiptHandle"]
        try:
            self._receive_counts[handle] = int(
                (msg.get("Attributes") or {}).get("ApproximateReceiveCount", 0)
            )
        except (TypeError, ValueError):
            self._receive_counts[handle] = 0
        self._bodies = getattr(self, "_bodies", {})
        self._bodies[handle] = msg["Body"]  # wire format: dead-letter re-sends it
        body, trace_id = unpack_task(msg["Body"])
        self._note_receive(handle, trace_id)
        return handle, body

    def delete(self, handle: str) -> None:
        self.client.delete_message(QueueUrl=self.queue_url, ReceiptHandle=handle)
        self._receive_counts.pop(handle, None)
        getattr(self, "_bodies", {}).pop(handle, None)
        getattr(self, "_traces", {}).pop(handle, None)

    def renew(self, handle: str, timeout: Optional[float] = None) -> None:
        timeout = self.visibility_timeout if timeout is None else timeout
        self.client.change_message_visibility(
            QueueUrl=self.queue_url, ReceiptHandle=handle,
            VisibilityTimeout=int(timeout),
        )

    def nack(self, handle: str, refund: bool = True) -> bool:
        # SQS cannot decrement ApproximateReceiveCount: `refund` is
        # accepted for protocol compatibility but has no effect
        self.renew(handle, 0)
        return True

    def receive_count(self, handle: str) -> int:
        return self._receive_counts.get(handle, 0)

    def _dead_queue_url(self) -> str:
        if self._dead_url is None:
            # short nonzero visibility: dead_letters() below drains to
            # empty to list, so entries must go invisible between
            # receives (or the listing loop would never terminate) and
            # reappear shortly after
            resp = self.client.create_queue(
                QueueName=f"{self.name}-dead",
                Attributes={"VisibilityTimeout": "300"},
            )
            self._dead_url = resp["QueueUrl"]
        return self._dead_url

    def dead_letter(self, handle: str, reason: str = "") -> None:
        body = getattr(self, "_bodies", {}).get(handle)
        if body is None:
            return  # not a task this client received
        entry = {"body": body, "reason": reason,
                 "receives": self.receive_count(handle), "t": time.time()}
        self.client.send_message(
            QueueUrl=self._dead_queue_url(), MessageBody=json.dumps(entry)
        )
        self.delete(handle)

    def _drain_dead(self):
        while True:
            resp = self.client.receive_message(
                QueueUrl=self._dead_queue_url(), MaxNumberOfMessages=10,
                WaitTimeSeconds=0,
            )
            messages = resp.get("Messages", [])
            if not messages:
                return
            for msg in messages:
                try:
                    entry = json.loads(msg["Body"])
                except ValueError:
                    entry = {"body": msg["Body"], "reason": "", "receives": 0}
                yield msg["ReceiptHandle"], entry

    def dead_letters(self) -> List[dict]:
        # SQS has no non-destructive listing: receive-to-empty instead;
        # the entries go invisible for the dead queue's short visibility
        # timeout and then reappear (listing never loses them)
        return [self._present(entry) for _, entry in self._drain_dead()]

    def requeue_dead(self) -> int:
        count = 0
        for handle, entry in self._drain_dead():
            self.send_messages([entry["body"]])
            self.client.delete_message(
                QueueUrl=self._dead_queue_url(), ReceiptHandle=handle
            )
            count += 1
        return count

    def stats(self) -> dict:
        out = {"pending": None, "inflight": None, "dead": None,
               "receives": sum(self._receive_counts.values()) or None}
        try:
            resp = self.client.get_queue_attributes(
                QueueUrl=self.queue_url,
                AttributeNames=["ApproximateNumberOfMessages",
                                "ApproximateNumberOfMessagesNotVisible"],
            )
            attrs = resp.get("Attributes") or {}
            out["pending"] = int(attrs.get("ApproximateNumberOfMessages", 0))
            out["inflight"] = int(
                attrs.get("ApproximateNumberOfMessagesNotVisible", 0))
        except Exception:
            pass  # older fakes / restricted IAM: depth stays unknown
        try:
            resp = self.client.get_queue_attributes(
                QueueUrl=self._dead_queue_url(),
                AttributeNames=["ApproximateNumberOfMessages"],
            )
            out["dead"] = int((resp.get("Attributes") or {})
                              .get("ApproximateNumberOfMessages", 0))
        except Exception:
            pass
        return out


def open_queue(spec: str, visibility_timeout: float = 1800.0) -> QueueBase:
    """Open a queue from a ``scheme://name`` spec (bare paths mean file://)."""
    if spec.startswith("memory://"):
        return MemoryQueue.open(spec[len("memory://"):], visibility_timeout)
    if spec.startswith("sqs://"):
        return SQSQueue(spec[len("sqs://"):], int(visibility_timeout))
    if spec.startswith("file://"):
        spec = spec[len("file://"):]
    return FileQueue(spec, visibility_timeout)
