"""Spatial task dependency tree + global ID allocation service.

Parity target: reference distributed/restapi/ — ``SpatialTaskTree``
(task.py:88-186, binary spatial decomposition with ready/working/done
states and parent completion propagation) and the FastAPI global-ID server
(server.py:12-23). The reference leaves both unwired prototypes; here the
tree is a complete, serializable state machine usable as the scheduling
core of hierarchical jobs (e.g. agglomeration: children chunks must finish
before the parent merge runs), and the ID allocator is an in-process class
the optional HTTP server (see chunkflow_tpu/parallel/restapi.py) exposes.
"""
from __future__ import annotations

import json
import threading
from typing import Iterator, List, Optional

import numpy as np

from chunkflow_tpu.core.bbox import BoundingBox
from chunkflow_tpu.core.cartesian import to_cartesian

READY = "ready"
WORKING = "working on"
DONE = "done"


class SpatialTaskTree:
    """Binary spatial decomposition with bottom-up completion states.

    Leaves are atomic block tasks; an interior node becomes ``done`` only
    when both children are (its own merge step can then run). All state
    transitions are thread-safe so one tree can back a multi-worker
    scheduler.
    """

    def __init__(
        self,
        bbox: BoundingBox,
        block_size,
        parent: Optional["SpatialTaskTree"] = None,
        _lock: Optional[threading.RLock] = None,
    ):
        self.bbox = bbox
        self.block_size = tuple(to_cartesian(block_size))
        self.parent = parent
        self.state = READY
        self.left: Optional[SpatialTaskTree] = None
        self.right: Optional[SpatialTaskTree] = None
        self._lock = _lock if _lock is not None else threading.RLock()

        shape = bbox.shape
        blocks = [
            -(-int(shape[i]) // int(self.block_size[i])) for i in range(3)
        ]
        if max(blocks) <= 1:
            return  # leaf
        axis = int(np.argmax(blocks))
        left_blocks = blocks[axis] // 2
        split = int(bbox.start[axis]) + left_blocks * int(self.block_size[axis])

        left_stop = list(bbox.stop)
        left_stop[axis] = split
        self.left = SpatialTaskTree(
            BoundingBox(bbox.start, tuple(left_stop)),
            self.block_size, parent=self, _lock=self._lock,
        )
        right_start = list(bbox.start)
        right_start[axis] = split
        self.right = SpatialTaskTree(
            BoundingBox(tuple(right_start), bbox.stop),
            self.block_size, parent=self, _lock=self._lock,
        )

    # ---- structure -----------------------------------------------------
    @property
    def is_leaf(self) -> bool:
        return self.left is None and self.right is None

    @property
    def leaf_list(self) -> List["SpatialTaskTree"]:
        if self.is_leaf:
            return [self]
        return self.left.leaf_list + self.right.leaf_list

    def walk(self) -> Iterator["SpatialTaskTree"]:
        yield self
        if not self.is_leaf:
            yield from self.left.walk()
            yield from self.right.walk()

    def post_order(self) -> Iterator["SpatialTaskTree"]:
        """Children-before-parents traversal — the execution order of a
        serial hierarchical merge (segment/driver.py)."""
        if not self.is_leaf:
            yield from self.left.post_order()
            yield from self.right.post_order()
        yield self

    def find(self, bbox_string: str) -> Optional["SpatialTaskTree"]:
        """The node whose bbox renders as ``bbox_string`` (task bodies
        round-trip through bbox strings), or None."""
        for node in self.walk():
            if node.bbox.string == bbox_string:
                return node
        return None

    # ---- state machine -------------------------------------------------
    @property
    def is_done(self) -> bool:
        return self.state == DONE

    def set_state_working_on(self) -> None:
        with self._lock:
            self.state = WORKING

    def set_state_done(self, auto_propagate: bool = False) -> None:
        """Mark done. With ``auto_propagate`` (the reference's semantics,
        task.py:133-140), a parent whose children are both done becomes done
        itself — for trees whose interior nodes carry no merge work. Without
        it, interior nodes become *claimable* via next_ready_task once their
        children finish (hierarchical merge scheduling)."""
        with self._lock:
            self.state = DONE
            if (
                auto_propagate
                and self.parent is not None
                and self.parent.left.is_done
                and self.parent.right.is_done
            ):
                self.parent.set_state_done(auto_propagate=True)

    def next_ready_task(self) -> Optional["SpatialTaskTree"]:
        """Claim the next runnable node: a ready leaf, or a ready interior
        node whose children are both done (its merge step). Returns None
        when nothing is runnable right now."""
        with self._lock:
            for node in self.walk():
                if node.state != READY:
                    continue
                if node.is_leaf or (node.left.is_done and node.right.is_done):
                    node.set_state_working_on()
                    return node
            return None

    @property
    def all_done(self) -> bool:
        return all(node.is_done for node in self.walk())

    # ---- serialization -------------------------------------------------
    @property
    def json(self) -> str:
        return json.dumps(self.to_dict())

    def to_dict(self) -> dict:
        return {
            "state": self.state,
            "bbox": self.bbox.string,
            "block_size": list(self.block_size),
            "left": None if self.left is None else self.left.to_dict(),
            "right": None if self.right is None else self.right.to_dict(),
        }

    @classmethod
    def from_dict(
        cls, data: dict, parent: Optional["SpatialTaskTree"] = None
    ) -> "SpatialTaskTree":
        tree = cls.__new__(cls)
        tree.bbox = BoundingBox.from_string(data["bbox"])
        tree.block_size = tuple(data["block_size"])
        tree.state = data["state"]
        tree.parent = parent
        tree._lock = parent._lock if parent is not None else threading.RLock()
        tree.left = (
            cls.from_dict(data["left"], parent=tree) if data["left"] else None
        )
        tree.right = (
            cls.from_dict(data["right"], parent=tree) if data["right"] else None
        )
        return tree

    @classmethod
    def from_json(cls, text: str) -> "SpatialTaskTree":
        return cls.from_dict(json.loads(text))


class GlobalIdAllocator:
    """Hand out disjoint global segment-ID ranges (reference server.py:12-23,
    made thread-safe)."""

    def __init__(self, start_id: int = 0):
        self._next = int(start_id)
        self._lock = threading.Lock()

    def allocate(self, count: int) -> int:
        """Reserve ``count`` ids; returns the base id of the range."""
        assert count >= 0
        with self._lock:
            base = self._next
            self._next += int(count)
            return base

    @property
    def watermark(self) -> int:
        return self._next
