"""Chunk: an array (numpy or jax) + voxel offset/size + layer type.

The core data model (parity target: reference chunk/base.py — ndarray with
global-coordinate metadata, ufunc interop, cutout/save/blend geometry ops).
TPU-first differences from the reference:

- the payload may live on device as a ``jax.Array``; ``device()`` / ``host()``
  move it explicitly, and compute operators work in jnp either way;
- spatial geometry always refers to the trailing 3 (z, y, x) dims, so 3D
  (zyx) and 4D (czyx) chunks flow through the same code paths — fixing the
  reference's acknowledged 3D/4D wart (load_precomputed.py:78-82);
- ``blend`` (overlap-add) is jit-friendly: it is also exposed as a pure
  function in :mod:`chunkflow_tpu.ops.blend` used inside the fused inference
  loop; the method here is the host-side convenience.
"""
from __future__ import annotations

import os
from enum import Enum
from typing import Optional, Union

import numpy as np

from chunkflow_tpu.core.bbox import BoundingBox
from chunkflow_tpu.core.cartesian import Cartesian, to_cartesian


class LayerType(str, Enum):
    IMAGE = "image"
    SEGMENTATION = "segmentation"
    AFFINITY_MAP = "affinity_map"
    PROBABILITY_MAP = "probability_map"
    UNKNOWN = "unknown"


def _is_jax(array) -> bool:
    return type(array).__module__.startswith("jax")


def as_native_dtype(arr: np.ndarray) -> np.ndarray:
    """Widen non-native dtypes (ml_dtypes bfloat16 and friends, numpy
    kind 'V') to float32 for host file formats that cannot store them
    (HDF5/TIFF/PNG/NRRD writers share this rule)."""
    if arr.dtype.kind not in "biufc":
        return arr.astype(np.float32)
    return arr


class Chunk(np.lib.mixins.NDArrayOperatorsMixin):
    """An ndarray located in a global voxel coordinate system."""

    def __init__(
        self,
        array,
        voxel_offset=None,
        voxel_size=None,
        layer_type: Union[str, LayerType, None] = None,
    ):
        if isinstance(array, Chunk):
            voxel_offset = voxel_offset or array.voxel_offset
            voxel_size = voxel_size or array.voxel_size
            layer_type = layer_type or array.layer_type
            array = array.array
        if not _is_jax(array):
            array = np.asarray(array)
        if array.ndim not in (3, 4):
            raise ValueError(
                f"chunks are 3D (zyx) or 4D (czyx); got shape {array.shape}"
            )
        self.array = array
        self.voxel_offset = to_cartesian(voxel_offset) or Cartesian.zeros()
        self.voxel_size = to_cartesian(voxel_size) or Cartesian(1, 1, 1)
        if layer_type is None:
            layer_type = self._infer_layer_type(array)
        self.layer_type = LayerType(layer_type)

    @staticmethod
    def _infer_layer_type(array) -> LayerType:
        dtype = np.dtype(array.dtype)
        if array.ndim == 4 and array.shape[0] == 3 and dtype.kind == "f":
            return LayerType.AFFINITY_MAP
        if dtype == np.uint8 and array.ndim == 3:
            return LayerType.IMAGE
        if dtype.kind in "iu" and dtype.itemsize >= 4:
            return LayerType.SEGMENTATION
        if dtype.kind == "f":
            return LayerType.PROBABILITY_MAP
        return LayerType.UNKNOWN

    # ---- factories -----------------------------------------------------
    @classmethod
    def create(
        cls,
        size=(64, 64, 64),
        dtype=np.uint8,
        voxel_offset=(0, 0, 0),
        voxel_size=(1, 1, 1),
        pattern: str = "sin",
        nchannels: Optional[int] = None,
        seed: int = 0,
    ) -> "Chunk":
        """Synthetic test chunk: smooth ``sin`` product, ``random``, ``zero``."""
        size = tuple(to_cartesian(size))
        dtype = np.dtype(dtype)
        if pattern == "zero":
            arr = np.zeros(size, dtype=np.float32)
        elif pattern == "random":
            rng = np.random.default_rng(seed)
            arr = rng.random(size)
        elif pattern == "sin":
            z, y, x = np.meshgrid(
                # float64 linspace keeps the sin fixture bit-stable
                *[np.linspace(0, 4 * np.pi, s)  # graftlint: disable=GL004
                  for s in size], indexing="ij"
            )
            arr = (np.sin(z) * np.sin(y) * np.sin(x) + 1.0) / 2.0
        else:
            raise ValueError(f"unknown pattern {pattern!r}")
        if dtype.kind in "iu":
            arr = (arr * np.iinfo(dtype).max).astype(dtype)
        else:
            arr = arr.astype(dtype)
        if nchannels is not None:
            arr = np.broadcast_to(arr[None, ...], (nchannels,) + size).copy()
        return cls(arr, voxel_offset=voxel_offset, voxel_size=voxel_size)

    @classmethod
    def from_bbox(
        cls, bbox: BoundingBox, dtype=np.float32, nchannels=None, voxel_size=None
    ) -> "Chunk":
        shape = tuple(bbox.shape)
        if nchannels is not None:
            shape = (nchannels,) + shape
        return cls(
            np.zeros(shape, dtype=dtype),
            voxel_offset=bbox.start,
            voxel_size=voxel_size,
        )

    @classmethod
    def from_array(cls, array, bbox: BoundingBox, voxel_size=None) -> "Chunk":
        """Wrap an array whose spatial extent is ``bbox`` (reference
        chunk/base.py:98-106)."""
        if tuple(array.shape[-3:]) != tuple(bbox.shape):
            raise ValueError(
                f"array spatial shape {tuple(array.shape[-3:])} does not "
                f"match bbox shape {tuple(bbox.shape)}"
            )
        return cls(array, voxel_offset=bbox.start, voxel_size=voxel_size)

    # ---- array protocol -------------------------------------------------
    @property
    def shape(self):
        return self.array.shape

    @property
    def dtype(self):
        return self.array.dtype

    @property
    def ndim(self) -> int:
        return self.array.ndim

    @property
    def nchannels(self) -> int:
        return self.array.shape[0] if self.ndim == 4 else 1

    def __len__(self):
        return len(self.array)

    def __array__(self, dtype=None, copy=None):
        arr = np.asarray(self.array)
        return arr.astype(dtype) if dtype is not None else arr

    _HANDLED = (np.ndarray, int, float, complex, np.number, bool, list, tuple)

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        """numpy interop: ``chunk * mask``, ``chunk / 255`` keep metadata."""
        out = kwargs.get("out", ())
        for item in inputs + out:
            if not isinstance(item, self._HANDLED + (Chunk,)) and not _is_jax(item):
                return NotImplemented
        unwrapped = tuple(i.array if isinstance(i, Chunk) else i for i in inputs)
        if out:
            kwargs["out"] = tuple(
                o.array if isinstance(o, Chunk) else o for o in out
            )
        result = getattr(ufunc, method)(*unwrapped, **kwargs)
        if method == "at":
            return None
        if isinstance(result, tuple):
            return tuple(self._rewrap(r) for r in result)
        if out:
            return self._rewrap(kwargs["out"][0])
        return self._rewrap(result)

    def _rewrap(self, result):
        if (
            hasattr(result, "ndim")
            and result.ndim in (3, 4)
            and result.shape[-3:] == self.shape[-3:]
        ):
            return Chunk(
                result,
                voxel_offset=self.voxel_offset,
                voxel_size=self.voxel_size,
                layer_type=self.layer_type,
            )
        return result

    def __getitem__(self, key):
        return self.array[key]

    def __setitem__(self, key, value):
        if _is_jax(self.array):
            self.array = self.array.at[key].set(value)
        else:
            self.array[key] = value

    def __repr__(self) -> str:
        return (
            f"Chunk(shape={self.shape}, dtype={self.dtype}, "
            f"offset={tuple(self.voxel_offset)}, layer={self.layer_type.value})"
        )

    # ---- device movement -------------------------------------------------
    def device(self, sharding=None) -> "Chunk":
        """Move payload to the default accelerator (or given sharding).
        The payload ships in its RAW dtype — uint8 rides the wire at 1/4
        the bytes of float32; conversion happens on device inside the
        inference program (ops/pallas_gather.py). This is the staging
        seam: host-resident payloads count ``transfer/h2d_bytes``."""
        import jax

        if not self.is_on_device:
            from chunkflow_tpu.core import profiling

            profiling.note_h2d(np.asarray(self.array).nbytes)
        arr = jax.device_put(self.array, sharding)
        return self._with_array(arr)

    def host(self) -> "Chunk":
        return self._with_array(np.asarray(self.array))

    @property
    def is_on_device(self) -> bool:
        return _is_jax(self.array)

    def _with_array(self, array) -> "Chunk":
        return type(self)(
            array,
            voxel_offset=self.voxel_offset,
            voxel_size=self.voxel_size,
            layer_type=self.layer_type,
        )

    def astype(self, dtype) -> "Chunk":
        return self._with_array(self.array.astype(dtype))

    def clone(self) -> "Chunk":
        arr = self.array if _is_jax(self.array) else self.array.copy()
        return self._with_array(arr)

    # ---- layer predicates ------------------------------------------------
    @property
    def is_image(self) -> bool:
        return self.layer_type is LayerType.IMAGE

    @property
    def is_segmentation(self) -> bool:
        return self.layer_type is LayerType.SEGMENTATION

    @property
    def is_affinity_map(self) -> bool:
        return self.layer_type is LayerType.AFFINITY_MAP

    @property
    def is_probability_map(self) -> bool:
        return self.layer_type is LayerType.PROBABILITY_MAP

    # ---- geometry --------------------------------------------------------
    @property
    def voxel_stop(self) -> Cartesian:
        return self.voxel_offset + Cartesian.from_collection(self.shape[-3:])

    @property
    def bbox(self) -> BoundingBox:
        return BoundingBox(self.voxel_offset, self.voxel_stop)

    # reference-API surface (chunk/base.py:517-760): drop-in spellings
    @property
    def bounding_box(self) -> BoundingBox:
        return self.bbox

    @property
    def start(self) -> Cartesian:
        return self.voxel_offset

    @property
    def stop(self) -> Cartesian:
        return self.voxel_stop

    @property
    def size(self):
        return self.array.size

    @property
    def ndoffset(self) -> tuple:
        """Offset with the channel dim prepended for 4D chunks."""
        if self.ndim == 4:
            return (0,) + tuple(self.voxel_offset)
        return tuple(self.voxel_offset)

    @property
    def slices(self) -> tuple:
        """Global-coordinate slices of this chunk in the big volume."""
        return tuple(
            slice(o, o + s) for o, s in zip(self.ndoffset, self.shape)
        )

    @property
    def properties(self) -> dict:
        return {
            "voxel_offset": self.voxel_offset,
            "voxel_size": self.voxel_size,
            "layer_type": self.layer_type,
        }

    @properties.setter
    def properties(self, value: dict) -> None:
        self.set_properties(value)

    def set_properties(self, properties: dict) -> None:
        # None values (e.g. JSON nulls) leave the attribute unchanged —
        # nulling voxel_offset would defer a crash to bbox/slices
        if properties.get("voxel_offset") is not None:
            self.voxel_offset = to_cartesian(properties["voxel_offset"])
        if properties.get("voxel_size") is not None:
            self.voxel_size = to_cartesian(properties["voxel_size"])
        if properties.get("layer_type") is not None:
            self.layer_type = LayerType(properties["layer_type"])

    def fill(self, x) -> None:
        if _is_jax(self.array):
            import jax.numpy as jnp

            self.array = jnp.full_like(self.array, x)
        else:
            self.array.fill(x)

    def where(self, mask) -> tuple:
        """np.where in GLOBAL coordinates (reference chunk/base.py:739)."""
        mask = np.asarray(mask)
        if mask.shape != tuple(self.shape):
            raise ValueError(
                f"mask shape {mask.shape} != chunk shape {tuple(self.shape)}"
            )
        return tuple(
            i + o for i, o in zip(np.where(mask), self.ndoffset)
        )

    def ascontiguousarray(self) -> "Chunk":
        if not _is_jax(self.array):
            self.array = np.ascontiguousarray(self.array)
        return self

    def _rel_slices(self, bbox: BoundingBox) -> tuple:
        rel = bbox.translate(-self.voxel_offset)
        spatial = rel.slices
        if self.ndim == 4:
            return (slice(None),) + spatial
        return spatial

    def cutout(self, bbox: BoundingBox) -> "Chunk":
        """Extract a sub-chunk in global coordinates."""
        if not self.bbox.contains(bbox):
            raise ValueError(f"{bbox} not inside chunk bbox {self.bbox}")
        arr = self.array[self._rel_slices(bbox)]
        return type(self)(
            arr,
            voxel_offset=bbox.start,
            voxel_size=self.voxel_size,
            layer_type=self.layer_type,
        )

    def save(self, patch: "Chunk") -> None:
        """Overwrite the region covered by ``patch`` (global coords)."""
        inter = self.bbox.intersection(patch.bbox)
        if not inter.is_valid():
            return
        src = patch.cutout(inter)
        sl = self._rel_slices(inter)
        value = src.array.astype(self.dtype)
        if _is_jax(self.array):
            self.array = self.array.at[sl].set(value)
        else:
            self.array[sl] = value

    def blend(self, patch: "Chunk") -> None:
        """Overlap-add ``patch`` into this chunk (global coords)."""
        inter = self.bbox.intersection(patch.bbox)
        if not inter.is_valid():
            return
        src = patch.cutout(inter)
        sl = self._rel_slices(inter)
        value = src.array.astype(self.dtype)
        if _is_jax(self.array):
            self.array = self.array.at[sl].add(value)
        else:
            self.array[sl] += value

    def add_overlap(self, other: "Chunk") -> None:
        """Sum the overlapping region of ``other`` into this chunk
        (reference chunk/base.py:750)."""
        self.blend(other)

    def shrink(self, size) -> "Chunk":
        """Trim voxels from the faces; ``size`` is 3 symmetric or 6
        (-z,-y,-x,+z,+y,+x) amounts (reference chunk/base.py:630-646)."""
        size = tuple(int(s) for s in size)
        if len(size) == 3:
            size = size + size
        if len(size) != 6:
            raise ValueError(f"need 3 or 6 elements, got {len(size)}")
        if any(s < 0 for s in size):
            raise ValueError(f"shrink amounts must be non-negative: {size}")
        z, y, x = self.shape[-3:]
        if size[0] + size[3] >= z or size[1] + size[4] >= y or \
                size[2] + size[5] >= x:
            raise ValueError(
                f"shrink {size} consumes the whole extent {(z, y, x)}"
            )
        arr = self.array[
            ...,
            size[0]:z - size[3],
            size[1]:y - size[4],
            size[2]:x - size[5],
        ]
        return type(self)(
            arr,
            voxel_offset=self.voxel_offset + Cartesian.from_collection(size[:3]),
            voxel_size=self.voxel_size,
            layer_type=self.layer_type,
        )

    def crop_margin(self, margin) -> "Chunk":
        """Shrink symmetrically by ``margin`` voxels per face."""
        margin = to_cartesian(margin)
        if margin == Cartesian.zeros():
            return self
        return self.cutout(self.bbox.adjust(-margin))

    def pad_to(self, shape, mode: str = "constant") -> "Chunk":
        """Pad (at the stop side) so spatial dims reach ``shape``."""
        target = tuple(to_cartesian(shape))
        current = self.shape[-3:]
        pad = [(0, t - c) for t, c in zip(target, current)]
        if all(p == (0, 0) for p in pad):
            return self
        if any(p[1] < 0 for p in pad):
            raise ValueError(f"cannot pad {current} down to {target}")
        if self.ndim == 4:
            pad = [(0, 0)] + pad
        arr = np.pad(np.asarray(self.array), pad, mode=mode)
        return self._with_array(arr)

    def transpose(self, only_spatial: bool = True) -> "Chunk":
        """Reverse spatial axis order (zyx <-> xyz)."""
        if self.ndim == 4:
            arr = self.array.transpose(0, 3, 2, 1) if only_spatial else self.array.transpose(3, 2, 1, 0)
        else:
            arr = self.array.transpose(2, 1, 0)
        return type(self)(
            arr,
            voxel_offset=Cartesian(*reversed(self.voxel_offset)),
            voxel_size=Cartesian(*reversed(self.voxel_size)),
            layer_type=self.layer_type,
        )

    def squeeze_channel(self) -> "Chunk":
        if self.ndim == 3:
            return self
        if self.shape[0] != 1:
            raise ValueError(f"cannot squeeze {self.shape[0]} channels")
        return self._with_array(self.array[0])

    # ---- analytics / transforms -----------------------------------------
    def all_zero(self) -> bool:
        if _is_jax(self.array):
            # reduce on device: only the scalar crosses D2H (np.asarray
            # here would pull the whole chunk over the link — on the
            # tunneled chip that transfer dwarfs the reduction)
            import jax.numpy as jnp

            return not bool(jnp.any(self.array))
        return not bool(np.any(self.array))

    def min(self):
        return self.array.min()

    def max(self):
        return self.array.max()

    def threshold(self, threshold: float) -> "Chunk":
        from chunkflow_tpu.ops import threshold as _threshold

        return _threshold.threshold(self, threshold)

    def connected_component(
        self, threshold: float = 0.5, connectivity: int = 26,
        device: bool = False,
    ) -> "Chunk":
        from chunkflow_tpu.ops import connected_components as _cc

        return _cc.connected_components(
            self, threshold=threshold, connectivity=connectivity,
            device=device,
        )

    def channel_voting(self) -> "Chunk":
        from chunkflow_tpu.ops import voting

        return voting.channel_voting(self)

    def mask_using_last_channel(self, threshold: float = 0.3) -> "Chunk":
        from chunkflow_tpu.ops import voting

        return voting.mask_using_last_channel(self, threshold=threshold)

    def maskout(self, mask: "Chunk") -> "Chunk":
        from chunkflow_tpu.ops import mask as _mask

        return _mask.maskout(self, mask)

    def validate(self) -> bool:
        """Detect black-box corruption by template matching
        (reference chunk/validate.py:6-74)."""
        from chunkflow_tpu.chunk.validate import validate_by_template_matching

        return validate_by_template_matching(np.asarray(self.array))

    def gaussian_filter_2d(self, sigma: float = 1.0) -> "Chunk":
        from chunkflow_tpu.ops import filters

        return filters.gaussian_filter_2d(self, sigma=sigma)

    # ---- I/O -------------------------------------------------------------
    def to_h5(
        self,
        path: str,
        compression: str = "gzip",
        with_unique: bool = False,
        chunk_size=None,
        with_offset: bool = True,
    ) -> str:
        import h5py

        if not path.endswith(".h5"):
            path = os.path.join(path, f"{self.bbox.string}.h5")
        with h5py.File(path, "w") as f:
            # HDF5 has no bfloat16: h5py would store opaque |V2 bytes
            arr = as_native_dtype(np.asarray(self.array))
            chunks = None
            if chunk_size is not None:
                chunks = tuple(chunk_size)
                if arr.ndim == 4 and len(chunks) == 3:
                    chunks = (arr.shape[0],) + chunks
                chunks = tuple(min(c, s) for c, s in zip(chunks, arr.shape))
            f.create_dataset(
                "main", data=arr, compression=compression, chunks=chunks
            )
            if with_offset:
                f.create_dataset("voxel_offset", data=self.voxel_offset.vec)
            f.create_dataset("voxel_size", data=self.voxel_size.vec)
            f.attrs["layer_type"] = self.layer_type.value
            if with_unique and self.is_segmentation:
                f.create_dataset(
                    "unique_nonzeros",
                    data=np.unique(np.asarray(self.array)[np.asarray(self.array) > 0]),
                )
        return path

    @classmethod
    def from_h5(
        cls,
        path: str,
        dataset_path: str = "main",
        voxel_offset=None,
        voxel_size=None,
        bbox: Optional[BoundingBox] = None,
        dtype=None,
        channels=None,
    ) -> "Chunk":
        import h5py

        with h5py.File(path, "r") as f:
            if voxel_offset is None and "voxel_offset" in f:
                voxel_offset = Cartesian(*f["voxel_offset"][()].tolist())
            if voxel_size is None and "voxel_size" in f:
                voxel_size = Cartesian(*f["voxel_size"][()].tolist())
            layer_type = f.attrs.get("layer_type", None)
            dset = f[dataset_path]
            if bbox is not None:
                offset = to_cartesian(voxel_offset) or Cartesian.zeros()
                rel = bbox.translate(-offset)
                sl = rel.slices
                if dset.ndim == 4:
                    sl = (slice(None),) + sl
                arr = dset[sl]
                voxel_offset = bbox.start
            else:
                arr = dset[()]
        if channels is not None and arr.ndim == 4:
            if isinstance(channels, str):
                idx = [int(c) for c in channels.split(",") if c.strip()]
            else:
                idx = [int(c) for c in channels]
            arr = arr[idx]
        if dtype is not None:
            arr = arr.astype(dtype)
        return cls(
            arr,
            voxel_offset=voxel_offset,
            voxel_size=voxel_size,
            layer_type=layer_type,
        )

    def to_tif(self, path: str, compression: str = "zlib") -> str:
        from chunkflow_tpu.volume import io_tif

        return io_tif.write_tif(self, path, compression=compression)

    def with_voxel_size(self, voxel_size) -> "Chunk":
        """Same data, different physical voxel size."""
        out = self._with_array(self.array)
        out.voxel_size = Cartesian.from_collection(voxel_size)
        return out

    @classmethod
    def from_tif(cls, path: str, voxel_offset=None, voxel_size=None, dtype=None):
        from chunkflow_tpu.volume import io_tif

        return io_tif.read_tif(
            path, voxel_offset=voxel_offset, voxel_size=voxel_size, dtype=dtype
        )

    def to_npy(self, path: str) -> str:
        np.save(path, np.asarray(self.array))
        return path

    @classmethod
    def from_npy(cls, path: str, voxel_offset=None, voxel_size=None) -> "Chunk":
        return cls(np.load(path), voxel_offset=voxel_offset, voxel_size=voxel_size)
