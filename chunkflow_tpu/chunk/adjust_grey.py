"""Grey-value adjustment library (parity: reference chunk/image/adjust_grey.py).

Same surface as the reference — clip_percentile, window_level, rescale,
normalize (meanstd / fill), adjust_gamma, grey_augment, normalize_shang —
but vectorized numpy/jnp instead of cv2 histograms and Python while-loops
(adjust_grey.py:12-33 builds the cumulative histogram with a loop; here it
is one ``np.bincount`` + ``searchsorted``). These run on the host pipeline
path; the hot inference path normalizes on device inside the fused engine.
"""
# Host-side grey-level statistics (histogram CDFs, mean/std) accumulate
# in float64 on purpose.  # graftlint: disable-file=GL004
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

__all__ = [
    "clip_percentile",
    "window_level",
    "rescale",
    "get_voxels_for_stats",
    "normalize",
    "adjust_gamma",
    "grey_augment",
    "normalize_shang",
]


def clip_percentile(
    img: np.ndarray,
    percentile_low: float = 0.01,
    percentile_high: float = 0.01,
) -> np.ndarray:
    """Histogram-based percentile contrast stretch for uint8 images.

    Finds the lowest/highest bins holding the clip fractions of voxels
    (reference adjust_grey.py:12-33) and linearly stretches the remaining
    range back to [0, 255].
    """
    assert img.dtype == np.uint8
    hist = np.bincount(img.ravel(), minlength=256).astype(np.float64)
    total = img.size
    cdf = np.cumsum(hist)
    # first bin where the cumulative count reaches the low fraction; the
    # reference's while-loop post-increments, landing one past the bin that
    # crossed the threshold
    lower_bound = int(np.searchsorted(cdf, percentile_low * total)) + 1
    rcdf = np.cumsum(hist[::-1])
    upper_bound = 254 - int(np.searchsorted(rcdf, percentile_high * total))
    alpha = 255.0 / max(upper_bound - lower_bound, 1)
    beta = -lower_bound * alpha
    return np.clip(img * alpha + beta, 0, 255).astype(np.uint8)


def window_level(img: np.ndarray, half_window: float, level: float) -> np.ndarray:
    """Map level -> 0 and level +/- half_window -> +/-1, in place."""
    if half_window <= 0:
        raise ValueError("half_window must be positive")
    img -= level
    img *= 1.0 / half_window
    return img


def rescale(img: np.ndarray, old_range, new_range=(-1, 1)) -> np.ndarray:
    """Linearly remap values in old_range to new_range, in place."""
    if np.array_equal(old_range, new_range):
        return img
    img -= old_range[0]
    img *= (new_range[1] - new_range[0]) / (old_range[1] - old_range[0])
    img += new_range[0]
    return img


def get_voxels_for_stats(
    img: np.ndarray, min_max_invalid: Sequence[bool] = (True, True)
) -> np.ndarray:
    """Voxels used for statistics, excluding the (possibly padded/invalid)
    extreme values when requested (reference adjust_grey.py:63-85)."""
    min_invalid, max_invalid = min_max_invalid
    mask = None
    if min_invalid:
        mask = img != np.min(img)
    if max_invalid:
        m = img != np.max(img)
        mask = m if mask is None else np.logical_and(mask, m)
    return img if mask is None else img[mask]


def normalize(
    img: np.ndarray,
    method,
    target_scale=(-1, 1),
    min_max_invalid: Sequence[bool] = (True, True),
    do_clipping: bool = False,
    make_copy: bool = True,
) -> np.ndarray:
    """Float normalization: 'meanstd' (z-score) or 'fill' (min/max rescale),
    statistics drawn from valid voxels only."""
    if img.size == 0:
        return np.copy(img) if make_copy else img
    stat_img = get_voxels_for_stats(img, min_max_invalid=min_max_invalid)
    if stat_img.size == 0:
        # blank / near-constant input (e.g. a padded all-255 section): the
        # invalid-extreme filter removed everything. Fall back to all
        # voxels so clipping still enforces the output contract; the
        # degenerate-range guards below skip the actual rescale/z-score.
        stat_img = img
    if make_copy:
        img = np.copy(img)

    if method in (1, "meanstd"):
        sd = np.std(stat_img)
        if sd > 0:
            img -= np.mean(stat_img)
            img /= sd
        if do_clipping:
            np.clip(img, -2, 2, img)
    elif method in (2, "fill"):
        mi = np.min(stat_img)
        ma = np.max(stat_img)
        if ma > mi:
            img = rescale(img, (mi, ma), new_range=target_scale)
        if do_clipping:
            np.clip(img, *target_scale, img)
    else:
        raise ValueError(f"unknown normalization method: {method}")
    return img


def adjust_gamma(img: np.ndarray, gamma: float, auto_rescale: bool = False) -> np.ndarray:
    """Gamma adjustment on [0, 1] float images, in place."""
    if auto_rescale:
        mi, ma = np.min(img), np.max(img)
        if mi != ma:
            img -= mi
            img /= ma - mi
    np.clip(img, 0, 1, img)
    img **= gamma
    return img


def grey_augment(
    img: np.ndarray,
    max_level_change: float = 0.15,
    max_window_change: float = 0.15,
    max_log2gamma_change: float = 1.0,
    level_prob: float = 1.0,
    window_prob: float = 0.8,
    gamma_prob: float = 0.3,
    value_range=(-1, 1),
    make_copy: bool = True,
    rng: Optional[np.random.Generator] = None,
) -> np.ndarray:
    """Random window/level + gamma augmentation (training-time intensity
    augmentation, reference adjust_grey.py:154-207)."""
    if rng is None:
        rng = np.random.default_rng()
    if make_copy:
        img = np.copy(img)

    change_level = rng.random() < level_prob
    change_window = rng.random() < window_prob
    change_gamma = rng.random() < gamma_prob

    level = (value_range[0] + value_range[1]) / 2
    half_window = (value_range[1] - value_range[0]) / 2
    log2gamma = 0.0
    if change_level:
        level += 2 * (rng.random() - 0.5) * max_level_change
    if change_window:
        half_window += 2 * (rng.random() - 0.5) * max_window_change / 2
    if change_gamma:
        log2gamma += 2 * (rng.random() - 0.5) * max_log2gamma_change

    if change_level or change_window or change_gamma:
        target_range = (0, 1) if change_gamma else value_range
        img = rescale(
            img, (level - half_window, level + half_window), target_range
        )
        np.clip(img, *target_range, img)
        if change_gamma:
            img = adjust_gamma(img, 2.0 ** log2gamma)
            img = rescale(img, (0, 1), value_range)
    return img


def normalize_shang(
    image: np.ndarray,
    nominalmin: Optional[float],
    nominalmax: Optional[float],
    clipvalues: bool,
) -> np.ndarray:
    """Shang's slice-wise min/max normalization to a nominal range
    (reference adjust_grey.py:209-255): per z-section 'fill' rescale with
    invalid-extreme exclusion; returns float32."""
    original_dtype = image.dtype
    arr = np.asarray(image).astype(np.float32)

    nbits = np.dtype(original_dtype).itemsize * 8
    if nominalmin is None:
        nominalmin = 0.0
    if nominalmax is None:
        nominalmax = float(2 ** nbits - 1)
    assert nominalmin < nominalmax

    for zz in range(arr.shape[0]):
        normalize(
            arr[zz, :, :],
            "fill",
            target_scale=(nominalmin, nominalmax),
            min_max_invalid=(True, True),
            do_clipping=clipvalues,
            make_copy=False,
        )
    return arr
