from chunkflow_tpu.chunk.base import Chunk, LayerType
from chunkflow_tpu.chunk.image import Image
from chunkflow_tpu.chunk.affinity_map import AffinityMap
from chunkflow_tpu.chunk.segmentation import Segmentation
from chunkflow_tpu.chunk.probability_map import ProbabilityMap

__all__ = [
    "Chunk",
    "LayerType",
    "Image",
    "AffinityMap",
    "Segmentation",
    "ProbabilityMap",
]
