"""Affinity map chunk (parity: reference chunk/affinity_map/base.py)."""
from __future__ import annotations

import numpy as np

from chunkflow_tpu.chunk.base import Chunk, LayerType


class AffinityMap(Chunk):

    """3-channel float 4D chunk of zyx boundary affinities."""

    @classmethod
    def from_chunk(cls, chunk: Chunk) -> "AffinityMap":
        # Chunk.__init__ copies all metadata when given a Chunk
        return cls(chunk)

    def __init__(self, array, **kwargs):
        kwargs.setdefault("layer_type", LayerType.AFFINITY_MAP)
        super().__init__(array, **kwargs)
        if self.ndim != 4:
            raise ValueError("affinity maps are 4D (c, z, y, x)")

    def quantize(self, mode: str = "xy") -> Chunk:
        """Compress to a uint8 grayscale thumbnail chunk.

        ``xy``: mean of the y and x affinity channels; ``z``: z channel only.
        """
        arr = np.asarray(self.array)
        if mode == "xy":
            gray = arr[1:3].mean(axis=0)
        elif mode == "z":
            gray = arr[0]
        else:
            raise ValueError(f"unknown quantize mode {mode!r}")
        gray = np.clip(gray * 255.0, 0, 255).astype(np.uint8)
        return Chunk(
            gray,
            voxel_offset=self.voxel_offset,
            voxel_size=self.voxel_size,
            layer_type=LayerType.IMAGE,
        )
