"""Affinity map chunk (parity: reference chunk/affinity_map/base.py)."""
from __future__ import annotations

import numpy as np

from chunkflow_tpu.chunk.base import Chunk, LayerType


class AffinityMap(Chunk):

    """3-channel float 4D chunk of zyx boundary affinities."""

    @classmethod
    def from_chunk(cls, chunk: Chunk) -> "AffinityMap":
        # Chunk.__init__ copies all metadata when given a Chunk
        return cls(chunk)

    @classmethod
    def from_segmentation(
        cls,
        seg,
        inside: float = 1.0,
        boundary: float = 0.0,
        **kwargs,
    ) -> "AffinityMap":
        """Ground-truth affinity graph of a segmentation.

        Channel ``c`` at voxel (z, y, x) holds the edge to its neighbor
        one step NEGATIVE along axis ``c`` — the zyx convention shared by
        the native watershed (native/src/watershed.cpp) and the
        reference's affinity outputs. An edge scores ``inside`` iff both
        endpoints share the same nonzero label, else ``boundary``;
        label 0 is background and never connects. Leading-plane edges
        (no neighbor in range) score ``inside`` (self-edge). Used for
        training-target generation and as the analytic fixture behind
        the agglomeration quality harness and watershed bench.
        """
        if isinstance(seg, Chunk):
            kwargs.setdefault("voxel_offset", seg.voxel_offset)
            kwargs.setdefault("voxel_size", seg.voxel_size)
            seg = seg.array
        arr = np.asarray(seg)
        if arr.ndim != 3:
            raise ValueError(f"need a 3D (z, y, x) segmentation, got "
                             f"{arr.shape}")
        aff = np.full((3,) + arr.shape, np.float32(inside), np.float32)
        for c in range(3):
            sl_a = [slice(None)] * 3
            sl_b = [slice(None)] * 3
            sl_a[c] = slice(1, None)
            sl_b[c] = slice(0, -1)
            a, b = arr[tuple(sl_a)], arr[tuple(sl_b)]
            aff[(c, *sl_a)] = np.where(
                (a == b) & (a != 0), np.float32(inside), np.float32(boundary)
            )
        return cls(aff, **kwargs)

    def __init__(self, array, **kwargs):
        kwargs.setdefault("layer_type", LayerType.AFFINITY_MAP)
        super().__init__(array, **kwargs)
        if self.ndim != 4:
            raise ValueError("affinity maps are 4D (c, z, y, x)")

    def quantize(self, mode: str = "xy") -> Chunk:
        """Compress to a uint8 grayscale thumbnail chunk.

        ``xy``: mean of the y and x affinity channels; ``z``: z channel only.
        """
        arr = np.asarray(self.array)
        if mode == "xy":
            gray = arr[1:3].mean(axis=0, dtype=np.float32)
        elif mode == "z":
            gray = arr[0]
        else:
            raise ValueError(f"unknown quantize mode {mode!r}")
        gray = np.clip(gray * 255.0, 0, 255).astype(np.uint8)
        return Chunk(
            gray,
            voxel_offset=self.voxel_offset,
            voxel_size=self.voxel_size,
            layer_type=LayerType.IMAGE,
        )
