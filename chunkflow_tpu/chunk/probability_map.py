"""Probability map chunk (parity: reference chunk/probability_map.py).

Peak detection replaces skimage.peak_local_max with a
scipy.ndimage.maximum_filter non-max suppression.
"""
from __future__ import annotations

import numpy as np
from scipy import ndimage

from chunkflow_tpu.chunk.base import Chunk, LayerType


class ProbabilityMap(Chunk):
    def __init__(self, array, **kwargs):
        kwargs.setdefault("layer_type", LayerType.PROBABILITY_MAP)
        super().__init__(array, **kwargs)

    @classmethod
    def from_chunk(cls, chunk: Chunk) -> "ProbabilityMap":
        return cls(
            chunk.array,
            voxel_offset=chunk.voxel_offset,
            voxel_size=chunk.voxel_size,
        )

    def detect_points(
        self,
        min_distance: int = 15,
        threshold_rel: float = 0.3,
    ):
        """Local maxima in global voxel coordinates with confidences.

        Returns (points Nx3 int array in global zyx, confidences N floats).
        """
        arr = np.asarray(self.array)
        if arr.ndim == 4:
            arr = arr[0]
        size = 2 * min_distance + 1
        local_max = ndimage.maximum_filter(arr, size=size, mode="constant")
        threshold = threshold_rel * float(arr.max()) if arr.size else 0.0
        peaks = np.logical_and(arr == local_max, arr > threshold)
        coords = np.argwhere(peaks)
        confidences = (arr[tuple(coords.T)] if coords.size
                       else np.zeros((0,), dtype=arr.dtype))
        coords = coords + self.voxel_offset.vec
        return coords.astype(np.int64), confidences
