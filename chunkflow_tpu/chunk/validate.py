"""Black-box corruption detector (parity: reference chunk/validate.py:6-74).

Detects 3D "black boxes" (zeroed cuboids from failed reads) by matching
6 axis-aligned step-edge templates (7x7x2 and rotations, one half true)
against the binarized image; >=5 orientations each matching >=100 positions
at NCC > 0.9 means a box with visible faces on both sides in every axis —
the chunk is invalid.

skimage.feature.match_template is replaced by a native normalized
cross-correlation built from three FFT convolutions (scipy.signal);
identical scores up to float tolerance.
"""
# Normalized cross-correlation accumulates in float64 on purpose: the
# FFT-based sums cancel catastrophically in float32.
# graftlint: disable-file=GL004
from __future__ import annotations

import numpy as np
from scipy.signal import fftconvolve

SCORE_THRESHOLD = 0.9
NUM_THRESHOLD = 100


def match_template_ncc(img: np.ndarray, template: np.ndarray) -> np.ndarray:
    """Normalized cross-correlation of a small template over ``img``
    ('valid' positions only), matching skimage.feature.match_template."""
    img = np.ascontiguousarray(img, dtype=np.float64)
    template = np.ascontiguousarray(template, dtype=np.float64)
    n = template.size
    t_mean = template.mean()
    t_ssd = ((template - t_mean) ** 2).sum()

    flipped = template[::-1, ::-1, ::-1]
    cross = fftconvolve(img, flipped, mode="valid")
    ones = np.ones_like(template)
    s1 = fftconvolve(img, ones, mode="valid")
    s2 = fftconvolve(img ** 2, ones, mode="valid")

    numerator = cross - s1 * t_mean
    img_var = np.maximum(s2 - s1 ** 2 / n, 0.0)
    denominator = np.sqrt(img_var * t_ssd)
    out = np.zeros_like(numerator)
    np.divide(numerator, denominator, out=out, where=denominator > 1e-12)
    return out


def _step_templates():
    for axis in range(3):
        for side in range(2):
            shape = [7, 7, 7]
            shape[axis] = 2
            template = np.zeros(shape, dtype=bool)
            index = [slice(None)] * 3
            index[axis] = side
            template[tuple(index)] = True
            yield template


def validate_by_template_matching(img: np.ndarray) -> bool:
    """True if the chunk looks valid, False if a black box is detected."""
    img = np.asarray(img)
    if img.ndim == 4:
        img = img[0]
    if np.issubdtype(img.dtype, np.floating):
        # float images lack the exact-zero box signature; skip validation
        return True
    binary = img.astype(bool)
    if binary.shape < (2, 7, 7):
        return True

    evidence = 0
    for template in _step_templates():
        if any(s < t for s, t in zip(binary.shape, template.shape)):
            continue
        score = match_template_ncc(binary, template)
        if np.count_nonzero(score > SCORE_THRESHOLD) > NUM_THRESHOLD:
            evidence += 1
    return evidence <= 4
