"""Grayscale image chunk (parity: reference chunk/image/base.py).

Contrast normalization is reimplemented as a vectorized per-section
percentile stretch (jnp-friendly) rather than the reference's pre-computed
lookup-table files; the lookup-table path can be added when histogram
sidecar files are in play.
"""
from __future__ import annotations

import numpy as np

from chunkflow_tpu.chunk.base import Chunk, LayerType


class Image(Chunk):
    def __init__(self, array, **kwargs):
        kwargs.setdefault("layer_type", LayerType.IMAGE)
        super().__init__(array, **kwargs)

    @classmethod
    def from_chunk(cls, chunk: Chunk) -> "Image":
        return cls(
            chunk.array,
            voxel_offset=chunk.voxel_offset,
            voxel_size=chunk.voxel_size,
        )

    def inference(self, inferencer) -> Chunk:
        """Run patch-wise convnet inference over this image."""
        return inferencer(self)

    def normalize_shang(
        self,
        nominalmin=None,
        nominalmax=None,
        clipvalues: bool = False,
    ) -> "Image":
        """Slice-wise min/max normalization to a nominal range, Shang's
        method (reference chunk/image/adjust_grey.py:209-255)."""
        from chunkflow_tpu.chunk.adjust_grey import normalize_shang

        out = normalize_shang(
            np.asarray(self.array), nominalmin, nominalmax, clipvalues
        )
        return Image(
            out, voxel_offset=self.voxel_offset, voxel_size=self.voxel_size
        )

    def normalize_contrast(
        self,
        lower_clip_fraction: float = 0.01,
        upper_clip_fraction: float = 0.01,
        minval: int = 1,
        maxval: int = 255,
        per_section: bool = True,
    ) -> "Image":
        """Percentile contrast stretch, per z-section by default.

        Mirrors the intent of the reference's histogram-lookup normalization
        (image/base.py:93-133): clip the darkest/brightest fractions and
        stretch the remainder to [minval, maxval].
        """
        # stays on device when the payload is already HBM-resident
        if self.is_on_device:
            import jax.numpy as xp
        else:
            xp = np
        arr = xp.asarray(self.array).astype(xp.float32)
        lo_q = lower_clip_fraction * 100.0
        hi_q = 100.0 - upper_clip_fraction * 100.0
        # per z-section (and per channel for 4D): reduce over the trailing
        # (y, x) axes; otherwise over the whole array
        axes = (-2, -1) if per_section else tuple(range(-3, 0))
        lows = xp.percentile(arr, lo_q, axis=axes, keepdims=True)
        highs = xp.percentile(arr, hi_q, axis=axes, keepdims=True)
        scale = (maxval - minval) / xp.maximum(highs - lows, 1e-6)
        out = xp.clip((arr - lows) * scale + minval, minval, maxval)
        dtype = self.dtype if np.dtype(self.dtype).kind in "iu" else np.uint8
        return Image(
            out.astype(dtype),
            voxel_offset=self.voxel_offset,
            voxel_size=self.voxel_size,
        )
