"""Segmentation chunk (parity: reference chunk/segmentation.py).

Evaluation metrics (Rand index, adjusted Rand, variation of information,
Fowlkes–Mallows) are computed from a sparse contingency table — the same
math gala/the reference use, implemented directly on scipy.sparse.
Remap/renumber replace the fastremap C++ wheel with vectorized numpy
(np.unique-based); see ops/remap.py.
"""
# Rand/VOI evaluation metrics accumulate pair counts in float64 on
# purpose (billions of voxel pairs overflow float32 precision).
# graftlint: disable-file=GL004
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from chunkflow_tpu.chunk.base import Chunk, LayerType


class Segmentation(Chunk):
    def __init__(self, array, **kwargs):
        kwargs.setdefault("layer_type", LayerType.SEGMENTATION)
        super().__init__(array, **kwargs)

    @classmethod
    def from_chunk(cls, chunk: Chunk) -> "Segmentation":
        return cls(
            chunk.array,
            voxel_offset=chunk.voxel_offset,
            voxel_size=chunk.voxel_size,
        )

    # ---- evaluation ------------------------------------------------------
    def evaluate(self, groundtruth) -> dict:
        """Clustering metrics of self vs groundtruth over nonzero voxels."""
        from scipy import sparse

        seg = np.asarray(self.array).ravel()
        if isinstance(groundtruth, Chunk):
            gt = np.asarray(groundtruth.array).ravel()
        else:
            gt = np.asarray(groundtruth).ravel()
        keep = np.logical_and(seg > 0, gt > 0)
        seg = seg[keep]
        gt = gt[keep]
        n = seg.size
        if n == 0:
            return dict(rand_index=1.0, adjusted_rand_index=1.0,
                        voi_split=0.0, voi_merge=0.0, fowlkes_mallows=1.0)

        _, seg_ids = np.unique(seg, return_inverse=True)
        _, gt_ids = np.unique(gt, return_inverse=True)
        cont = sparse.coo_matrix(
            (np.ones(n, dtype=np.float64), (seg_ids, gt_ids))
        ).tocsr()

        # pair counts
        sum_all = float((cont.data ** 2).sum())
        rows = np.asarray(cont.sum(axis=1)).ravel()
        cols = np.asarray(cont.sum(axis=0)).ravel()
        sum_rows = float((rows ** 2).sum())
        sum_cols = float((cols ** 2).sum())
        n_pairs = n * (n - 1) / 2.0
        a_pairs = (sum_all - n) / 2.0            # same in both
        row_pairs = (sum_rows - n) / 2.0
        col_pairs = (sum_cols - n) / 2.0
        b_pairs = row_pairs - a_pairs            # same in seg only
        c_pairs = col_pairs - a_pairs            # same in gt only
        d_pairs = n_pairs - row_pairs - col_pairs + a_pairs

        rand_index = (a_pairs + d_pairs) / n_pairs if n_pairs else 1.0
        expected = row_pairs * col_pairs / n_pairs if n_pairs else 0.0
        max_index = (row_pairs + col_pairs) / 2.0
        ari = (
            (a_pairs - expected) / (max_index - expected)
            if max_index != expected
            else 1.0
        )
        fm = (
            a_pairs / np.sqrt(row_pairs * col_pairs)
            if row_pairs > 0 and col_pairs > 0
            else 1.0
        )

        # variation of information
        p = cont.data / n
        pr = rows / n
        pc = cols / n
        h_joint = -np.sum(p * np.log(p))
        h_rows = -np.sum(pr * np.log(pr))
        h_cols = -np.sum(pc * np.log(pc))
        voi_split = h_joint - h_cols   # H(seg | gt)
        voi_merge = h_joint - h_rows   # H(gt | seg)

        return dict(
            rand_index=float(rand_index),
            adjusted_rand_index=float(ari),
            voi_split=float(max(voi_split, 0.0)),
            voi_merge=float(max(voi_merge, 0.0)),
            fowlkes_mallows=float(fm),
        )

    # ---- remapping -------------------------------------------------------
    def renumber(self, start_id: int = 1, base_id: int = 0) -> "Segmentation":
        from chunkflow_tpu.ops import remap

        arr, _ = remap.renumber(np.asarray(self.array), start_id=start_id)
        if base_id:
            # offset in uint64 so large bases never wrap the source dtype
            arr = np.asarray(arr, dtype=np.uint64)
            arr = np.where(arr > 0, arr + np.uint64(base_id), np.uint64(0))
        return self._with_array(arr)

    def remap(self, base_id: int = 0) -> Tuple["Segmentation", int]:
        """Renumber ids consecutively, offset by ``base_id``; returns the
        new chunk and its max id as the next base (reference
        chunk/segmentation.py:69-84). Functional twist: the reference
        mutates in place and returns only the new base id."""
        seg = self.renumber(start_id=1, base_id=base_id).astype(np.uint64)
        new_base_id = max(int(np.asarray(seg.array).max()), int(base_id))
        return seg, new_base_id

    def mask_fragments(self, voxel_num_threshold: int) -> "Segmentation":
        """Dust removal: zero out objects smaller than the threshold."""
        arr = np.asarray(self.array)
        ids, counts = np.unique(arr, return_counts=True)
        small = ids[(counts < voxel_num_threshold) & (ids > 0)]
        keep = ~np.isin(arr, small)
        return self._with_array(np.where(keep, arr, 0).astype(arr.dtype))

    def mask_except(self, selected_ids: Sequence[int]) -> "Segmentation":
        """Keep only the listed object ids."""
        arr = np.asarray(self.array)
        keep = np.isin(arr, np.asarray(list(selected_ids)))
        return self._with_array(np.where(keep, arr, 0).astype(arr.dtype))
