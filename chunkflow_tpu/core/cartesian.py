"""zyx coordinate triple with full elementwise algebra.

Feature parity with the reference geometry core
(/root/reference/chunkflow/lib/cartesian_coordinate.py:26-187) but written
fresh: a ``NamedTuple`` in (z, y, x) order — the C-order axis convention used
throughout the framework — supporting elementwise arithmetic against scalars,
other triples, and numpy arrays.
"""
from __future__ import annotations

import math
import operator
from typing import NamedTuple, Union

import numpy as np

ScalarOrTriple = Union[int, float, tuple, list, np.ndarray, "Cartesian"]


def _coerce(other: ScalarOrTriple) -> tuple:
    """Broadcast ``other`` to a 3-tuple for elementwise ops."""
    if isinstance(other, (int, float, np.integer, np.floating)):
        return (other, other, other)
    if isinstance(other, np.ndarray):
        other = other.tolist()
    if len(other) != 3:
        raise ValueError(f"expected a scalar or length-3 sequence, got {other!r}")
    return tuple(other)


class Cartesian(NamedTuple):
    """An integer or float coordinate/size triple in (z, y, x) order."""

    z: Union[int, float]
    y: Union[int, float]
    x: Union[int, float]

    # ---- constructors -------------------------------------------------
    @classmethod
    def from_collection(cls, col: ScalarOrTriple) -> "Cartesian":
        return cls(*_coerce(col))

    @classmethod
    def zeros(cls) -> "Cartesian":
        return cls(0, 0, 0)

    @classmethod
    def ones(cls) -> "Cartesian":
        return cls(1, 1, 1)

    # ---- elementwise algebra ------------------------------------------
    def _binop(self, other: ScalarOrTriple, op) -> "Cartesian":
        o = _coerce(other)
        return Cartesian(op(self.z, o[0]), op(self.y, o[1]), op(self.x, o[2]))

    def _rbinop(self, other: ScalarOrTriple, op) -> "Cartesian":
        o = _coerce(other)
        return Cartesian(op(o[0], self.z), op(o[1], self.y), op(o[2], self.x))

    def __add__(self, other):  # type: ignore[override]
        return self._binop(other, operator.add)

    def __radd__(self, other):
        return self._rbinop(other, operator.add)

    def __sub__(self, other):
        return self._binop(other, operator.sub)

    def __rsub__(self, other):
        return self._rbinop(other, operator.sub)

    def __mul__(self, other):  # type: ignore[override]
        return self._binop(other, operator.mul)

    def __rmul__(self, other):  # type: ignore[override]
        return self._rbinop(other, operator.mul)

    def __floordiv__(self, other):
        return self._binop(other, operator.floordiv)

    def __truediv__(self, other):
        return self._binop(other, operator.truediv)

    def __mod__(self, other):
        return self._binop(other, operator.mod)

    def __neg__(self):
        return Cartesian(-self.z, -self.y, -self.x)

    def __invert__(self) -> "Cartesian":
        """Elementwise reciprocal (matches the reference's ``-`` inverse op)."""
        return Cartesian(1.0 / self.z, 1.0 / self.y, 1.0 / self.x)

    # ---- comparisons (all-elementwise; NamedTuple supplies __eq__) ----
    def __lt__(self, other) -> bool:  # type: ignore[override]
        o = _coerce(other)
        return all(s < v for s, v in zip(self, o))

    def __le__(self, other) -> bool:  # type: ignore[override]
        o = _coerce(other)
        return all(s <= v for s, v in zip(self, o))

    def __gt__(self, other) -> bool:  # type: ignore[override]
        o = _coerce(other)
        return all(s > v for s, v in zip(self, o))

    def __ge__(self, other) -> bool:  # type: ignore[override]
        o = _coerce(other)
        return all(s >= v for s, v in zip(self, o))

    # ---- rounding / casting -------------------------------------------
    def ceil(self) -> "Cartesian":
        return Cartesian(*(int(math.ceil(v)) for v in self))

    def floor(self) -> "Cartesian":
        return Cartesian(*(int(math.floor(v)) for v in self))

    def astype_int(self) -> "Cartesian":
        return Cartesian(*(int(v) for v in self))

    def ceildiv(self, other: ScalarOrTriple) -> "Cartesian":
        o = _coerce(other)
        return Cartesian(*(-((-s) // v) for s, v in zip(self, o)))

    def maximum(self, other: ScalarOrTriple) -> "Cartesian":
        return self._binop(other, max)

    def minimum(self, other: ScalarOrTriple) -> "Cartesian":
        return self._binop(other, min)

    # ---- conversions ---------------------------------------------------
    @property
    def inverse(self) -> "Cartesian":
        """Reversed order (zyx <-> xyz), reference spelling."""
        return Cartesian(self.x, self.y, self.z)

    @property
    def vec(self) -> np.ndarray:
        return np.asarray(self)

    @property
    def tuple(self) -> tuple:
        return (self.z, self.y, self.x)

    def prod(self):
        return self.z * self.y * self.x

    def all_positive(self) -> bool:
        return self.z > 0 and self.y > 0 and self.x > 0

    def __repr__(self) -> str:
        return f"Cartesian(z={self.z}, y={self.y}, x={self.x})"


def to_cartesian(value) -> "Cartesian | None":
    """Lenient conversion used at API boundaries; ``None`` passes through."""
    if value is None:
        return None
    if isinstance(value, Cartesian):
        return value
    return Cartesian.from_collection(value)
