"""Shared warn-once env-mode parsing (ISSUE 16 satellite).

Three kernel/precision selection knobs grew the same parser
independently — ``CHUNKFLOW_PALLAS`` (ops/pallas_blend.py),
``CHUNKFLOW_GATHER`` (ops/pallas_gather.py) and the lenient env path of
``CHUNKFLOW_PRECISION`` (inference/precision.py) — each with the same
three-part contract:

1. recognized values map to a mode, case-insensitively;
2. unrecognized values resolve to a SAFE default (a typo must never
   force-select a compiled Mosaic kernel or a quantized forward, and
   must never silently pick a slow fallback either);
3. the fallback warns ONCE per distinct unrecognized value on stderr,
   tracked in a per-variable warned-set so long-lived workers don't
   spam and tests can reset it.

:func:`resolve` is that contract, once, so the fused patch program's
future knob (ROADMAP: gather->forward->blend in one kernel) does not
become copy #4. Callers keep their own module-level ``_WARNED_VALUES``
set and pass it in — the established test seam monkeypatches the
caller's set, and per-module sets keep one variable's typos from
muting another's.

Import-light on purpose: selection helpers run before jax loads.
"""
from __future__ import annotations

import os
import sys
from typing import Callable, Dict, Optional, Sequence, Set, Tuple

__all__ = ["resolve"]

#: fallback warned-sets for callers that don't carry their own,
#: keyed per variable so CHUNKFLOW_PALLAS typos never mute
#: CHUNKFLOW_GATHER warnings
_WARNED_BY_VAR: Dict[str, Set[str]] = {}


def resolve(
    var: str,
    choices: Dict[str, Tuple[str, ...]],
    default: str,
    note: str,
    warned: Optional[Set[str]] = None,
    normalize: Optional[Callable[[str], str]] = None,
) -> str:
    """The mode selected by env var ``var``: the first ``choices`` entry
    whose recognized-value tuple contains the (lowercased, optionally
    ``normalize``d) env value; ``default`` with a one-time stderr
    warning otherwise.

    choices:   mode -> recognized raw values (include ``""`` wherever
               unset-env should land WITHOUT warning)
    note:      what the fallback means operationally, appended to the
               warning so a typo'd opt-in says which path actually runs
    warned:    the caller's per-variable warned-set (module-level, so
               tests can reset it); defaults to an internal per-``var``
               set
    normalize: alias folding applied after lowercasing (the precision
               spec's ``bf16`` -> ``bfloat16``)
    """
    env = os.environ.get(var, "").lower()
    if normalize is not None:
        env = normalize(env)
    for mode, values in choices.items():
        if env in values:
            return mode
    if warned is None:
        warned = _WARNED_BY_VAR.setdefault(var, set())
    if env not in warned:
        warned.add(env)
        expected = ", ".join(
            "/".join(v for v in values if v) or "(unset)"
            for values in choices.values()
        )
        print(
            f"{var}={os.environ.get(var)!r} is not a recognized value "
            f"(expected one of {expected}); {note}",
            file=sys.stderr,
        )
    return default


def recognized_values(choices: Dict[str, Sequence[str]]) -> Tuple[str, ...]:
    """Every recognized raw value across ``choices`` (tests enumerate
    these to assert no recognized value ever warns)."""
    out = []
    for values in choices.values():
        out.extend(values)
    return tuple(out)
