"""Device-performance plane: program cost ledger, roofline accounting,
and bounded profiler capture.

The host-side observability stack (core/telemetry.py, PR 3/6) attributes
*wall-clock*; this module attributes the *device*. Three instruments,
all riding the telemetry registry and its kill switch
(``CHUNKFLOW_TELEMETRY=0`` ⇒ no ledger, no files, no capture threads,
no ``/profile`` route — nothing):

1. **Program cost ledger.** Every :class:`~chunkflow_tpu.core.
   compile_cache.ProgramCache` build passes through
   :func:`instrument_program`: the jit program is wrapped so its FIRST
   invocation (the one that pays trace + XLA compile) is timed as
   ``compile_s``, and the lowered computation's XLA
   ``cost_analysis()`` — FLOPs and bytes accessed — is captured
   best-effort *without compiling twice* (``Lowered.cost_analysis``
   runs on the unoptimized HLO). Results land in ``program/*``
   counters, one ``compile``-kind telemetry event per program, and a
   per-run ``programs.json`` catalog written at flush time.

2. **Roofline accounting.** At catalog time each program's cost is
   scored against a small peak-FLOPs/HBM-bandwidth table keyed on
   ``jax.devices()[0].device_kind`` (env-overridable via
   ``CHUNKFLOW_PEAK_FLOPS`` / ``CHUNKFLOW_PEAK_BW``; a conservative CPU
   fallback keeps the math defined on the test mesh):
   ``roofline_s = max(flops/peak_flops, bytes/peak_bw)`` and
   ``roofline_util = roofline_s / exec_s``. ``exec_s`` is the mean
   post-compile *dispatch wall* — under async dispatch that is a lower
   bound on device time, so the utilisation figure is an upper bound;
   it answers "which program family is worth a kernel" (the Pallas
   blend / multi-chip question), not "publishable MXU utilisation"
   (that stays tools/tpu_validation.py's ``profile_flagship``).

3. **Bounded profiler capture.** The whole-run ``--profile-dir`` trace
   is replaced by a task window (:func:`start_task_window`: first N
   tasks, ``CHUNKFLOW_PROFILE_TASKS`` default 4), and two *automatic*
   triggers capture one bounded ``jax.profiler`` window each — the
   retrace watchdog firing (:func:`note_retrace`) and a dominant stall
   share holding above ``CHUNKFLOW_PROFILE_STALL_SHARE`` for
   ``CHUNKFLOW_PROFILE_STALL_TICKS`` controller intervals
   (:func:`note_stall`) — with a cooldown
   (``CHUNKFLOW_PROFILE_COOLDOWN``, default 300 s) so an anomaly storm
   cannot fill the disk with traces. A fleet operator can also demand a
   window from a live worker: ``POST /profile?seconds=N``
   (parallel/restapi.py). Captures land under the metrics dir
   (``profile-<reason>-<n>/``) and are summarised offline by
   ``tools/analyze_trace.py`` through ``log-summary``.

Design rules inherited from core/telemetry.py: never inside jit
(GL007 — every clock here wraps the program from the host side), zero
when off, zero dependencies beyond jax itself (imported lazily, only
on paths that already run jax programs).

See docs/observability.md "Device program view".
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional, Tuple

from chunkflow_tpu.core import telemetry

__all__ = [
    "instrument_program", "stamp_cost", "catalog", "write_catalog",
    "device_peaks", "estimate_collective_split", "note_h2d",
    "h2d_by_family",
    "note_hbm_intermediate", "hbm_intermediate_by_family",
    "note_collective", "collective_by_family",
    "capture", "maybe_capture", "note_retrace", "note_stall",
    "note_slo_page", "start_task_window", "note_task_done",
    "wait_for_captures", "capture_base_dir",
]


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# roofline peak table
# ---------------------------------------------------------------------------
#: (device_kind substring, (peak FLOP/s, peak HBM bytes/s)) — matched
#: case-insensitively, first hit wins, most specific first. Values are
#: published bf16 peaks per chip (the inference dtype of record); the
#: ``cpu`` row is a deliberately conservative host fallback so the
#: roofline math stays defined on the CI mesh (override with
#: CHUNKFLOW_PEAK_FLOPS / CHUNKFLOW_PEAK_BW for a calibrated host).
DEVICE_PEAKS = (
    ("tpu v6", (918e12, 1640e9)),   # Trillium
    ("tpu v5p", (459e12, 2765e9)),
    ("tpu v5 lite", (197e12, 819e9)),
    ("tpu v5e", (197e12, 819e9)),
    ("tpu v4", (275e12, 1228e9)),
    ("tpu v3", (123e12, 900e9)),
    ("cpu", (1e11, 5e10)),
)

_CPU_FALLBACK = (1e11, 5e10)


def device_peaks(device_kind: str) -> dict:
    """Peak FLOP/s + bytes/s for a device kind: env overrides first
    (``CHUNKFLOW_PEAK_FLOPS`` / ``CHUNKFLOW_PEAK_BW``), then the
    substring table, then the CPU fallback. ``source`` says which."""
    env_flops = _env_float("CHUNKFLOW_PEAK_FLOPS", 0.0)
    env_bw = _env_float("CHUNKFLOW_PEAK_BW", 0.0)
    kind = (device_kind or "").lower()
    flops, bw, source = None, None, "fallback"
    for needle, (f, b) in DEVICE_PEAKS:
        if needle in kind:
            flops, bw, source = f, b, f"table:{needle}"
            break
    if flops is None:
        flops, bw = _CPU_FALLBACK
    if env_flops > 0:
        flops, source = env_flops, "env"
    if env_bw > 0:
        bw, source = env_bw, "env"
    return {"flops_per_s": flops, "bytes_per_s": bw, "source": source}


def estimate_collective_split(flops: float, collective_bytes: float,
                              device_kind: Optional[str] = None) -> dict:
    """Analytic collective-vs-compute split of one sharded dispatch
    against the roofline peak table: ``compute_s = flops / peak_flops``
    and ``collective_s = collective_bytes / peak_bytes`` for the mesh's
    device kind. The bytes/s figure is the chip's HBM row — a proxy that
    flatters the interconnect (ICI/DCN are slower than HBM), so the
    returned ``collective_share`` is a *lower bound* on how
    communication-dominated the mesh shape is; a shape that already
    looks collective-bound here is definitely not worth scaling.
    ``device_kind=None`` probes ``jax.devices()[0]``."""
    if device_kind is None:
        _, device_kind = _device_identity()
    peaks = device_peaks(device_kind)
    compute_s = max(0.0, float(flops)) / peaks["flops_per_s"]
    collective_s = max(0.0, float(collective_bytes)) / peaks["bytes_per_s"]
    total = compute_s + collective_s
    return {
        "compute_s": compute_s,
        "collective_s": collective_s,
        "collective_share": (collective_s / total) if total > 0 else 0.0,
        "device_kind": device_kind,
        "peak_source": peaks["source"],
    }


# ---------------------------------------------------------------------------
# program cost ledger
# ---------------------------------------------------------------------------
class _ProgramRecord:
    """One ProgramCache build's cost story. ``compile_s`` is None until
    the program's first invocation pays trace + XLA compile."""

    __slots__ = (
        "family", "key", "label", "build_s", "compile_s", "flops",
        "bytes_accessed", "vmem_bytes", "hbm_intermediate", "optimal_s",
        "calls", "dispatch_s", "platform", "device_kind", "lock",
    )

    def __init__(self, family: str, key: str, label: str, build_s: float):
        self.family = family
        self.key = key
        self.label = label
        self.build_s = build_s
        self.compile_s: Optional[float] = None
        self.flops: Optional[float] = None
        self.bytes_accessed: Optional[float] = None
        self.vmem_bytes: Optional[float] = None
        self.hbm_intermediate: Optional[float] = None
        self.optimal_s: Optional[float] = None
        self.calls = 0
        self.dispatch_s = 0.0  # post-compile dispatch wall, cumulative
        self.platform = ""
        self.device_kind = ""
        self.lock = threading.Lock()


_LEDGER_LOCK = threading.Lock()
_LEDGER: dict = {}  # (family, key) -> _ProgramRecord


def _device_identity() -> Tuple[str, str]:
    try:
        import jax

        dev = jax.devices()[0]
        return dev.platform, dev.device_kind
    except Exception:
        return "unknown", "unknown"


def _cost_analysis(program, args, kwargs) -> dict:
    """Best-effort XLA cost analysis of the program at these argument
    shapes, via ``Lowered.cost_analysis()`` (no second compile). Returns
    {} when the backend / program doesn't expose it."""
    try:
        cost = program.lower(*args, **kwargs).cost_analysis()
    except Exception:
        return {}
    if isinstance(cost, list):  # older jax returns [dict]
        cost = cost[0] if cost else {}
    return cost if isinstance(cost, dict) else {}


class _InstrumentedProgram:
    """Transparent wrapper around one cached jit program: first call
    timed as compile, later calls accumulate dispatch wall; attribute
    access (``lower``, ``_cache_size``, ...) forwards to the program."""

    __slots__ = ("_fn", "_rec")

    def __init__(self, fn, rec: _ProgramRecord):
        self._fn = fn
        self._rec = rec

    def __call__(self, *args, **kwargs):
        rec = self._rec
        if rec.compile_s is None:
            return self._first_call(args, kwargs)
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        with rec.lock:
            rec.calls += 1
            rec.dispatch_s += dt
        return out

    def _first_call(self, args, kwargs):
        rec = self._rec
        # an analytic cost stamp (stamp_cost) wins over XLA's
        # cost_analysis: programs whose HLO hides traffic behind custom
        # calls (the fused Pallas kernel) or loop bodies are opaque or
        # miscounted by the unoptimized-HLO analysis
        cost = getattr(self._fn, "_chunkflow_cost", None)
        if not isinstance(cost, dict):
            # cost analysis BEFORE dispatch: afterwards a donated input
            # buffer is dead, and lowering only needs shapes anyway
            cost = _cost_analysis(self._fn, args, kwargs)
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        first = False
        with rec.lock:
            if rec.compile_s is None:
                first = True
                rec.compile_s = dt
                rec.platform, rec.device_kind = _device_identity()
                flops = cost.get("flops")
                nbytes = cost.get("bytes accessed")
                vmem = cost.get("vmem_bytes")
                hbm_i = cost.get("hbm_intermediate_bytes")
                optimal = cost.get("optimal_seconds")
                rec.flops = float(flops) if flops is not None else None
                rec.bytes_accessed = (
                    float(nbytes) if nbytes is not None else None
                )
                rec.vmem_bytes = float(vmem) if vmem is not None else None
                rec.hbm_intermediate = (
                    float(hbm_i) if hbm_i is not None else None
                )
                rec.optimal_s = (
                    float(optimal) if optimal is not None else None
                )
            else:  # raced: the other thread's call was the compile
                rec.calls += 1
                rec.dispatch_s += dt
        if first:
            telemetry.inc("program/builds")
            telemetry.inc("program/compile_seconds", dt)
            if rec.flops:
                telemetry.inc("program/flops_total", rec.flops)
            if rec.bytes_accessed:
                telemetry.inc("program/bytes_total", rec.bytes_accessed)
            telemetry.event(
                "compile", f"program/{rec.family}",
                family=rec.family, key=rec.key, label=rec.label,
                build_s=round(rec.build_s, 4),
                compile_s=round(dt, 4),
                flops=rec.flops, bytes_accessed=rec.bytes_accessed,
                device=rec.device_kind, platform=rec.platform,
            )
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)


class _CostStamped:
    """A jit program carrying an analytic cost model. Transparent:
    ``__call__`` and attribute access (``lower``, ...) forward to the
    program; :func:`instrument_program`'s wrapper reads the stamp."""

    __slots__ = ("_fn", "_chunkflow_cost")

    def __init__(self, fn, cost: dict):
        self._fn = fn
        self._chunkflow_cost = cost

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._fn, name)


def stamp_cost(program, flops: Optional[float] = None,
               bytes_accessed: Optional[float] = None,
               vmem_bytes: Optional[float] = None,
               hbm_intermediate_bytes: Optional[float] = None):
    """Attach an ANALYTIC cost model to a program before it enters a
    ProgramCache: the ledger then scores its roofline against these
    numbers instead of XLA's ``cost_analysis()``. Use for programs the
    unoptimized-HLO analysis cannot see into (Pallas custom calls) or
    systematically miscounts (loop-body traffic) — the stamp is the
    builder's arithmetic, so it must state what the program actually
    moves/computes, not what would look good. ``vmem_bytes`` is the
    kernel's analytic on-chip footprint (block windows, double-buffered
    where the pipeline does, plus scratch — the GL021 arithmetic; see
    ``ops/pallas_blend.fused_kernel_cost`` /
    ``ops/pallas_gather.gather_kernel_cost``), surfaced as the catalog's
    ``vmem_bytes`` column so a budget regression shows up in the DEVICE
    PROGRAMS table before it shows up as a Mosaic OOM.
    ``hbm_intermediate_bytes`` is the inter-stage stack traffic this
    program's composition materializes in HBM between pipeline stages
    per call (ISSUE 17): the separate gather/forward/blend legs stamp
    the stacks they write+re-read, the fused pipeline stamps ~0 — the
    fusion's prize, surfaced as the catalog's
    ``hbm_intermediate_bytes`` / log-summary ``hbm_i`` column."""
    cost: dict = {}
    if flops is not None:
        cost["flops"] = float(flops)
    if bytes_accessed is not None:
        cost["bytes accessed"] = float(bytes_accessed)
    if vmem_bytes is not None:
        cost["vmem_bytes"] = float(vmem_bytes)
    if hbm_intermediate_bytes is not None:
        cost["hbm_intermediate_bytes"] = float(hbm_intermediate_bytes)
    return _CostStamped(program, cost)


_H2D_LOCK = threading.Lock()
_H2D: dict = {}  # program family -> staged H2D bytes


def note_h2d(nbytes, key=None, label: str = "") -> None:
    """Count one host->device staging transfer at the staging seam
    (ISSUE 15): the ``transfer/h2d_bytes`` / ``transfer/h2d_chunks``
    counters make the front-half win visible in byte terms, and ``key``
    (a ProgramCache key) attributes the bytes to the program family that
    consumes them — the ``h2d_bytes`` column of the programs.json
    catalog / log-summary DEVICE PROGRAMS table. No-op under the
    telemetry kill switch."""
    if not telemetry.enabled():
        return
    telemetry.inc("transfer/h2d_bytes", float(nbytes))
    telemetry.inc("transfer/h2d_chunks")
    if key is not None:
        family, _ = _family_of(key, label)
        with _H2D_LOCK:
            _H2D[family] = _H2D.get(family, 0.0) + float(nbytes)


def h2d_by_family() -> dict:
    """Staged H2D bytes per program family (a copy)."""
    with _H2D_LOCK:
        return dict(_H2D)


_HBM_I_LOCK = threading.Lock()
_HBM_I: dict = {}  # program family -> inter-stage stack bytes


def note_hbm_intermediate(nbytes, key=None, label: str = "") -> None:
    """Count inter-stage stack traffic the SEPARATE-programs composition
    pays between pipeline stages (ISSUE 17): the gathered-patch /
    weighted-prediction stacks one program materializes and the next
    re-reads (including the serving packer's D2H+H2D round trip of the
    weighted stack). The fused pipeline leg notes ~nothing here — the
    ``transfer/hbm_intermediate_bytes`` counter and the per-family
    bucket (the catalog's ``hbm_intermediate_bytes`` fallback when no
    stamp carries it) make the fusion win visible in byte terms, the
    same shape as :func:`note_h2d`. No-op under the telemetry kill
    switch."""
    if not telemetry.enabled():
        return
    telemetry.inc("transfer/hbm_intermediate_bytes", float(nbytes))
    if key is not None:
        family, _ = _family_of(key, label)
        with _HBM_I_LOCK:
            _HBM_I[family] = _HBM_I.get(family, 0.0) + float(nbytes)


def hbm_intermediate_by_family() -> dict:
    """Inter-stage stack bytes per program family (a copy)."""
    with _HBM_I_LOCK:
        return dict(_HBM_I)


_COLLECTIVE_LOCK = threading.Lock()
_COLLECTIVE: dict = {}  # program family -> analytic collective bytes


def note_collective(nbytes, key=None, label: str = "") -> None:
    """Count ANALYTIC cross-chip collective traffic for one sharded
    dispatch (ISSUE 18): halo ``ppermute`` exchanges, the weighted-
    stack ``all_gather`` (replicated-replay legs only), the fringe
    replay-strip ``ppermute`` exchanges of the sharded blend replay,
    and the per-tick activation handoffs of the ``pipeline=N`` ring
    (ISSUE 19) — each computed by the engine from halo/fringe widths,
    shard shapes and dtypes — the same stamped-arithmetic discipline as
    :func:`stamp_cost`, because XLA's cost analysis does not price
    inter-chip links. Feeds the ``shard/collective_bytes`` counter and
    a per-family bucket (the catalog's ``collective_bytes`` column), so
    the MESH block can show collective-vs-compute per mesh shape; the
    engine additionally splits the total into ``shard/halo_bytes``,
    ``shard/gather_bytes``, ``shard/replay_strip_bytes`` and
    ``shard/handoff_bytes`` counters. No-op under the telemetry kill
    switch."""
    if not telemetry.enabled():
        return
    telemetry.inc("shard/collective_bytes", float(nbytes))
    if key is not None:
        family, _ = _family_of(key, label)
        with _COLLECTIVE_LOCK:
            _COLLECTIVE[family] = _COLLECTIVE.get(family, 0.0) \
                + float(nbytes)


def collective_by_family() -> dict:
    """Analytic collective bytes per program family (a copy)."""
    with _COLLECTIVE_LOCK:
        return dict(_COLLECTIVE)


def _family_of(key, label: str) -> Tuple[str, str]:
    """(family, shape-ish remainder) from a ProgramCache key. Keys are
    tuples like ``("scatter",)`` / ``("fold", (8, 32, 32))``; anything
    else falls back to the cache label."""
    if isinstance(key, tuple) and key:
        family = str(key[0])
        rest = ",".join(str(part) for part in key[1:])
    else:
        family = label or str(key)
        rest = "" if isinstance(key, tuple) else str(key)
    return family, rest


def instrument_program(program, key, label: str = "",
                       build_s: float = 0.0):
    """Wrap a freshly built cached program into the cost ledger; returns
    the program untouched when telemetry is off (kill switch: the plane
    does not exist) or when the object is not a lowerable jit program
    (tests cache plain sentinels)."""
    if not telemetry.enabled():
        return program
    if not callable(program) or not hasattr(program, "lower"):
        return program
    family, rest = _family_of(key, label)
    rec = _ProgramRecord(family=family, key=rest, label=label,
                         build_s=build_s)
    with _LEDGER_LOCK:
        _LEDGER[(family, rest, id(rec))] = rec
    return _InstrumentedProgram(program, rec)


def catalog() -> list:
    """The cost ledger with roofline derivations, one dict per program:
    compile seconds, FLOPs / bytes accessed (when XLA exposed them),
    post-compile dispatch stats, and — against :func:`device_peaks` —
    ``roofline_s`` (the cost-model floor per call) and
    ``roofline_util`` (floor / mean dispatch wall; an *upper bound*
    under async dispatch, see module docstring)."""
    with _LEDGER_LOCK:
        records = list(_LEDGER.values())
    h2d = h2d_by_family()
    hbm_i = hbm_intermediate_by_family()
    coll = collective_by_family()
    out = []
    for rec in records:
        with rec.lock:
            entry = {
                "family": rec.family,
                "key": rec.key,
                "label": rec.label,
                "build_s": round(rec.build_s, 4),
                "compile_s": (
                    round(rec.compile_s, 4)
                    if rec.compile_s is not None else None
                ),
                "flops": rec.flops,
                "bytes_accessed": rec.bytes_accessed,
                "vmem_bytes": rec.vmem_bytes,
                "optimal_s": rec.optimal_s,
                "calls": rec.calls + (1 if rec.compile_s is not None else 0),
                "dispatch_total_s": round(rec.dispatch_s, 4),
                "platform": rec.platform,
                "device_kind": rec.device_kind,
            }
            calls, dispatch_s = rec.calls, rec.dispatch_s
            flops, nbytes = rec.flops, rec.bytes_accessed
            kind = rec.device_kind
        peaks = device_peaks(kind)
        entry["peak_flops_per_s"] = peaks["flops_per_s"]
        entry["peak_bytes_per_s"] = peaks["bytes_per_s"]
        entry["peak_source"] = peaks["source"]
        roofline_s = None
        if flops is not None or nbytes is not None:
            roofline_s = max(
                (flops or 0.0) / peaks["flops_per_s"],
                (nbytes or 0.0) / peaks["bytes_per_s"],
            )
        entry["roofline_s"] = roofline_s
        exec_s = dispatch_s / calls if calls else None
        entry["exec_mean_s"] = round(exec_s, 6) if exec_s else None
        entry["roofline_util"] = (
            round(roofline_s / exec_s, 4)
            if roofline_s and exec_s else None
        )
        # lost seconds: (dispatch_wall − roofline_s) × calls — the total
        # wall this program spent ABOVE its cost-model floor, i.e. the
        # prize for fusing/optimizing it. The "what do I fuse next"
        # ranking key (log-summary DEVICE PROGRAMS); clamped at zero
        # because async dispatch can put measured wall under the floor.
        entry["lost_s"] = (
            round(max(0.0, exec_s - roofline_s) * calls, 6)
            if roofline_s is not None and exec_s else None
        )
        entry["achieved_flops_per_s"] = (
            round(flops / exec_s, 2) if flops and exec_s else None
        )
        # staged H2D bytes attributed to this family (note_h2d): the
        # front-half "what does this program cost the PCIe link" column
        entry["h2d_bytes"] = h2d.get(rec.family)
        # inter-stage stack traffic (ISSUE 17): a stamp on the program
        # wins (the builder's analytic per-call figure); otherwise the
        # note_hbm_intermediate family bucket (measured counters, e.g.
        # the serving round trip) — ~0 / absent on the fused pipeline
        entry["hbm_intermediate_bytes"] = (
            rec.hbm_intermediate
            if rec.hbm_intermediate is not None
            else hbm_i.get(rec.family)
        )
        # analytic cross-chip traffic attributed to this family
        # (note_collective): the "what does this program cost the
        # interconnect" column — absent on single-device programs
        entry["collective_bytes"] = coll.get(rec.family)
        out.append(entry)
    out.sort(key=lambda e: -(e["compile_s"] or 0.0))
    return out


def write_catalog(metrics_dir: Optional[str] = None) -> Optional[str]:
    """Write the per-run ``programs.json`` catalog (and emit a
    ``programs``-kind event carrying the same entries) under
    ``metrics_dir`` — default: the telemetry sink's directory. No-op
    (returns None) with telemetry off, an empty ledger, or nowhere to
    write. Registered as a telemetry flush hook, so every run that
    flushes a sink gets its catalog for free."""
    if not telemetry.enabled():
        return None
    entries = catalog()
    if not entries:
        return None
    if metrics_dir is None:
        path = telemetry.configured_path()
        metrics_dir = os.path.dirname(path) if path else None
    if metrics_dir is None:
        return None
    telemetry.event("programs", "program/catalog", programs=entries)
    payload = {
        "worker": telemetry.worker_id(),
        "t": time.time(),
        "programs": entries,
    }
    target = os.path.join(metrics_dir, "programs.json")
    try:
        os.makedirs(metrics_dir, exist_ok=True)
        tmp = target + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=2)
        os.replace(tmp, target)
    except OSError:
        return None
    return target


# ---------------------------------------------------------------------------
# bounded profiler capture (anomaly-triggered + operator-requested)
# ---------------------------------------------------------------------------
_STATE_LOCK = threading.Lock()
_TRACE_ACTIVE = False  # one jax profiler session at a time, window or capture
_LAST_CAPTURE_T: Optional[float] = None  # monotonic, automatic cooldown clock
_CAPTURE_SEQ = 0
_CAPTURE_THREADS: list = []
_STALL_PHASE: Optional[str] = None
_STALL_TICKS = 0
_WINDOW = None


def capture_base_dir() -> Optional[str]:
    """Where captures land: the telemetry sink's directory, else
    ``CHUNKFLOW_PROFILE_DIR``, else None (captures disabled)."""
    path = telemetry.configured_path()
    if path:
        return os.path.dirname(path)
    return os.environ.get("CHUNKFLOW_PROFILE_DIR") or None


def _anomaly_capture_enabled() -> bool:
    return os.environ.get(
        "CHUNKFLOW_PROFILE_ON_ANOMALY", "1"
    ).lower() not in ("0", "off", "false", "no")


def _acquire_trace() -> bool:
    global _TRACE_ACTIVE
    with _STATE_LOCK:
        if _TRACE_ACTIVE:
            return False
        _TRACE_ACTIVE = True
        return True


def _release_trace() -> None:
    global _TRACE_ACTIVE
    with _STATE_LOCK:
        _TRACE_ACTIVE = False


def _safe_name(reason: str) -> str:
    return "".join(
        ch if ch.isalnum() or ch in "._-" else "-" for ch in reason
    )[:48]


def _run_capture(target: str, seconds: float, reason: str) -> bool:
    """One bounded profiler window into ``target``; the caller holds the
    trace flag. Never raises — a failed capture is an event, not a
    pipeline death."""
    try:
        import jax

        os.makedirs(target, exist_ok=True)
        jax.profiler.start_trace(target)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
    except Exception as exc:
        telemetry.inc("profile/capture_errors")
        telemetry.event("profile", "profile/capture_error",
                        reason=reason, error=str(exc)[:300])
        return False
    finally:
        _release_trace()
    telemetry.inc("profile/captures")
    telemetry.event("profile", "profile/capture", dir=target,
                    seconds=seconds, reason=reason)
    return True


def capture(seconds: float, reason: str, force: bool = False,
            background: bool = False) -> Tuple[Optional[str], Optional[str]]:
    """One bounded profiler window; returns ``(trace_dir, error)``.

    ``force=True`` (operator request, the ``/profile`` route) bypasses
    the automatic-capture cooldown but never the one-session-at-a-time
    exclusion. ``background=True`` runs the window in a daemon thread
    (anomaly triggers must not stall the pipeline for the window's
    duration). Disabled telemetry or no capture dir ⇒ ``(None, why)``.
    """
    global _TRACE_ACTIVE, _LAST_CAPTURE_T, _CAPTURE_SEQ
    if not telemetry.enabled():
        return None, "telemetry disabled (CHUNKFLOW_TELEMETRY=0)"
    base = capture_base_dir()
    if base is None:
        return None, ("no capture dir: run with --metrics-dir or set "
                      "CHUNKFLOW_PROFILE_DIR")
    seconds = min(max(float(seconds), 0.05),
                  _env_float("CHUNKFLOW_PROFILE_MAX_SECONDS", 60.0))
    cooldown = _env_float("CHUNKFLOW_PROFILE_COOLDOWN", 300.0)
    with _STATE_LOCK:
        if _TRACE_ACTIVE:
            return None, "a profiler session is already active"
        if not force and _LAST_CAPTURE_T is not None \
                and time.monotonic() - _LAST_CAPTURE_T < cooldown:
            return None, "capture cooldown in effect"
        _TRACE_ACTIVE = True
        _LAST_CAPTURE_T = time.monotonic()
        _CAPTURE_SEQ += 1
        seq = _CAPTURE_SEQ
    target = os.path.join(base, f"profile-{_safe_name(reason)}-{seq}")
    if background:
        thread = threading.Thread(
            target=_run_capture, args=(target, seconds, reason),
            name=f"chunkflow-profile-{seq}", daemon=True,
        )
        _CAPTURE_THREADS.append(thread)
        thread.start()
        return target, None
    ok = _run_capture(target, seconds, reason)
    return (target, None) if ok else (None, "capture failed (see events)")


def maybe_capture(reason: str) -> bool:
    """Automatic (anomaly) capture: bounded window in a background
    thread, honoring the cooldown and the anomaly kill switch
    (``CHUNKFLOW_PROFILE_ON_ANOMALY=0``). Returns True when a capture
    was started."""
    if not telemetry.enabled() or not _anomaly_capture_enabled():
        return False
    seconds = _env_float("CHUNKFLOW_PROFILE_SECONDS", 3.0)
    target, err = capture(seconds, reason, force=False, background=True)
    if target is None:
        if err not in ("capture cooldown in effect",):
            telemetry.event("profile", "profile/capture_skipped",
                            reason=reason, why=err)
        return False
    return True


def note_retrace(label: str) -> None:
    """The retrace watchdog fired (core/compile_cache.py): the pipeline
    is paying an unplanned XLA compile per chunk — exactly the moment a
    bounded trace is worth its cost."""
    maybe_capture(f"retrace-{_safe_name(label)}")


def note_slo_page(objective: str) -> None:
    """A page-severity SLO burn-rate alert fired (core/slo.py): the
    serving plane is burning error budget fast enough to page a human —
    grab one bounded trace while the regression is still live, so the
    evidence is on disk before anyone is awake. Rides the same cooldown
    and kill switches as every other anomaly capture: an alert storm
    cannot fill the disk, and a second alert inside the cooldown
    captures nothing."""
    maybe_capture(f"slo-{_safe_name(objective)}")


def note_stall(phase: str, share: float) -> None:
    """One depth-controller tick's dominant stall sample
    (flow/scheduler.py). A share at or above
    ``CHUNKFLOW_PROFILE_STALL_SHARE`` (default 0.8) for
    ``CHUNKFLOW_PROFILE_STALL_TICKS`` (default 3) *consecutive* ticks
    on the SAME phase triggers one bounded capture — a persistent
    bottleneck the depth controller could not widen away."""
    global _STALL_PHASE, _STALL_TICKS
    threshold = _env_float("CHUNKFLOW_PROFILE_STALL_SHARE", 0.8)
    need = _env_int("CHUNKFLOW_PROFILE_STALL_TICKS", 3)
    with _STATE_LOCK:
        if share < threshold:
            _STALL_PHASE, _STALL_TICKS = None, 0
            return
        if phase != _STALL_PHASE:
            _STALL_PHASE, _STALL_TICKS = phase, 1
        else:
            _STALL_TICKS += 1
        if _STALL_TICKS < need:
            return
        _STALL_PHASE, _STALL_TICKS = None, 0
    maybe_capture(f"stall-{_safe_name(phase)}")


def wait_for_captures(timeout: float = 10.0) -> None:
    """Join outstanding background capture threads (tests, teardown)."""
    deadline = time.monotonic() + timeout
    for thread in list(_CAPTURE_THREADS):
        thread.join(timeout=max(0.0, deadline - time.monotonic()))
    _CAPTURE_THREADS[:] = [
        t for t in _CAPTURE_THREADS if t.is_alive()
    ]


# ---------------------------------------------------------------------------
# windowed --profile-dir capture (first N tasks)
# ---------------------------------------------------------------------------
class _TaskWindow:
    """A profiler session covering the first N pipeline tasks (N<=0:
    the whole run — the historical behavior, now opt-in)."""

    def __init__(self, trace_dir: str, tasks: int):
        self.trace_dir = trace_dir
        self.remaining = tasks
        self.active = False
        self._lock = threading.Lock()

    def _start(self) -> bool:
        if not _acquire_trace():
            return False
        try:
            import jax

            os.makedirs(self.trace_dir, exist_ok=True)
            jax.profiler.start_trace(self.trace_dir)
        except Exception as exc:
            _release_trace()
            telemetry.event("profile", "profile/window_error",
                            error=str(exc)[:300])
            return False
        self.active = True
        telemetry.event("profile", "profile/window_start",
                        dir=self.trace_dir, tasks=self.remaining)
        return True

    def note_task(self) -> None:
        with self._lock:
            if not self.active or self.remaining <= 0:
                return  # whole-run window: only close() stops it
            self.remaining -= 1
            if self.remaining > 0:
                return
            self._stop()

    def close(self) -> None:
        with self._lock:
            if self.active:
                self._stop()

    def _stop(self) -> None:
        """Caller holds self._lock (or is single-threaded teardown)."""
        self.active = False
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as exc:
            telemetry.event("profile", "profile/window_error",
                            error=str(exc)[:300])
        finally:
            _release_trace()
        telemetry.inc("profile/windows")
        telemetry.event("profile", "profile/window_stop",
                        dir=self.trace_dir)


def start_task_window(trace_dir: str,
                      tasks: Optional[int] = None) -> Optional[_TaskWindow]:
    """Start the windowed ``--profile-dir`` trace: the profiler runs
    from now until ``tasks`` pipeline tasks complete
    (``CHUNKFLOW_PROFILE_TASKS`` default 4; <=0 traces the whole run).
    Returns None — creating nothing — when telemetry is off or another
    profiler session is active."""
    global _WINDOW
    if not telemetry.enabled():
        return None
    if tasks is None:
        tasks = _env_int("CHUNKFLOW_PROFILE_TASKS", 4)
    window = _TaskWindow(trace_dir, tasks)
    if not window._start():
        return None
    _WINDOW = window
    return window


def note_task_done() -> None:
    """One pipeline task finished (flow/runtime.process_stream). Cheap
    flag check when no window is open."""
    window = _WINDOW
    if window is not None:
        window.note_task()


# ---------------------------------------------------------------------------
# per-run lifecycle: ride telemetry's flush/reset
# ---------------------------------------------------------------------------
def _on_reset() -> None:
    global _LAST_CAPTURE_T, _STALL_PHASE, _STALL_TICKS, _WINDOW
    window = _WINDOW
    if window is not None:
        window.close()
    _WINDOW = None
    with _LEDGER_LOCK:
        _LEDGER.clear()
    with _H2D_LOCK:
        _H2D.clear()
    with _HBM_I_LOCK:
        _HBM_I.clear()
    with _COLLECTIVE_LOCK:
        _COLLECTIVE.clear()
    with _STATE_LOCK:
        _LAST_CAPTURE_T = None
        _STALL_PHASE, _STALL_TICKS = None, 0


telemetry.add_flush_hook(write_catalog)
telemetry.add_reset_hook(_on_reset)
