"""Axis-aligned bounding boxes and overlapping task grids.

Parity targets (reference /root/reference/chunkflow/lib/cartesian_coordinate.py):
``BoundingBox`` (:190-519) — frozen start/stop box with the canonical
``zs-ze_ys-ye_xs-xe`` filename string, set algebra, block decomposition and
alignment checks; ``BoundingBoxes.from_manual_setup`` (:522-654) — the task
grid factory that turns a huge volume into overlapping chunk tasks;
``PhysicalBoundingBox`` (:698-724) — a box tagged with voxel size, rescalable
across mip levels.  All re-designed fresh on top of :class:`Cartesian`.
"""
from __future__ import annotations

import itertools
import re
from dataclasses import dataclass
from typing import Iterable, Iterator, List, Optional, Sequence, Union

import numpy as np

from chunkflow_tpu.core.cartesian import Cartesian, to_cartesian

_BBOX_RE = re.compile(
    r"(-?\d+)-(-?\d+)_(-?\d+)-(-?\d+)_(-?\d+)-(-?\d+)(?:\.\w+)?$"
)


@dataclass(frozen=True)
class BoundingBox:
    """Half-open box ``[start, stop)`` in zyx voxel coordinates."""

    start: Cartesian
    stop: Cartesian

    def __post_init__(self):
        object.__setattr__(self, "start", to_cartesian(self.start))
        object.__setattr__(self, "stop", to_cartesian(self.stop))

    # ---- constructors -------------------------------------------------
    @classmethod
    def from_delta(cls, start, size) -> "BoundingBox":
        start = to_cartesian(start)
        return cls(start, start + to_cartesian(size))

    @classmethod
    def from_center(cls, center, extent) -> "BoundingBox":
        center = to_cartesian(center)
        extent = to_cartesian(extent)
        return cls(center - extent, center + extent)

    @classmethod
    def from_string(cls, text: str) -> "BoundingBox":
        """Parse the canonical ``zs-ze_ys-ye_xs-xe`` string.

        Accepts an optional leading channel range and trailing file extension
        (e.g. ``0-3_16384-16492_86294-88342_121142-123190.json``): the LAST
        three ``a-b`` groups are the spatial box.
        """
        match = _BBOX_RE.search(text.strip())
        if match is None:
            raise ValueError(f"cannot parse bounding box from {text!r}")
        nums = [int(g) for g in match.groups()]
        start = Cartesian(nums[0], nums[2], nums[4])
        stop = Cartesian(nums[1], nums[3], nums[5])
        return cls(start, stop)

    @classmethod
    def from_slices(cls, slices: Sequence[slice]) -> "BoundingBox":
        slices = tuple(slices)[-3:]
        start = Cartesian(*(s.start for s in slices))
        stop = Cartesian(*(s.stop for s in slices))
        return cls(start, stop)

    @classmethod
    def from_array_like(cls, arr, voxel_offset=None) -> "BoundingBox":
        """Box covering the trailing-3 spatial dims of an array."""
        shape = Cartesian.from_collection(arr.shape[-3:])
        offset = to_cartesian(voxel_offset) or Cartesian.zeros()
        return cls(offset, offset + shape)

    # ---- basic properties ---------------------------------------------
    @property
    def shape(self) -> Cartesian:
        return self.stop - self.start

    @property
    def voxel_count(self) -> int:
        return int(self.shape.prod())

    @property
    def center(self) -> Cartesian:
        return (self.start + self.stop) // 2

    @property
    def string(self) -> str:
        s, e = self.start, self.stop
        return f"{s.z}-{e.z}_{s.y}-{e.y}_{s.x}-{e.x}"

    @property
    def slices(self) -> tuple:
        return tuple(slice(s, e) for s, e in zip(self.start, self.stop))

    def is_valid(self) -> bool:
        return self.shape.all_positive()

    def __repr__(self) -> str:
        return f"BoundingBox({self.string})"

    def __hash__(self) -> int:
        return hash((self.start, self.stop))

    # ---- geometry ops --------------------------------------------------
    def clone(self) -> "BoundingBox":
        return BoundingBox(self.start, self.stop)

    def translate(self, offset) -> "BoundingBox":
        offset = to_cartesian(offset)
        return BoundingBox(self.start + offset, self.stop + offset)

    def adjust(self, margin) -> "BoundingBox":
        """Grow (positive) or shrink (negative) symmetrically by ``margin``."""
        if margin is None:
            return self
        margin = Cartesian.from_collection(margin)
        return BoundingBox(self.start - margin, self.stop + margin)

    def union(self, other: "BoundingBox") -> "BoundingBox":
        return BoundingBox(
            self.start.minimum(other.start), self.stop.maximum(other.stop)
        )

    def intersection(self, other: "BoundingBox") -> "BoundingBox":
        return BoundingBox(
            self.start.maximum(other.start), self.stop.minimum(other.stop)
        )

    def overlaps(self, other: "BoundingBox") -> bool:
        return self.intersection(other).is_valid()

    def contains_point(self, point) -> bool:
        point = to_cartesian(point)
        return self.start <= point and all(
            p < e for p, e in zip(point, self.stop)
        )

    def contains(self, other) -> bool:
        """Box containment for a BoundingBox, point containment otherwise
        (the reference calls contains() with bare zyx points, INCLUSIVE at
        the stop corner — cartesian_coordinate.py:448-452 — unlike the
        half-open contains_point)."""
        if not isinstance(other, BoundingBox):
            point = to_cartesian(other)
            return self.start <= point and point <= self.stop
        return self.start <= other.start and other.stop <= self.stop

    def clamp(self, outer: "BoundingBox") -> "BoundingBox":
        """Shift/shrink this box so it fits inside ``outer``."""
        start = self.start.maximum(outer.start)
        stop = self.stop.minimum(outer.stop)
        return BoundingBox(start, stop)

    # ---- block alignment ----------------------------------------------
    def is_aligned_with(self, block_size, offset=None) -> bool:
        """True if both corners land on the block grid anchored at ``offset``.

        Block alignment is the write-conflict-avoidance contract: two aligned
        chunks never share a storage block, so parallel writers never race
        (reference volume.py:194-209 and --aligned-block-size semantics).
        """
        block_size = to_cartesian(block_size)
        offset = to_cartesian(offset) or Cartesian.zeros()
        return ((self.start - offset) % block_size == Cartesian.zeros()) and (
            (self.stop - offset) % block_size == Cartesian.zeros()
        )

    def snap_to_blocks(self, block_size, offset=None, outward: bool = True) -> "BoundingBox":
        """Round corners to the block grid (outward=True expands the box)."""
        block_size = to_cartesian(block_size)
        offset = to_cartesian(offset) or Cartesian.zeros()
        rel_start = self.start - offset
        rel_stop = self.stop - offset
        if outward:
            start = rel_start // block_size * block_size
            stop = rel_stop.ceildiv(block_size) * block_size
        else:
            start = rel_start.ceildiv(block_size) * block_size
            stop = rel_stop // block_size * block_size
        return BoundingBox(start + offset, stop + offset)

    # ---- reference-spelling compatibility surface ----------------------
    @property
    def minpt(self) -> Cartesian:
        return self.start

    @property
    def maxpt(self) -> Cartesian:
        return self.stop

    @classmethod
    def from_list(cls, lst) -> "BoundingBox":
        """[z0, y0, x0, ..., z1, y1, x1] (reference :236-239)."""
        return cls(
            Cartesian.from_collection(lst[:3]),
            Cartesian.from_collection(lst[-3:]),
        )

    @classmethod
    def from_points(cls, points) -> "BoundingBox":
        """Tight integer box around an [N, 3] point array (stop is
        exclusive); float points floor toward -inf so negatives stay
        inside."""
        points = np.asarray(points)
        lo = np.floor(points.min(axis=0)).astype(np.int64)
        hi = np.floor(points.max(axis=0)).astype(np.int64) + 1
        return cls(
            Cartesian.from_collection(lo), Cartesian.from_collection(hi)
        )

    @property
    def random_coordinate(self) -> Cartesian:
        # property, matching the reference's attribute access (:300-301)
        import random

        return Cartesian(
            *(random.randrange(s, e) for s, e in zip(self.start, self.stop))
        )

    def inverse_order(self) -> "BoundingBox":
        """zyx <-> xyz flipped corners (plain method like reference :376)."""
        return BoundingBox(self.start.inverse, self.stop.inverse)

    def adjust_corner(self, corner_offset) -> "BoundingBox":
        """Six-element (start_z, start_y, start_x, stop_z, stop_y, stop_x)
        additive adjustment (reference :419-426)."""
        if corner_offset is None or len(corner_offset) != 6:
            raise ValueError("corner_offset must have 6 elements")
        return BoundingBox(
            self.start + Cartesian.from_collection(corner_offset[:3]),
            self.stop + Cartesian.from_collection(corner_offset[3:]),
        )

    @property
    def left_neighbors(self):
        """The three same-sized boxes adjacent on the -z, -y, -x faces
        (attribute access like the reference's cached_property :491)."""
        size = self.shape
        return tuple(
            BoundingBox.from_delta(
                self.start - Cartesian(*(size[i] if j == i else 0
                                         for j in range(3))),
                size,
            )
            for i in range(3)
        )

    def decompose_to_aligned_block_bounding_boxes(
        self, block_size, bounded: bool = True
    ) -> List["BoundingBox"]:
        """Grid of full-size blocks anchored at start; with bounded=False
        the grid extends to cover the stop corner (reference :316-331)."""
        block_size = to_cartesian(block_size)
        stops = (
            self.stop if bounded
            else self.stop + block_size - Cartesian(1, 1, 1)
        )
        boxes = []
        for z in range(self.start.z, stops.z, block_size.z):
            for y in range(self.start.y, stops.y, block_size.y):
                for x in range(self.start.x, stops.x, block_size.x):
                    boxes.append(
                        BoundingBox.from_delta(Cartesian(z, y, x), block_size)
                    )
        return boxes

    def decompose_to_unaligned_block_bounding_boxes(
        self, block_size
    ) -> List["BoundingBox"]:
        """Like the aligned decomposition but trailing blocks are clipped
        at this box's stop (reference :333-347)."""
        block_size = to_cartesian(block_size)
        boxes = []
        for z in range(self.start.z, self.stop.z, block_size.z):
            for y in range(self.start.y, self.stop.y, block_size.y):
                for x in range(self.start.x, self.stop.x, block_size.x):
                    start = Cartesian(z, y, x)
                    stop = Cartesian.from_collection(
                        np.minimum((start + block_size).vec, self.stop.vec)
                    )
                    boxes.append(BoundingBox(start, stop))
        return boxes

    def decompose(self, block_size) -> List["BoundingBox"]:
        """Tile this box exactly into non-overlapping blocks."""
        block_size = to_cartesian(block_size)
        if self.shape % block_size != Cartesian.zeros():
            raise ValueError(
                f"shape {self.shape} is not a multiple of block size {block_size}"
            )
        grid = self.shape // block_size
        boxes = []
        for idx in itertools.product(*(range(g) for g in grid)):
            start = self.start + Cartesian(*idx) * block_size
            boxes.append(BoundingBox.from_delta(start, block_size))
        return boxes

    # ---- numpy bridge --------------------------------------------------
    def to_array(self) -> np.ndarray:
        return np.array([self.start.tuple, self.stop.tuple], dtype=np.int64)

    @classmethod
    def from_array(cls, arr) -> "BoundingBox":
        arr = np.asarray(arr).reshape(2, 3)
        return cls(Cartesian(*arr[0].tolist()), Cartesian(*arr[1].tolist()))


class BoundingBoxes:
    """An ordered collection of task bounding boxes (the task grid).

    The factory :meth:`from_manual_setup` mirrors the reference task-grid
    generator: an ROI is covered by an overlapping grid of chunk-sized boxes
    with stride ``chunk_size - overlap``, optionally clamped to the ROI and
    snapped to storage-block alignment.
    """

    def __init__(self, boxes: Iterable[BoundingBox]):
        self.boxes: List[BoundingBox] = list(boxes)

    # ---- factory -------------------------------------------------------
    @classmethod
    def from_manual_setup(
        cls,
        chunk_size,
        overlap=None,
        stride=None,
        roi_start=None,
        roi_stop=None,
        roi_size=None,
        grid_size=None,
        aligned_block_size=None,
        block_offset=None,
        bounded: bool = False,
    ) -> "BoundingBoxes":
        """Build the overlapping chunk grid covering an ROI.

        Exactly one of ``overlap``/``stride`` may be given (default: no
        overlap, stride == chunk_size). ``grid_size`` overrides the computed
        grid. With ``bounded=True`` trailing chunks are shifted back inside
        the ROI (so the last chunk overlaps its neighbor more instead of
        spilling out).
        """
        chunk_size = to_cartesian(chunk_size)
        if stride is not None and overlap is not None:
            raise ValueError("give either overlap or stride, not both")
        if stride is None:
            overlap = to_cartesian(overlap) or Cartesian.zeros()
            stride = chunk_size - overlap
        else:
            stride = to_cartesian(stride)
            overlap = chunk_size - stride
        if not stride.all_positive():
            raise ValueError(f"stride must be positive, got {stride}")

        roi_start = to_cartesian(roi_start) or Cartesian.zeros()
        if roi_stop is None:
            if roi_size is not None:
                roi_stop = roi_start + to_cartesian(roi_size)
            elif grid_size is not None:
                grid = to_cartesian(grid_size)
                roi_stop = roi_start + (grid - 1) * stride + chunk_size
            else:
                raise ValueError("need roi_stop, roi_size, or grid_size")
        else:
            roi_stop = to_cartesian(roi_stop)

        if aligned_block_size is not None:
            # block grids anchor at the volume's voxel_offset, not the
            # absolute origin (storage blocks of an offset volume start at
            # the offset; snapping without it straddles block boundaries)
            roi = BoundingBox(roi_start, roi_stop).snap_to_blocks(
                aligned_block_size, offset=block_offset, outward=True
            )
            roi_start, roi_stop = roi.start, roi.stop

        roi_shape = roi_stop - roi_start
        if not roi_shape.all_positive():
            raise ValueError(
                f"empty roi: start {tuple(roi_start)} stop {tuple(roi_stop)}"
            )
        if grid_size is None:
            # number of strides needed so chunks cover [roi_start, roi_stop)
            grid_size = (roi_shape - overlap).maximum(1).ceildiv(stride)
        grid_size = to_cartesian(grid_size)
        if not grid_size.all_positive():
            raise ValueError(f"grid size must be positive, got {tuple(grid_size)}")

        boxes = []
        for idx in itertools.product(*(range(g) for g in grid_size)):
            start = roi_start + Cartesian(*idx) * stride
            stop = start + chunk_size
            if bounded:
                # shift trailing chunks back inside the ROI
                shift = (stop - roi_stop).maximum(0)
                start = start - shift
                stop = stop - shift
                start = start.maximum(roi_start)
            boxes.append(BoundingBox(start, stop))
        obj = cls(boxes)
        obj.chunk_size = chunk_size
        obj.overlap = overlap
        obj.stride = stride
        obj.grid_size = grid_size
        obj.roi = BoundingBox(roi_start, roi_stop)
        return obj

    # ---- container protocol -------------------------------------------
    def __len__(self) -> int:
        return len(self.boxes)

    def __iter__(self) -> Iterator[BoundingBox]:
        return iter(self.boxes)

    def __getitem__(self, idx):
        picked = self.boxes[idx]
        if isinstance(idx, slice):
            return BoundingBoxes(picked)
        return picked

    def __eq__(self, other) -> bool:
        return isinstance(other, BoundingBoxes) and self.boxes == other.boxes

    # ---- serialization -------------------------------------------------
    def to_file(self, path: str) -> None:
        path = str(path)
        if path.endswith(".npy"):
            np.save(path, np.stack([b.to_array() for b in self.boxes]))
        elif path.endswith(".txt"):
            with open(path, "w") as f:
                for b in self.boxes:
                    f.write(b.string + "\n")
        else:
            raise ValueError(f"unsupported task-file format: {path}")

    @classmethod
    def from_file(cls, path: str) -> "BoundingBoxes":
        path = str(path)
        if path.endswith(".npy"):
            arr = np.load(path)
            return cls(BoundingBox.from_array(a) for a in arr)
        elif path.endswith(".txt"):
            with open(path) as f:
                return cls(
                    BoundingBox.from_string(line)
                    for line in f
                    if line.strip()
                )
        raise ValueError(f"unsupported task-file format: {path}")


@dataclass(frozen=True)
class PhysicalBoundingBox(BoundingBox):
    """A voxel box tagged with physical voxel size (nm), mip-rescalable."""

    voxel_size: Cartesian = Cartesian(1, 1, 1)

    def __post_init__(self):
        super().__post_init__()
        object.__setattr__(self, "voxel_size", to_cartesian(self.voxel_size))

    @property
    def physical_start(self) -> Cartesian:
        return self.start * self.voxel_size

    @property
    def physical_stop(self) -> Cartesian:
        return self.stop * self.voxel_size

    def to_voxel_size(self, voxel_size) -> "PhysicalBoundingBox":
        """Rescale box coordinates to another voxel size (mip change)."""
        voxel_size = to_cartesian(voxel_size)
        factor = voxel_size / self.voxel_size
        start = (self.start / factor).floor()
        stop = (self.stop / factor).ceil()
        return PhysicalBoundingBox(start, stop, voxel_size)

    # reference spellings (cartesian_coordinate.py:709-724)
    def to_other_voxel_size(self, voxel_size) -> "PhysicalBoundingBox":
        """Reference rounding: floor-divide BOTH corners when coarsening
        (:712-724) — unlike to_voxel_size, which ceils the stop so the box
        always covers the original extent."""
        voxel_size = to_cartesian(voxel_size)
        factor = voxel_size / self.voxel_size
        return PhysicalBoundingBox(
            (self.start / factor).floor(),
            (self.stop / factor).floor(),
            voxel_size,
        )

    @property
    def voxel_bounding_box(self) -> BoundingBox:
        return BoundingBox(self.start, self.stop)
