"""Physical regions of interest and their spatial decomposition tree.

Parity target: reference lib/region_of_interest.py — ``RegionOfInterest``
(a BoundingBox with voxel size, :10-71) and ``ROITree`` (:73-128). The
reference's ``ROITree.from_roi`` is an unimplemented prototype (its body is
``pass``); here it is a working aligned k-d decomposition: split along the
longest axis at a block-aligned midpoint until every leaf fits the atomic
block size. The tree drives dependency-ordered scheduling of hierarchical
tasks (see parallel/task_tree.py for the ready/working/done state machine).
"""
from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from chunkflow_tpu.core.bbox import BoundingBox, PhysicalBoundingBox
from chunkflow_tpu.core.cartesian import Cartesian, to_cartesian


class RegionOfInterest(PhysicalBoundingBox):
    """A bounding box in voxel units paired with its physical voxel size."""

    @classmethod
    def from_bbox(cls, bbox: BoundingBox, voxel_size) -> "RegionOfInterest":
        return cls(bbox.start, bbox.stop, voxel_size)

    @property
    def bounding_box(self) -> BoundingBox:
        return BoundingBox(self.start, self.stop)

    @property
    def physical_size(self) -> Cartesian:
        return self.voxel_size * self.shape

    def clone(self) -> "RegionOfInterest":
        return RegionOfInterest(self.start, self.stop, self.voxel_size)

    def slices_in_scale(self, voxel_size) -> tuple:
        """Slices of this ROI viewed in a volume of another voxel size."""
        voxel_size = to_cartesian(voxel_size)
        start = tuple(
            p * s1 // s2
            for p, s1, s2 in zip(self.start, self.voxel_size, voxel_size)
        )
        stop = tuple(
            p * s1 // s2
            for p, s1, s2 in zip(self.stop, self.voxel_size, voxel_size)
        )
        return BoundingBox(start, stop).slices

    def __repr__(self) -> str:
        return (
            f"RegionOfInterest(from {tuple(self.start)} to "
            f"{tuple(self.stop)}, voxel_size={tuple(self.voxel_size)})"
        )


class ROITree:
    """Aligned binary space partition of an ROI down to atomic blocks."""

    def __init__(
        self,
        roi: RegionOfInterest,
        axis: Optional[int] = None,
        left: Optional["ROITree"] = None,
        right: Optional["ROITree"] = None,
    ):
        if axis is not None:
            assert 0 <= axis < 3
        self.roi = roi
        self.axis = axis
        self.left = left
        self.right = right

    @property
    def is_leaf(self) -> bool:
        return self.left is None and self.right is None

    @classmethod
    def from_roi(
        cls, roi: RegionOfInterest, atomic_block_size
    ) -> "ROITree":
        """Split recursively along the longest axis (in blocks) at a
        block-aligned midpoint until one block (or less) remains per leaf."""
        block = to_cartesian(atomic_block_size)
        shape = roi.shape
        blocks_per_axis = [
            -(-int(shape[i]) // int(block[i])) for i in range(3)
        ]
        if max(blocks_per_axis) <= 1:
            return cls(roi)
        axis = int(np.argmax(blocks_per_axis))
        mid_blocks = blocks_per_axis[axis] // 2
        split = int(roi.start[axis]) + mid_blocks * int(block[axis])

        left_stop = list(roi.stop)
        left_stop[axis] = split
        right_start = list(roi.start)
        right_start[axis] = split
        left = cls.from_roi(
            RegionOfInterest(roi.start, tuple(left_stop), roi.voxel_size),
            block,
        )
        right = cls.from_roi(
            RegionOfInterest(tuple(right_start), roi.stop, roi.voxel_size),
            block,
        )
        return cls(roi, axis=axis, left=left, right=right)

    def leaves(self) -> Iterator[RegionOfInterest]:
        if self.is_leaf:
            yield self.roi
            return
        yield from self.left.leaves()
        yield from self.right.leaves()

    def __len__(self) -> int:
        if self.is_leaf:
            return 1
        return len(self.left) + len(self.right)
