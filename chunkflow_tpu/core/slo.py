"""SLO plane: declarative objectives, error budgets, burn-rate alerting.

Everything the serving/fleet planes measure — request latency, errors,
deadline misses, dead-letters, storage hit rates — was, until this
module, *compared against nothing*: the paper's production claim
(3600 nodes, 18 PB) only works because operators can tell when the
fleet is out of spec. This module is the measurement half of that
closed loop (a later PR wires policy to it):

* **Objectives** are declarative: a name, a target fraction of *good*
  events, and where good/bad come from — either a pair of registry
  counter sets (``kind="ratio"``: availability, deadline-miss rate,
  dead-letter rate, storage hit rate) or a quantile histogram plus a
  latency threshold (``kind="latency"``: "99% of requests under
  500 ms", which is exactly "p99 <= 500 ms" said budgetably).
  :data:`DEFAULT_OBJECTIVES` cover the serving plane out of the box; a
  ``[tool.chunkflow.slo]`` pyproject table or a ``--slo-config`` TOML
  file overrides targets, thresholds, windows, or disables objectives.

* **Error budgets**: an objective's budget is ``1 - target`` of events
  over a rolling period (default 30 days, scaled by the ``scale``
  config so tests run the same math in seconds). ``budget_remaining``
  is 1.0 untouched, 0.0 exactly spent, negative when blown.

* **Burn-rate alerting** is the Google SRE multi-window, multi-burn-rate
  recipe: an alert fires when the budget burn rate — bad-event share
  over the budget share — exceeds a rule's threshold over BOTH a long
  window (sustained, not a blip) and a short window (still happening
  *now*, so the page self-resolves when the regression stops).
  Defaults: ``fast`` = 14.4x over 1 h AND 5 m (page: a full 30-day
  budget would die in ~2 days), ``slow`` = 1x over 3 d AND 6 h
  (ticket: on pace to just exhaust the budget). Window lengths are
  configurable so tests compress days into seconds.

* **Outputs**: one ``alert``-kind JSONL event per rising edge (and one
  ``state="resolved"`` on falling), carrying burn rates and budget
  remaining; ``slo/<objective>/burn_rate|budget_remaining|firing``
  gauges (rendered as ``chunkflow_slo_*`` on ``/metrics``); the
  ``/alerts`` JSON route (parallel/restapi.py); and — page severity
  only — one bounded profiler capture through the PR 8 cooldown
  machinery (:func:`chunkflow_tpu.core.profiling.note_slo_page`), so
  the trace of the regression is on disk before anyone is awake.

The evaluator samples the registry on the telemetry time-series tick
(:func:`chunkflow_tpu.core.telemetry.add_tick_hook`) into a bounded
ring; window deltas are differences of cumulative counts, so burn math
is exact regardless of tick jitter. Kill-switch discipline matches the
rest of the plane: ``CHUNKFLOW_TELEMETRY=0`` (or ``CHUNKFLOW_SLO=0``)
creates no evaluator, no thread, no events, no route.

See docs/observability.md "SLO view".
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from chunkflow_tpu.core import telemetry

__all__ = [
    "Objective", "BurnRule", "SLOEvaluator", "DEFAULT_OBJECTIVES",
    "DEFAULT_RULES", "DEFAULT_PERIOD_S", "load_slo_config",
    "evaluator_from_config", "start_slo", "stop_slo", "current",
    "slo_enabled",
]

_OFF_VALUES = ("0", "off", "false", "no")

#: 30 days — the canonical SRE budget period; ``scale`` compresses it
DEFAULT_PERIOD_S = 30 * 86400.0


def slo_enabled() -> bool:
    """The SLO plane runs only when telemetry does; ``CHUNKFLOW_SLO=0``
    additionally disables just this plane (timeseries history stays)."""
    if not telemetry.enabled():
        return False
    return os.environ.get(
        "CHUNKFLOW_SLO", "1").strip().lower() not in _OFF_VALUES


# ---------------------------------------------------------------------------
# objectives + burn rules
# ---------------------------------------------------------------------------
class Objective:
    """One service-level objective: ``target`` fraction of events must
    be good. ``kind="ratio"``: good/bad derive from summed registry
    counters (``total`` minus ``bad`` is good). ``kind="latency"``:
    events are qhist samples; bad = samples above ``threshold_s``
    (snapped up to the nearest histogram bound, so bucket math is
    exact and fleet-summable)."""

    __slots__ = ("name", "target", "kind", "total", "bad", "qhist",
                 "threshold_s", "_bound_index", "description")

    def __init__(self, name: str, target: float, kind: str = "ratio",
                 total: Tuple[str, ...] = (), bad: Tuple[str, ...] = (),
                 qhist: Optional[str] = None,
                 threshold_s: Optional[float] = None,
                 description: str = ""):
        if not 0.0 < target < 1.0:
            raise ValueError(
                f"objective {name!r}: target must be in (0, 1), "
                f"got {target!r}")
        if kind not in ("ratio", "latency"):
            raise ValueError(
                f"objective {name!r}: kind must be ratio|latency, "
                f"got {kind!r}")
        if kind == "latency" and (qhist is None or threshold_s is None):
            raise ValueError(
                f"objective {name!r}: latency kind needs qhist + "
                f"threshold_s")
        self.name = name
        self.target = float(target)
        self.kind = kind
        self.total = tuple(total)
        self.bad = tuple(bad)
        self.qhist = qhist
        self.description = description
        self.threshold_s = None
        self._bound_index = None
        if threshold_s is not None:
            self.threshold_s = float(threshold_s)
            # snap the threshold UP to a bucket bound: everything at or
            # below that bound counts good, everything above counts bad
            idx = len(telemetry.QUANTILE_BOUNDS) - 1
            for i, bound in enumerate(telemetry.QUANTILE_BOUNDS):
                if bound >= self.threshold_s:
                    idx = i
                    break
            self._bound_index = idx

    def counts(self, counters: dict, qhists: dict) -> Tuple[float, float]:
        """Cumulative ``(total, bad)`` event counts right now."""
        if self.kind == "ratio":
            total = sum(counters.get(name, 0.0) for name in self.total)
            bad = sum(counters.get(name, 0.0) for name in self.bad)
            return float(total), float(bad)
        h = qhists.get(self.qhist) or {}
        buckets = h.get("buckets") or []
        total = float(h.get("count", 0))
        good = float(sum(buckets[: self._bound_index + 1]))
        return total, max(0.0, total - good)

    def describe(self) -> dict:
        out = {"name": self.name, "kind": self.kind, "target": self.target}
        if self.kind == "latency":
            out["qhist"] = self.qhist
            out["threshold_s"] = self.threshold_s
        else:
            out["total"] = list(self.total)
            out["bad"] = list(self.bad)
        return out


class BurnRule:
    """One multi-window burn-rate alert rule: fire when the burn rate
    exceeds ``burn`` over BOTH ``long_s`` and ``short_s``."""

    __slots__ = ("name", "short_s", "long_s", "burn", "severity")

    def __init__(self, name: str, short_s: float, long_s: float,
                 burn: float, severity: str = "ticket"):
        if short_s <= 0 or long_s <= 0 or short_s > long_s:
            raise ValueError(
                f"rule {name!r}: need 0 < short_s <= long_s, got "
                f"{short_s}/{long_s}")
        self.name = name
        self.short_s = float(short_s)
        self.long_s = float(long_s)
        self.burn = float(burn)
        self.severity = severity


def default_objectives() -> List[Objective]:
    return [
        Objective(
            "availability", target=0.999,
            total=("serving/requests",), bad=("serving/errors",),
            description="non-error share of serving requests",
        ),
        Objective(
            "latency", target=0.99, kind="latency",
            qhist="serving/latency", threshold_s=0.5,
            description="share of requests answered within threshold_s "
                        "(p99 <= threshold)",
        ),
        Objective(
            "deadline", target=0.99,
            total=("serving/requests",), bad=("serving/deadline_missed",),
            description="share of requests meeting their deadline",
        ),
        Objective(
            "dead_letter", target=0.999,
            total=("tasks/committed", "tasks/dead_lettered"),
            bad=("tasks/dead_lettered",),
            description="share of finished tasks not dead-lettered",
        ),
        Objective(
            "storage_hit", target=0.5,
            total=("storage/hits", "storage/misses"),
            bad=("storage/misses",),
            description="block-cache hit share (advisory: a cold cache "
                        "burns this budget by design while warming)",
        ),
    ]


def default_rules() -> List[BurnRule]:
    return [
        BurnRule("fast", short_s=300.0, long_s=3600.0, burn=14.4,
                 severity="page"),
        BurnRule("slow", short_s=6 * 3600.0, long_s=3 * 86400.0, burn=1.0,
                 severity="ticket"),
    ]


DEFAULT_OBJECTIVES = default_objectives()
DEFAULT_RULES = default_rules()


# ---------------------------------------------------------------------------
# configuration: [tool.chunkflow.slo] / --slo-config TOML
# ---------------------------------------------------------------------------
def _parse_scalar(raw: str):
    raw = raw.strip()
    if raw.startswith('"') and raw.endswith('"') and len(raw) >= 2:
        return raw[1:-1]
    if raw.startswith("'") and raw.endswith("'") and len(raw) >= 2:
        return raw[1:-1]
    if raw in ("true", "false"):
        return raw == "true"
    if raw.startswith("[") and raw.endswith("]"):
        inner = raw[1:-1].strip()
        if not inner:
            return []
        return [_parse_scalar(part) for part in inner.split(",")]
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"unparseable TOML value {raw!r}") from None


def _parse_toml_minimal(text: str, lenient: bool = False) -> dict:
    """A TOML subset parser (this image ships neither tomllib nor
    tomli): ``[dotted.section]`` headers and ``key = value`` pairs with
    strings, numbers, booleans and flat arrays — exactly the shapes the
    SLO config uses. Full TOML files that stay inside the subset parse
    identically; exotica (multiline strings/arrays, inline tables)
    raise in strict mode. ``lenient=True`` skips unparseable lines
    instead — the pyproject.toml scan, whose unrelated sections
    legitimately use full TOML the subset cannot read."""
    root: dict = {}
    table = root
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("[") and line.endswith("]"):
            table = root
            for part in line[1:-1].strip().split("."):
                part = part.strip().strip('"').strip("'")
                table = table.setdefault(part, {})
            continue
        if "=" not in line:
            if lenient:
                continue
            raise ValueError(f"slo config line {lineno}: not key=value: "
                             f"{line!r}")
        key, _, raw = line.partition("=")
        # strip a trailing comment outside quotes (good enough for the
        # subset: values containing '#' must be quoted, and quoted
        # values must not contain the quote character itself)
        stripped = raw.strip()
        if stripped[:1] in ('"', "'"):
            close = stripped.find(stripped[0], 1)
            if close > 0:
                raw = stripped[: close + 1]
        elif "#" in raw:
            raw = raw.split("#", 1)[0]
        try:
            value = _parse_scalar(raw)
        except ValueError:
            if lenient:
                continue
            raise
        table[key.strip().strip('"').strip("'")] = value
    return root


def _load_toml(path: str, lenient: bool = False) -> dict:
    with open(path, "rb") as f:
        data = f.read()
    try:
        import tomllib  # Python >= 3.11
    except ImportError:
        try:
            import tomli as tomllib  # type: ignore[no-redef]
        except ImportError:
            return _parse_toml_minimal(data.decode(), lenient=lenient)
    import io

    return tomllib.load(io.BytesIO(data))


def load_slo_config(path: Optional[str] = None,
                    pyproject: Optional[str] = None) -> dict:
    """The merged SLO config table: ``[tool.chunkflow.slo]`` from
    ``pyproject`` (default: ``./pyproject.toml`` when present), then —
    overriding it key-by-key at the objective/rule level — the
    ``--slo-config`` file, whose top level IS the slo table. Missing
    files are empty config, a malformed file raises (a typo'd alerting
    config must fail loudly, not silently alert on defaults)."""
    merged: dict = {}

    def fold(table: dict) -> None:
        for key, value in table.items():
            if key in ("objective", "rule") and isinstance(value, dict):
                dest = merged.setdefault(key, {})
                for name, sub in value.items():
                    dest.setdefault(name, {}).update(
                        sub if isinstance(sub, dict) else {})
            else:
                merged[key] = value

    if pyproject is None and os.path.exists("pyproject.toml"):
        pyproject = "pyproject.toml"
    if pyproject and os.path.exists(pyproject):
        # lenient: a pyproject's unrelated sections legitimately use
        # TOML shapes the fallback subset parser cannot read
        data = _load_toml(pyproject, lenient=True)
        fold(data.get("tool", {}).get("chunkflow", {}).get("slo", {}))
    if path:
        fold(_load_toml(path))
    return merged


def _as_tuple(value) -> Tuple[str, ...]:
    if isinstance(value, str):
        return tuple(s.strip() for s in value.split(",") if s.strip())
    return tuple(value or ())


def evaluator_from_config(config: Optional[dict] = None,
                          clock: Callable[[], float] = time.time,
                          source: Optional[Callable[[], dict]] = None,
                          ) -> "SLOEvaluator":
    """Build an evaluator from a merged config table: defaults, with
    per-objective / per-rule overrides (``enabled = false`` drops one,
    unknown names add one) and global ``period_s`` / ``scale`` /
    ``points`` knobs."""
    config = config or {}
    scale = float(config.get("scale", 1.0))
    period_s = float(config.get("period_s", DEFAULT_PERIOD_S))
    objectives: List[Objective] = []
    obj_cfg = dict(config.get("objective") or {})
    for obj in default_objectives():
        over = obj_cfg.pop(obj.name, None)
        if over is None:
            objectives.append(obj)
            continue
        if not over.get("enabled", True):
            continue
        objectives.append(Objective(
            obj.name,
            target=float(over.get("target", obj.target)),
            kind=over.get("kind", obj.kind),
            total=_as_tuple(over.get("total", obj.total)),
            bad=_as_tuple(over.get("bad", obj.bad)),
            qhist=over.get("qhist", obj.qhist),
            threshold_s=over.get("threshold_s", obj.threshold_s),
            description=over.get("description", obj.description),
        ))
    for name, over in sorted(obj_cfg.items()):  # config-only objectives
        if not over.get("enabled", True):
            continue
        objectives.append(Objective(
            name, target=float(over.get("target", 0.999)),
            kind=over.get("kind", "ratio"),
            total=_as_tuple(over.get("total")),
            bad=_as_tuple(over.get("bad")),
            qhist=over.get("qhist"), threshold_s=over.get("threshold_s"),
            description=over.get("description", ""),
        ))
    rules: List[BurnRule] = []
    rule_cfg = dict(config.get("rule") or {})
    for rule in default_rules():
        over = rule_cfg.pop(rule.name, None)
        if over is None:
            rules.append(rule)
            continue
        if not over.get("enabled", True):
            continue
        rules.append(BurnRule(
            rule.name,
            short_s=float(over.get("short_s", rule.short_s)),
            long_s=float(over.get("long_s", rule.long_s)),
            burn=float(over.get("burn", rule.burn)),
            severity=over.get("severity", rule.severity),
        ))
    for name, over in sorted(rule_cfg.items()):  # config-only rules
        if not over.get("enabled", True):
            continue
        rules.append(BurnRule(
            name, short_s=float(over["short_s"]),
            long_s=float(over["long_s"]), burn=float(over["burn"]),
            severity=over.get("severity", "ticket"),
        ))
    return SLOEvaluator(
        objectives=objectives, rules=rules, period_s=period_s,
        scale=scale, points=int(config.get("points", 2048)),
        clock=clock, source=source,
    )


# ---------------------------------------------------------------------------
# the evaluator
# ---------------------------------------------------------------------------
def _slug(name: str) -> str:
    return "".join(ch if ch.isalnum() or ch == "_" else "_"
                   for ch in name)


class SLOEvaluator:
    """Samples cumulative good/bad counts into a bounded ring and runs
    multi-window burn-rate evaluation on every :meth:`tick`. Alert
    state is edge-triggered: one ``alert`` event when a (objective,
    rule) pair starts firing, one ``resolved`` event when it stops —
    never one per tick. Thread-safety: ``tick`` is expected from one
    clock (the telemetry sampler thread), readers (``/alerts``, the
    serving stats payload) may call :meth:`status`/:meth:`firing`
    from any thread; all shared state sits behind one lock and no
    telemetry emission happens under it."""

    def __init__(self, objectives: Optional[List[Objective]] = None,
                 rules: Optional[List[BurnRule]] = None,
                 period_s: float = DEFAULT_PERIOD_S, scale: float = 1.0,
                 points: int = 2048,
                 clock: Callable[[], float] = time.time,
                 source: Optional[Callable[[], dict]] = None):
        if scale <= 0:
            raise ValueError(f"scale must be > 0, got {scale}")
        self.objectives = list(DEFAULT_OBJECTIVES if objectives is None
                               else objectives)
        self.rules = [
            BurnRule(r.name, short_s=r.short_s * scale,
                     long_s=r.long_s * scale, burn=r.burn,
                     severity=r.severity)
            for r in (DEFAULT_RULES if rules is None else rules)
        ]
        self.period_s = float(period_s) * scale
        self.scale = float(scale)
        self._clock = clock
        self._source = source or telemetry.snapshot
        self._lock = threading.Lock()
        # ring of (t, {objective: (total, bad)}) cumulative samples
        self._samples: deque = deque(maxlen=max(8, int(points)))
        self._firing: Dict[Tuple[str, str], dict] = {}
        self._status: dict = {"t": None, "objectives": [], "firing": []}

    # -- sampling -------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> List[dict]:
        """Record one sample and evaluate every (objective, rule) pair;
        returns the alerts that newly fired this tick. This is the
        telemetry tick hook's body (and the test entry point, with an
        injected clock/source)."""
        if now is None:
            now = self._clock()
        snap = self._source()
        counters = snap.get("counters") or {}
        qhists = snap.get("qhists") or {}
        counts = {
            obj.name: obj.counts(counters, qhists)
            for obj in self.objectives
        }
        with self._lock:
            self._samples.append((now, counts))
        return self._evaluate(now, counts)

    def _baseline(self, samples: list, now: float, window_s: float,
                  name: str) -> Tuple[float, float]:
        """Cumulative (total, bad) at the start of the window: the
        newest sample at or before ``now - window_s``, else the oldest
        available (a not-yet-covered window evaluates over the data it
        has — standard Prometheus ``increase`` behavior; a healthy
        service reads 0 bad either way)."""
        cutoff = now - window_s
        chosen = None
        for t, counts in samples:
            if t > cutoff:
                break
            chosen = counts
        if chosen is None:
            chosen = samples[0][1] if samples else {}
        return chosen.get(name, (0.0, 0.0))

    def _burn(self, samples: list, now: float, window_s: float,
              obj: Objective, cur: Tuple[float, float]) -> float:
        """Budget burn rate over one window: bad-share / budget-share.
        1.0 = exactly on budget pace, 0.0 = clean (or no traffic)."""
        base = self._baseline(samples, now, window_s, obj.name)
        d_total = cur[0] - base[0]
        if d_total <= 0:
            return 0.0
        frac = min(1.0, max(0.0, (cur[1] - base[1]) / d_total))
        return frac / (1.0 - obj.target)

    # -- evaluation -----------------------------------------------------
    def _evaluate(self, now: float, counts: dict) -> List[dict]:
        with self._lock:
            samples = list(self._samples)
        from chunkflow_tpu.core import profiling

        new_alerts: List[dict] = []
        emissions: List[Tuple[str, dict]] = []
        status_objs: List[dict] = []
        gauges: List[Tuple[str, float]] = []
        with self._lock:
            for obj in self.objectives:
                cur = counts[obj.name]
                period_burn = self._burn(samples, now, self.period_s,
                                         obj, cur)
                budget_remaining = round(1.0 - period_burn, 6)
                firing_rules = []
                rule_rows = []
                for rule in self.rules:
                    burn_long = self._burn(samples, now, rule.long_s,
                                           obj, cur)
                    burn_short = self._burn(samples, now, rule.short_s,
                                            obj, cur)
                    firing = (burn_long >= rule.burn
                              and burn_short >= rule.burn)
                    key = (obj.name, rule.name)
                    alert = {
                        "alert": f"{obj.name}:{rule.name}",
                        "objective": obj.name,
                        "rule": rule.name,
                        "severity": rule.severity,
                        "target": obj.target,
                        "burn_threshold": rule.burn,
                        "burn_short": round(burn_short, 4),
                        "burn_long": round(burn_long, 4),
                        "short_s": rule.short_s,
                        "long_s": rule.long_s,
                        "budget_remaining": budget_remaining,
                    }
                    if firing and key not in self._firing:
                        self._firing[key] = alert
                        new_alerts.append(alert)
                        emissions.append(("firing", alert))
                    elif not firing and key in self._firing:
                        self._firing.pop(key)
                        emissions.append(("resolved", alert))
                    if firing:
                        firing_rules.append(rule.name)
                    rule_rows.append({
                        "rule": rule.name, "severity": rule.severity,
                        "burn_short": round(burn_short, 4),
                        "burn_long": round(burn_long, 4),
                        "threshold": rule.burn, "firing": firing,
                    })
                # headline burn: the fastest rule's long window — "how
                # fast is the budget going, smoothed past blips"
                headline = rule_rows[0]["burn_long"] if rule_rows else 0.0
                slug = _slug(obj.name)
                gauges.append((f"slo/{slug}/burn_rate", headline))
                gauges.append((f"slo/{slug}/budget_remaining",
                               budget_remaining))
                gauges.append((f"slo/{slug}/firing",
                               1.0 if firing_rules else 0.0))
                status_objs.append({
                    **obj.describe(),
                    "burn_rate": headline,
                    "budget_remaining": budget_remaining,
                    "rules": rule_rows,
                    "firing": firing_rules,
                })
            self._status = {
                "t": now,
                "period_s": self.period_s,
                "objectives": status_objs,
                "firing": sorted(a["alert"]
                                 for a in self._firing.values()),
            }
        # emissions AFTER the lock: telemetry takes its own lock, and a
        # page capture spawns a thread — neither belongs under ours
        for name, value in gauges:
            telemetry.gauge(name, value)
        for state, alert in emissions:
            if state == "firing":
                telemetry.inc("slo/alerts")
                telemetry.event("alert", f"slo/{alert['objective']}",
                                state="firing", **alert)
                if alert["severity"] == "page":
                    profiling.note_slo_page(alert["objective"])
            else:
                telemetry.inc("slo/alerts_resolved")
                telemetry.event("alert", f"slo/{alert['objective']}",
                                state="resolved", alert=alert["alert"],
                                objective=alert["objective"],
                                rule=alert["rule"],
                                severity=alert["severity"])
        return new_alerts

    # -- readers --------------------------------------------------------
    def status(self) -> dict:
        """The ``/alerts`` payload: per-objective burn rates, budget
        remaining, rule states, and the flat firing list."""
        with self._lock:
            status = dict(self._status)
            status["objectives"] = [dict(o) for o in status["objectives"]]
            status["firing"] = list(status["firing"])
        return status

    def firing(self) -> List[str]:
        """Currently-firing alert names (``objective:rule``), sorted."""
        with self._lock:
            return sorted(a["alert"] for a in self._firing.values())


# ---------------------------------------------------------------------------
# process-global lifecycle (rides telemetry's tick/reset hooks)
# ---------------------------------------------------------------------------
_EVALUATOR_LOCK = threading.Lock()
_EVALUATOR: Optional[SLOEvaluator] = None


def _tick(now: float) -> None:
    evaluator = _EVALUATOR
    if evaluator is not None:
        evaluator.tick(now)


def start_slo(config_path: Optional[str] = None,
              pyproject: Optional[str] = None) -> Optional[SLOEvaluator]:
    """Start the process-global SLO evaluator on the telemetry
    time-series tick (idempotent). Returns None — creating no evaluator,
    no hook, no thread — when telemetry or the plane is disabled. The
    CLI calls this for every instrumented run; a malformed config
    raises (fail loudly, not alert on defaults)."""
    global _EVALUATOR
    if not slo_enabled():
        return None
    with _EVALUATOR_LOCK:
        if _EVALUATOR is not None:
            return _EVALUATOR
        config = load_slo_config(config_path, pyproject=pyproject)
        _EVALUATOR = evaluator_from_config(config)
    telemetry.add_tick_hook(_tick)
    # the evaluator's clock is the sampler thread; make sure one runs
    telemetry.start_timeseries()
    return _EVALUATOR


def current() -> Optional[SLOEvaluator]:
    """The live evaluator (``/alerts``, serving stats), or None."""
    return _EVALUATOR


def stop_slo() -> None:
    global _EVALUATOR
    telemetry.remove_tick_hook(_tick)
    with _EVALUATOR_LOCK:
        _EVALUATOR = None


telemetry.add_reset_hook(stop_slo)
