"""Lightweight runtime shape/dtype contracts for chunk-geometry APIs.

The blending stack's correctness hinges on array-shape conventions (czyx
channel-leading chunks, [N, 3] zyx start coordinates, float32
accumulators) that Python can't express in signatures. ``@contract``
declares them at the public entry points and validates every call:

    @contract(out=Spec("co", "z", "y", "x", dtype="float32"),
              weight=Spec("z", "y", "x", dtype="float32"))
    def normalize_blend(out, weight, dtype="float32"): ...

Dimension entries are exact ints, named symbols (equal names must match
across all specs in one call — ``"z"`` above ties ``out`` and ``weight``
to the same grid), or None for don't-care; a leading/trailing ``...``
allows extra dims. Validation reads ONLY static trace-time facts
(``x.shape``/``x.dtype``/``x.ndim``), so under ``jax.jit`` it runs once
at trace time and costs nothing in the compiled program — and via
``jax.eval_shape`` (see ``check_abstract``) a whole program's result
contract can be validated without executing a single FLOP.

Chunk objects participate too: anything exposing ``.shape``/``.dtype``
(numpy arrays, jax arrays, tracers, ``Chunk``) is checkable; values
without a shape are rejected unless the Spec says ``optional=True`` and
the value is None. Set ``CHUNKFLOW_CONTRACTS=0`` to strip all checks
(e.g. a production run that has already been validated).
"""
from __future__ import annotations

import functools
import inspect
import os
from typing import Any, Dict, Optional, Tuple


class ContractError(TypeError):
    """An argument or result violated a declared shape/dtype contract."""


def contracts_enabled() -> bool:
    return os.environ.get("CHUNKFLOW_CONTRACTS", "1").lower() not in (
        "0", "off", "false",
    )


class Spec:
    """Shape/dtype expectation for one array-like value.

    ``Spec("co", "z", "y", "x")``: 4D with dims named for cross-argument
    consistency. ``Spec(ndim=4)``: rank only. ``Spec(..., 3)``: any dims
    then a final extent-3 axis. ``dtype=`` accepts one name or a tuple of
    admissible names.
    """

    def __init__(self, *dims, ndim=None, dtype=None, optional=False):
        self.dims: Optional[Tuple] = tuple(dims) if dims else None
        if self.dims is not None and self.dims.count(Ellipsis) > 1:
            raise ValueError("at most one ... per Spec")
        self.ndim = ndim
        self.dtypes: Optional[Tuple[str, ...]] = (
            (dtype,) if isinstance(dtype, str) else tuple(dtype)
        ) if dtype is not None else None
        self.optional = optional

    def __repr__(self):
        parts = []
        if self.dims is not None:
            parts.append(
                "(" + ", ".join(
                    "..." if d is Ellipsis else repr(d) for d in self.dims
                ) + ")"
            )
        if self.ndim is not None:
            parts.append(f"ndim={self.ndim}")
        if self.dtypes is not None:
            parts.append(f"dtype={'|'.join(self.dtypes)}")
        return f"Spec({', '.join(parts)})"

    # ------------------------------------------------------------------
    def validate(self, value: Any, where: str,
                 bindings: Dict[str, int]) -> None:
        if value is None:
            if self.optional:
                return
            raise ContractError(f"{where}: required value is None")
        shape = getattr(value, "shape", None)
        if shape is None:
            raise ContractError(
                f"{where}: expected an array-like with .shape, got "
                f"{type(value).__name__}"
            )
        shape = tuple(shape)
        if self.ndim is not None:
            allowed = (
                self.ndim if isinstance(self.ndim, tuple) else (self.ndim,)
            )
            if len(shape) not in allowed:
                raise ContractError(
                    f"{where}: rank {len(shape)} (shape {shape}), "
                    f"contract wants ndim {self.ndim}"
                )
        if self.dims is not None:
            self._match_dims(shape, where, bindings)
        if self.dtypes is not None:
            dt = getattr(value, "dtype", None)
            name = getattr(dt, "name", str(dt))
            if name not in self.dtypes:
                raise ContractError(
                    f"{where}: dtype {name}, contract wants "
                    f"{' or '.join(self.dtypes)}"
                )

    def _match_dims(self, shape: Tuple[int, ...], where: str,
                    bindings: Dict[str, int]) -> None:
        dims = self.dims
        if Ellipsis in dims:
            i = dims.index(Ellipsis)
            head, tail = dims[:i], dims[i + 1:]
            if len(shape) < len(head) + len(tail):
                raise ContractError(
                    f"{where}: shape {shape} too short for contract "
                    f"{self!r}"
                )
            pairs = list(zip(head, shape[:len(head)]))
            if tail:
                pairs += list(zip(tail, shape[-len(tail):]))
        else:
            if len(shape) != len(dims):
                raise ContractError(
                    f"{where}: shape {shape} has rank {len(shape)}, "
                    f"contract {self!r} wants {len(dims)}"
                )
            pairs = list(zip(dims, shape))
        for dim, actual in pairs:
            if dim is None:
                continue
            if isinstance(dim, int):
                if actual != dim:
                    raise ContractError(
                        f"{where}: shape {shape} violates contract "
                        f"{self!r} (expected extent {dim}, got {actual})"
                    )
            else:  # named symbol: must be consistent across the call
                prev = bindings.setdefault(str(dim), actual)
                if prev != actual:
                    raise ContractError(
                        f"{where}: dim '{dim}'={actual} conflicts with "
                        f"'{dim}'={prev} bound earlier in this call"
                    )


def contract(_result=None, **arg_specs):
    """Declare per-argument (by name) and result shape contracts.

    ``_result`` is a Spec, or a tuple of Specs for tuple-returning
    functions. Unknown argument names fail at decoration time, so a
    contract can't silently drift off its signature.
    """
    for spec in list(arg_specs.values()) + (
        list(_result) if isinstance(_result, tuple) else
        [_result] if _result is not None else []
    ):
        if not isinstance(spec, Spec):
            raise TypeError(f"contract specs must be Spec, got {spec!r}")

    def decorate(fn):
        sig = inspect.signature(fn)
        unknown = set(arg_specs) - set(sig.parameters)
        if unknown:
            raise TypeError(
                f"@contract on {fn.__qualname__}: no such parameter(s) "
                f"{sorted(unknown)}"
            )

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not contracts_enabled():
                return fn(*args, **kwargs)
            bound = sig.bind(*args, **kwargs)
            bindings: Dict[str, int] = {}
            for name, spec in arg_specs.items():
                if name in bound.arguments:
                    spec.validate(
                        bound.arguments[name],
                        f"{fn.__qualname__}(..{name}..)", bindings,
                    )
            result = fn(*args, **kwargs)
            if _result is not None:
                _validate_result(fn.__qualname__, _result, result, bindings)
            return result

        wrapper.__contract__ = {"args": dict(arg_specs), "result": _result}
        return wrapper

    return decorate


def _validate_result(qualname, result_spec, result, bindings):
    if isinstance(result_spec, tuple):
        if not isinstance(result, tuple) or len(result) != len(result_spec):
            raise ContractError(
                f"{qualname}: result contract wants a {len(result_spec)}-"
                f"tuple, got {type(result).__name__}"
            )
        for i, (spec, value) in enumerate(zip(result_spec, result)):
            spec.validate(value, f"{qualname} -> result[{i}]", bindings)
    else:
        result_spec.validate(result, f"{qualname} -> result", bindings)


def check_abstract(fn, *args, **kwargs):
    """Validate ``fn``'s contract — including the RESULT — without running
    it: ``jax.eval_shape`` traces the function over ShapeDtypeStructs, so
    a malformed program fails in microseconds instead of after a chunk's
    worth of TPU time. Returns the abstract result."""
    import jax

    return jax.eval_shape(fn, *args, **kwargs)
