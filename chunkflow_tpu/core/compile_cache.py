"""Compile-cache layer: persistent XLA artifacts + in-process programs.

Two tiers, attacking two different retrace costs:

1. **Persistent compilation cache** (:func:`enable_persistent_cache`):
   points ``jax.config`` at an on-disk cache directory so a process
   restart (or the driver's bench invocation after tools/tpu_validation.py
   warmed the cache) skips the multi-minute UNet compile. Directory comes
   from ``CHUNKFLOW_JAX_CACHE`` (``0``/``off`` disables); default
   ``~/.cache/chunkflow_tpu/jax_cache``. Entries below
   ``min_compile_time_secs`` are not persisted, so CPU test-suite
   micro-programs never churn the disk.

2. **In-process keyed program cache** (:class:`ProgramCache`): one bounded
   FIFO map from geometry key -> built (jit-wrapped) program, shared by
   every program family the :class:`~chunkflow_tpu.inference.inferencer.
   Inferencer` builds (scatter, fold, patch-sharded, spatial, spatial2d).
   The key is derived from the *bucketed* run shape (``shape_bucket``), so
   ragged edge chunks that pad into the same bucket hit the same entry and
   never retrace. ``builds``/``hits`` counters make trace counts a
   testable invariant (tests/inference/test_compile_cache.py).

Donation note: programs cached here donate their chunk buffer
(``donate_argnums=(0,)``, GL005) — see docs/performance.md for the
buffer-lifetime contract. When XLA cannot alias the donated input to the
output (e.g. 1 input channel, 3 affinity output channels) it emits a
"donated buffers were not usable" warning on every compile; that is the
expected, harmless half of the donation bargain, so it is silenced
process-wide on import of this module.
"""
from __future__ import annotations

import os
import threading
import warnings
from typing import Callable, Hashable, Optional

from chunkflow_tpu.core import profiling, telemetry

# Donation is best-effort by design: a chunk buffer that cannot alias the
# program's output is simply dropped, and the warning would otherwise fire
# once per compiled geometry (ops/fold_blend.py, parallel/*, inferencer).
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)

_LOCK = threading.Lock()
_PERSISTENT_DIR: Optional[str] = None


class RetraceWarning(UserWarning):
    """More program builds than the planned bucket count (see
    :class:`ProgramCache`)."""


def persistent_cache_dir() -> Optional[str]:
    """The on-disk XLA cache directory in effect, or None when the
    persistent cache is disabled/unavailable (CLI end-of-run summary)."""
    return _PERSISTENT_DIR


def default_cache_dir() -> str:
    return os.path.join(
        os.path.expanduser("~"), ".cache", "chunkflow_tpu", "jax_cache"
    )


def enable_persistent_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Enable jax's on-disk compilation cache; returns the directory in
    effect, or None when disabled/unavailable.

    Idempotent and never raises: the cache is an optimization, not a
    dependency. Precedence: explicit ``cache_dir`` argument, then
    ``CHUNKFLOW_JAX_CACHE`` (``0``/``off``/``false`` disables), then
    :func:`default_cache_dir`.
    """
    global _PERSISTENT_DIR
    env = os.environ.get("CHUNKFLOW_JAX_CACHE", "")
    if cache_dir is None:
        if env.lower() in ("0", "off", "false"):
            return None
        cache_dir = env or default_cache_dir()
    with _LOCK:
        if _PERSISTENT_DIR == cache_dir:
            return _PERSISTENT_DIR
        try:
            import jax

            jax.config.update("jax_compilation_cache_dir", cache_dir)
            # persist everything that took real compile time; tiny CPU
            # test programs stay in-memory only
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1
            )
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0
            )
            _PERSISTENT_DIR = cache_dir
        except Exception as e:
            import sys

            print(f"compilation cache unavailable: {e}", file=sys.stderr)
            return None
    return _PERSISTENT_DIR


class ProgramCache:
    """Bounded FIFO cache of built programs keyed on trace geometry.

    Each entry's closure pins its engine (and params) alive, so the cache
    is bounded: past ``maxsize`` the oldest entry is dropped (same policy
    as parallel/distributed._PROGRAM_CACHE). ``builds`` counts builder
    invocations — i.e. traces of new program geometry — and ``hits``
    counts reuses, so tests can assert "two same-bucket chunks, one
    trace" as an invariant instead of a benchmark. Both also feed the
    process-global telemetry counters (``compile_cache/builds``,
    ``compile_cache/hits``) the CLI surfaces at end of run.

    Retrace watchdog: ``expected_builds`` is the bucket count the owner
    planned for (with shape bucketing, ragged chunks collapse into a
    handful of buckets). The first build past it raises a
    ``RetraceWarning`` — the signature of a silent retrace-per-chunk
    (e.g. bucketing misconfigured, a key deriving from the RAW rather
    than bucketed shape) that would otherwise only show up as an
    unexplained N-minute compile stall per task.
    """

    def __init__(self, maxsize: int = 16,
                 expected_builds: Optional[int] = None,
                 label: str = "programs"):
        if maxsize < 1:
            raise ValueError(f"maxsize must be >= 1, got {maxsize}")
        self.maxsize = maxsize
        self.expected_builds = expected_builds
        self.label = label
        self.builds = 0
        self.hits = 0
        self._warned = False
        self._entries: dict = {}
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: Hashable) -> bool:
        return key in self._entries

    def items(self):
        """Snapshot of (key, program) pairs (debugging, tests)."""
        with self._lock:
            return list(self._entries.items())

    def peek(self, key: Hashable, default=None):
        """The cached program for ``key`` without building or counting."""
        return self._entries.get(key, default)

    def get(self, key: Hashable, build: Callable[[], object]):
        """Return the cached program for ``key``, building (and counting a
        trace) on first sight. Eviction is FIFO by insertion order."""
        with self._lock:
            if key in self._entries:
                self.hits += 1
                hit = self._entries[key]
            else:
                hit = None
        if hit is not None:
            telemetry.inc("compile_cache/hits")
            return hit
        # build outside the lock: builders jit-trace, which can re-enter
        # (a fold program build may consult the same Inferencer)
        with telemetry.span("compile_cache/build", label=self.label) as sp:
            program = build()
        # cost ledger (core/profiling.py): the wrapper times the first
        # invocation — the one that pays trace + XLA compile — and
        # captures the program's XLA cost analysis; a no-op passthrough
        # under CHUNKFLOW_TELEMETRY=0 or for non-jit cache entries
        program = profiling.instrument_program(
            program, key, label=self.label,
            build_s=getattr(sp, "duration", 0.0),
        )
        raced = False
        with self._lock:
            if key not in self._entries:
                self.builds += 1
                self._entries[key] = program
                while len(self._entries) > self.maxsize:
                    self._entries.pop(next(iter(self._entries)))
            else:
                # lost a race: keep the first-published program so every
                # caller shares one compiled executable
                self.hits += 1
                raced = True
            result = self._entries[key]
        telemetry.inc("compile_cache/hits" if raced else
                      "compile_cache/builds")
        if not raced:
            self._watchdog()
        return result

    def _watchdog(self) -> None:
        """Warn (once per cache) when builds exceed the planned bucket
        count — the retrace-per-chunk signature."""
        if (self.expected_builds is None or self._warned
                or self.builds <= self.expected_builds):
            return
        self._warned = True
        telemetry.inc("compile_cache/retrace_warnings")
        # a retrace-per-chunk in flight is the highest-value moment for
        # device evidence: one bounded profiler window (cooldown-gated,
        # core/profiling.py) captures what the extra compiles cost
        profiling.note_retrace(self.label)
        warnings.warn(
            f"ProgramCache[{self.label}]: {self.builds} program builds "
            f"exceed the expected bucket count "
            f"({self.expected_builds}) — likely a retrace per chunk "
            f"(check --shape-bucket / key derivation); every extra "
            f"build pays a full XLA compile",
            RetraceWarning,
            stacklevel=3,
        )

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
