"""Unified telemetry: counters, gauges, histograms, spans, JSONL events.

The paper's fleet story (3600 GPU nodes, 18 PB of output) rests on
knowing, per task and per operator, where wall-clock goes. The reference
ships only coarse per-task ``log['timer']`` dicts aggregated offline by
``log_summary``; our pipelined TPU port has far more internal state —
ring occupancy, stage/compute/drain stall time, program-cache builds vs.
hits — and none of it was visible anywhere. This module is the one
substrate every perf-sensitive layer reports into:

* a process-global registry of **counters** (:func:`inc`), **gauges**
  (:func:`gauge`) and **histograms** (:func:`observe`), aggregated
  in-process and snapshot-able at any time (:func:`snapshot`);
* a **span** tracer (``with span("inference/fold"):``) that both feeds
  the histogram registry and, when a metrics dir is configured
  (:func:`configure`, CLI ``--metrics-dir``), appends one JSONL event
  per span so offline tooling (``flow/log_summary.py``) can attribute
  pipeline stalls after the fact;
* an end-of-run :func:`summary_table` the CLI prints under ``-v``.

Design rules, in priority order:

1. **Never inside jit.** Telemetry is host-side bookkeeping; a
   ``time.perf_counter`` or counter increment inside a traced function
   would either concretize tracers or silently stop measuring (trace
   time is not run time). graftlint rule GL007 enforces this statically.
2. **Near-zero overhead, zero when off.** ``CHUNKFLOW_TELEMETRY=0``
   turns every entry point into an early-out: no locks, no allocation,
   no file IO, nothing emitted. Enabled-path span cost is two
   ``perf_counter`` calls plus one locked dict update.
3. **Zero dependencies.** Events are plain JSON lines; aggregation
   needs nothing beyond the stdlib (pandas enters only in
   ``log_summary``'s optional pretty printing).

Event schema (one JSON object per line; see docs/observability.md):

    {"kind": "span",    "name": "...", "t": <epoch end>, "dur_s": ...,
     "pid": ..., ...attrs}
    {"kind": "gauge",   "name": "...", "t": <epoch>, "value": ...}
    {"kind": "snapshot", "t": <epoch>, "counters": {...}, "gauges": {...},
     "hists": {name: {count,total,min,max}}}

Span naming convention: ``<layer>/<phase>`` — ``pipeline/stage``,
``pipeline/compute``, ``pipeline/drain``, ``scheduler/load``,
``scheduler/post``, ``scheduler/write``, ``op/<operator-name>``,
``inference/<family>``. Counters likewise: ``compile_cache/builds``,
``pipeline/tasks``. The adaptive scheduler (flow/scheduler.py) both
*consumes* this stream (per-phase stall totals via :func:`hist_totals`
drive its depth controller) and *feeds* it: ``scheduler/depth/<knob>``
gauges and ``depth_change`` events record every widening decision.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

__all__ = [
    "enabled", "configure", "configured_path", "inc", "gauge", "observe",
    "span", "event", "snapshot", "flush", "reset", "summary_table",
    "hist_totals",
]

_OFF_VALUES = ("0", "off", "false", "no")


def enabled() -> bool:
    """The kill switch, re-read per call so tests (and long-lived workers
    reacting to a config push) can flip it at runtime."""
    return os.environ.get("CHUNKFLOW_TELEMETRY", "1").lower() \
        not in _OFF_VALUES


class _Registry:
    """Process-global metric state + optional JSONL sink. All mutation is
    behind one lock; the disabled path never takes it."""

    def __init__(self):
        self.lock = threading.Lock()
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        # name -> [count, total, min, max]
        self.hists: Dict[str, list] = {}
        self.sink = None
        self.sink_path: Optional[str] = None

    # -- metric updates (caller holds no lock) -------------------------
    def add_counter(self, name: str, n: float) -> None:
        with self.lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def set_gauge(self, name: str, value: float) -> None:
        with self.lock:
            self.gauges[name] = value

    def add_hist(self, name: str, value: float) -> None:
        with self.lock:
            h = self.hists.get(name)
            if h is None:
                self.hists[name] = [1, value, value, value]
            else:
                h[0] += 1
                h[1] += value
                h[2] = min(h[2], value)
                h[3] = max(h[3], value)

    # -- sink ----------------------------------------------------------
    def emit(self, payload: dict) -> None:
        with self.lock:
            if self.sink is None:
                return
            try:
                self.sink.write(json.dumps(payload) + "\n")
            except (OSError, ValueError):
                # a full disk / closed sink must never take the pipeline
                # down; drop the event and keep computing
                self.sink = None


_REG = _Registry()


def configure(metrics_dir: Optional[str]) -> Optional[str]:
    """Open (or close, with None) the per-process JSONL sink under
    ``metrics_dir``. Returns the file path in effect, or None when
    disabled — with ``CHUNKFLOW_TELEMETRY=0`` nothing is created, so an
    off run leaves no trace on disk."""
    with _REG.lock:
        if _REG.sink is not None:
            try:
                _REG.sink.close()
            except OSError:
                pass
            _REG.sink, _REG.sink_path = None, None
    if metrics_dir is None or not enabled():
        return None
    os.makedirs(metrics_dir, exist_ok=True)
    path = os.path.join(metrics_dir, f"telemetry-{os.getpid()}.jsonl")
    sink = open(path, "a")
    with _REG.lock:
        _REG.sink, _REG.sink_path = sink, path
    return path


def configured_path() -> Optional[str]:
    return _REG.sink_path


def inc(name: str, n: float = 1) -> None:
    """Increment a counter. Counters are aggregate-only: they ride the
    end-of-run snapshot event, not one line per increment."""
    if not enabled():
        return
    _REG.add_counter(name, n)


def gauge(name: str, value: float) -> None:
    """Record an instantaneous level (ring occupancy, queue depth). Kept
    as last-value in the registry AND folded into the histogram of the
    same name so mean occupancy is queryable offline; emits one event
    when a sink is configured."""
    if not enabled():
        return
    _REG.set_gauge(name, value)
    _REG.add_hist(name, value)
    if _REG.sink is not None:
        _REG.emit({"kind": "gauge", "name": name, "t": time.time(),
                   "value": value})


def observe(name: str, value: float) -> None:
    """Fold a sample into a histogram without emitting an event."""
    if not enabled():
        return
    _REG.add_hist(name, value)


def event(kind: str, name: str, **attrs) -> None:
    """Emit a free-form event line (sink configured and telemetry on)."""
    if not enabled() or _REG.sink is None:
        return
    payload = {"kind": kind, "name": name, "t": time.time()}
    payload.update(attrs)
    _REG.emit(payload)


class _NullSpan:
    """The disabled span: a shared, stateless context manager."""

    __slots__ = ()
    duration = 0.0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "attrs", "t0", "duration")

    def __init__(self, name: str, attrs):
        self.name = name
        self.attrs = attrs
        self.duration = 0.0

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.duration = time.perf_counter() - self.t0
        _REG.add_hist(self.name, self.duration)
        if _REG.sink is not None:
            payload = {"kind": "span", "name": self.name, "t": time.time(),
                       "dur_s": self.duration, "pid": os.getpid()}
            if self.attrs:
                payload.update(self.attrs)
            _REG.emit(payload)
        return False


def span(name: str, **attrs):
    """Time a block: ``with span("pipeline/drain"): ...``. Feeds the
    histogram registry and (sink configured) emits one JSONL event. The
    span object exposes ``.duration`` after exit for callers that keep a
    legacy timer view."""
    if not enabled():
        return _NULL_SPAN
    return _Span(name, attrs)


def hist_totals(names) -> Dict[str, float]:
    """Cumulative histogram totals (seconds for span histograms) for the
    given names; 0.0 for a name with no samples yet. The adaptive
    scheduler's depth controller (flow/scheduler.py) polls per-phase
    stall totals through this every few tasks — one lock, no per-name
    dict rebuild — instead of materializing a full :func:`snapshot`.
    Disabled telemetry returns all-zero totals, which the controller
    reads as "no stall signal": depths stay at their static initial
    values (the documented graceful fallback)."""
    if not enabled():
        return {name: 0.0 for name in names}
    with _REG.lock:
        return {
            name: (_REG.hists[name][1] if name in _REG.hists else 0.0)
            for name in names
        }


def snapshot() -> dict:
    """Copy of all aggregated metrics:
    ``{"counters": {...}, "gauges": {...}, "hists": {name:
    {"count", "total", "min", "max", "mean"}}}``."""
    with _REG.lock:
        hists = {
            name: {
                "count": h[0],
                "total": h[1],
                "min": h[2],
                "max": h[3],
                "mean": h[1] / h[0] if h[0] else 0.0,
            }
            for name, h in _REG.hists.items()
        }
        return {
            "counters": dict(_REG.counters),
            "gauges": dict(_REG.gauges),
            "hists": hists,
        }


def flush() -> None:
    """Write the aggregate snapshot as a final event and flush the sink.
    Counters (builds/hits, task counts) reach the JSONL stream here —
    they are aggregate-only during the run."""
    if not enabled():
        return
    snap = snapshot()
    if _REG.sink is not None:
        _REG.emit({"kind": "snapshot", "t": time.time(),
                   "pid": os.getpid(), **snap})
        with _REG.lock:
            if _REG.sink is not None:
                try:
                    _REG.sink.flush()
                except OSError:
                    pass


def reset() -> None:
    """Clear all metrics and close the sink (tests; each CLI invocation
    is one process, so production never needs this)."""
    with _REG.lock:
        _REG.counters.clear()
        _REG.gauges.clear()
        _REG.hists.clear()
        if _REG.sink is not None:
            try:
                _REG.sink.close()
            except OSError:
                pass
        _REG.sink, _REG.sink_path = None, None


# -- end-of-run reporting ----------------------------------------------
def summary_table() -> str:
    """Fixed-width end-of-run table of spans (count/total/mean/max),
    counters and last-value gauges — the CLI prints this under ``-v``.
    Empty string when nothing was recorded."""
    snap = snapshot()
    lines = []
    if snap["hists"]:
        lines.append(
            f"  {'span':<28} {'count':>7} {'total_s':>9} {'mean_s':>9} "
            f"{'max_s':>9}"
        )
        for name in sorted(snap["hists"]):
            h = snap["hists"][name]
            lines.append(
                f"  {name:<28} {h['count']:>7} {h['total']:>9.3f} "
                f"{h['mean']:>9.4f} {h['max']:>9.4f}"
            )
    if snap["counters"]:
        lines.append(f"  {'counter':<28} {'value':>7}")
        for name in sorted(snap["counters"]):
            value = snap["counters"][name]
            lines.append(f"  {name:<28} {value:>7g}")
    if snap["gauges"]:
        lines.append(f"  {'gauge (last)':<28} {'value':>7}")
        for name in sorted(snap["gauges"]):
            lines.append(f"  {name:<28} {snap['gauges'][name]:>7g}")
    if not lines:
        return ""
    return "\n".join(["telemetry summary:"] + lines)
