"""Unified telemetry: counters, gauges, histograms, spans, JSONL events.

The paper's fleet story (3600 GPU nodes, 18 PB of output) rests on
knowing, per task and per operator, where wall-clock goes. The reference
ships only coarse per-task ``log['timer']`` dicts aggregated offline by
``log_summary``; our pipelined TPU port has far more internal state —
ring occupancy, stage/compute/drain stall time, program-cache builds vs.
hits — and none of it was visible anywhere. This module is the one
substrate every perf-sensitive layer reports into:

* a process-global registry of **counters** (:func:`inc`), **gauges**
  (:func:`gauge`) and **histograms** (:func:`observe`), aggregated
  in-process and snapshot-able at any time (:func:`snapshot`);
* a **span** tracer (``with span("inference/fold"):``) that both feeds
  the histogram registry and, when a metrics dir is configured
  (:func:`configure`, CLI ``--metrics-dir``), appends one JSONL event
  per span so offline tooling (``flow/log_summary.py``) can attribute
  pipeline stalls after the fact;
* an end-of-run :func:`summary_table` the CLI prints under ``-v``.

Design rules, in priority order:

1. **Never inside jit.** Telemetry is host-side bookkeeping; a
   ``time.perf_counter`` or counter increment inside a traced function
   would either concretize tracers or silently stop measuring (trace
   time is not run time). graftlint rule GL007 enforces this statically.
2. **Near-zero overhead, zero when off.** ``CHUNKFLOW_TELEMETRY=0``
   turns every entry point into an early-out: no locks, no allocation,
   no file IO, nothing emitted. Enabled-path span cost is two
   ``perf_counter`` calls plus one locked dict update.
3. **Zero dependencies.** Events are plain JSON lines; aggregation
   needs nothing beyond the stdlib (pandas enters only in
   ``log_summary``'s optional pretty printing).

Event schema (one JSON object per line; see docs/observability.md):

    {"kind": "span",    "name": "...", "t": <epoch end>, "dur_s": ...,
     "pid": ..., ...attrs}
    {"kind": "gauge",   "name": "...", "t": <epoch>, "value": ...}
    {"kind": "snapshot", "t": <epoch>, "counters": {...}, "gauges": {...},
     "hists": {name: {count,total,min,max}}}

Span naming convention: ``<layer>/<phase>`` — ``pipeline/stage``,
``pipeline/compute``, ``pipeline/drain``, ``scheduler/load``,
``scheduler/post``, ``scheduler/write``, ``op/<operator-name>``,
``inference/<family>``. Counters likewise: ``compile_cache/builds``,
``pipeline/tasks``. The adaptive scheduler (flow/scheduler.py) both
*consumes* this stream (per-phase stall totals via :func:`hist_totals`
drive its depth controller) and *feeds* it: ``scheduler/depth/<knob>``
gauges and ``depth_change`` events record every widening decision.

Fleet correlation (docs/observability.md "Fleet view"): every emitted
line is stamped with this process's :func:`worker_id` (stable host+pid
identity, ``CHUNKFLOW_WORKER_ID`` override for pid-namespaced
containers), and — while a task is in flight under
:func:`task_context` — with the task's ``trace_id``, the id minted when
the task was first submitted to a queue (parallel/queues.py). Merged
multi-worker JSONL therefore reconstructs a task's full history across
claim/retry/requeue hops between workers. The task context is a
``contextvars.ContextVar``: thread- and generator-safe on the host
side, and statically banned inside jitted code like every other
telemetry call (graftlint GL007).

Time series (docs/observability.md "SLO view"): the registry alone
answers "how much, total" — an SLO plane needs "how fast, lately".
:func:`start_timeseries` runs a bounded ring sampler in a daemon
thread: every ``CHUNKFLOW_TS_INTERVAL`` seconds it derives counter
*rates*, copies gauges, and estimates qhist p50/p99 into per-metric
``(t, value)`` rings of ``CHUNKFLOW_TS_POINTS`` points
(:func:`timeseries` reads them), flushes one ``timeseries``-kind event
— including the raw cumulative qhist buckets, which sum across workers
— to the JSONL stream so history survives worker death, and then runs
the registered :func:`add_tick_hook` callbacks (the SLO evaluator,
core/slo.py, rides here). ``CHUNKFLOW_TELEMETRY=0`` creates no sampler
thread, no rings, no events.
"""
from __future__ import annotations

import contextvars
import json
import os
import re
import socket
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

__all__ = [
    "enabled", "configure", "configured_path", "inc", "gauge", "observe",
    "span", "event", "snapshot", "flush", "reset", "summary_table",
    "hist_totals", "worker_id", "task_context", "current_trace_id",
    "snapshot_interval", "add_flush_hook", "add_reset_hook",
    "observe_quantile", "quantile", "quantile_from_buckets",
    "QUANTILE_BOUNDS", "timeseries", "start_timeseries",
    "stop_timeseries", "timeseries_running", "add_tick_hook",
    "remove_tick_hook", "ts_interval", "ts_points",
    "chip_gauge", "CHIP_METRIC_RE",
]

#: Per-chip metric naming convention: ``<plane>/chip/<i>/<metric>``
#: (``device/chip/0/bytes_in_use``, ``shard/chip/3/voxels``). Every
#: consumer that wants to fold the chip index back out of the name —
#: the ``/metrics`` renderer turns it into a ``chip`` label, the
#: log-summary MESH block groups by it — matches against this one
#: regex so the convention cannot drift between emitters and readers.
CHIP_METRIC_RE = re.compile(
    r"^(?P<plane>[^/]+(?:/[^/]+)*)/chip/(?P<chip>\d+)/(?P<metric>.+)$")

_OFF_VALUES = ("0", "off", "false", "no")


def enabled() -> bool:
    """The kill switch, re-read per call so tests (and long-lived workers
    reacting to a config push) can flip it at runtime."""
    return os.environ.get("CHUNKFLOW_TELEMETRY", "1").lower() \
        not in _OFF_VALUES


# ---------------------------------------------------------------------------
# fleet identity + per-task trace context
# ---------------------------------------------------------------------------
_WORKER_ID: Optional[str] = None
_WORKER_ID_LOCK = threading.Lock()


def worker_id() -> str:
    """Stable identity of this worker process: ``<hostname>-<pid>``, or
    the ``CHUNKFLOW_WORKER_ID`` env override (pid-namespaced containers
    where every worker is pid 1, and tests simulating a fleet in one
    process). Cached after first use — double-checked under a lock,
    since the time-series sampler thread stamps events too; :func:`reset`
    clears the cache (a forked child should call
    :func:`configure`/:func:`reset` anyway — it must not inherit the
    parent's sink)."""
    global _WORKER_ID
    wid = _WORKER_ID
    if wid is None:
        with _WORKER_ID_LOCK:
            if _WORKER_ID is None:
                _WORKER_ID = (
                    os.environ.get("CHUNKFLOW_WORKER_ID")
                    or f"{socket.gethostname()}-{os.getpid()}"
                )
            wid = _WORKER_ID
    return wid


_TASK_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "chunkflow_trace_id", default=None
)


def current_trace_id() -> Optional[str]:
    """The trace id of the task currently in flight on this
    thread/context, or None outside any :func:`task_context`."""
    return _TASK_CTX.get()


class _TaskContext:
    """Scoped trace-id binding; ``trace_id=None`` is a no-op so an
    un-traced task never clobbers an enclosing context."""

    __slots__ = ("trace_id", "_token")

    def __init__(self, trace_id: Optional[str]):
        self.trace_id = trace_id
        self._token = None

    def __enter__(self):
        if self.trace_id is not None:
            self._token = _TASK_CTX.set(self.trace_id)
        return self

    def __exit__(self, *exc):
        if self._token is not None:
            _TASK_CTX.reset(self._token)
            self._token = None
        return False


def task_context(trace_id: Optional[str]):
    """Bind ``trace_id`` for the dynamic extent of a ``with`` block:
    every span/gauge/event emitted inside is stamped with it (plus
    :func:`worker_id`), so a task's history is reconstructable from
    merged multi-worker JSONL. Call sites hold the task dict or
    lifecycle object: the runtime operator wrapper, the adaptive
    scheduler's dispatch/finalize, the lifecycle claim/commit/release
    paths. Host-side only (GL007)."""
    return _TaskContext(trace_id)


def _stamp(payload: dict) -> dict:
    """Fleet-correlation stamp on an outgoing JSONL payload."""
    payload["worker"] = worker_id()
    trace_id = _TASK_CTX.get()
    if trace_id is not None:
        payload["trace_id"] = trace_id
    return payload


def snapshot_interval() -> int:
    """Tasks between periodic snapshot events in the supervised claim
    loop (``CHUNKFLOW_TELEMETRY_SNAPSHOT_EVERY``, default 8; 0
    disables). Without it a killed worker leaves no counter record —
    snapshots otherwise ride only the end-of-run flush()."""
    raw = os.environ.get("CHUNKFLOW_TELEMETRY_SNAPSHOT_EVERY", "")
    try:
        return max(0, int(raw)) if raw else 8
    except ValueError:
        return 8


def _max_sink_bytes() -> int:
    """JSONL rotation threshold (``CHUNKFLOW_TELEMETRY_MAX_MB``,
    default a generous 256 MB; <=0 disables rotation)."""
    raw = os.environ.get("CHUNKFLOW_TELEMETRY_MAX_MB", "")
    try:
        mb = float(raw) if raw else 256.0
    except ValueError:
        mb = 256.0
    return int(mb * (1 << 20))


def _keep_generations() -> int:
    """Total JSONL generations kept per worker, live file included
    (``CHUNKFLOW_TELEMETRY_KEEP``, default 2 = the live file plus one
    ``.1`` rotation; minimum 1 = rotation truncates outright). A long
    SLO run whose time-series history must survive rotation raises
    this — each extra generation is another ``CHUNKFLOW_TELEMETRY_MAX_MB``
    of history ``load_telemetry_dir`` can still read."""
    raw = os.environ.get("CHUNKFLOW_TELEMETRY_KEEP", "")
    try:
        return max(1, int(raw)) if raw else 2
    except ValueError:
        return 2


#: Upper bucket bounds (seconds) of the quantile histograms — log-spaced
#: from 1 ms to 2 min, with an implicit +inf overflow bucket. Chosen for
#: request-latency distributions (docs/serving.md): a serving p50 of a
#: few ms and a p99 of seconds both land mid-range. Fixed bounds (not
#: per-process sketches) are what make bucket counts summable across
#: workers in ``log-summary --fleet`` and renderable as a Prometheus
#: ``histogram`` (parallel/restapi.py).
QUANTILE_BOUNDS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)


def quantile_from_buckets(qhist: dict, q: float) -> Optional[float]:
    """Estimate the ``q``-quantile (0..1) from a snapshot-form quantile
    histogram ``{"count": n, "buckets": [..per-bound.., overflow]}`` by
    linear interpolation inside the covering bucket. Returns None for an
    empty histogram; the overflow bucket reports its lower bound (the
    estimate saturates at the largest tracked bound). Shared by
    ``log-summary`` (merged multi-worker buckets) and live reporting so
    every p50/p99 figure is computed one way."""
    count = qhist.get("count", 0)
    buckets = qhist.get("buckets") or []
    if not count or not buckets:
        return None
    rank = q * count
    seen = 0.0
    lower = 0.0
    for i, n in enumerate(buckets):
        upper = (QUANTILE_BOUNDS[i] if i < len(QUANTILE_BOUNDS)
                 else QUANTILE_BOUNDS[-1])
        if n and seen + n >= rank:
            if i >= len(QUANTILE_BOUNDS):
                return QUANTILE_BOUNDS[-1]  # overflow: saturate
            frac = (rank - seen) / n
            return lower + frac * (upper - lower)
        seen += n
        lower = upper
    return QUANTILE_BOUNDS[-1]


class _Registry:
    """Process-global metric state + optional JSONL sink. All mutation is
    behind one lock; the disabled path never takes it."""

    def __init__(self):
        self.lock = threading.Lock()
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        # name -> [count, total, min, max]
        self.hists: Dict[str, list] = {}
        # name -> [count, total, min, max, [bucket counts + overflow]]
        self.qhists: Dict[str, list] = {}
        self.sink = None
        self.sink_path: Optional[str] = None
        self.sink_bytes = 0
        self.max_sink_bytes = 0

    # -- metric updates (caller holds no lock) -------------------------
    def add_counter(self, name: str, n: float) -> None:
        with self.lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def set_gauge(self, name: str, value: float) -> None:
        with self.lock:
            self.gauges[name] = value

    def add_hist(self, name: str, value: float) -> None:
        with self.lock:
            h = self.hists.get(name)
            if h is None:
                self.hists[name] = [1, value, value, value]
            else:
                h[0] += 1
                h[1] += value
                h[2] = min(h[2], value)
                h[3] = max(h[3], value)

    def add_qhist(self, name: str, value: float) -> None:
        with self.lock:
            h = self.qhists.get(name)
            if h is None:
                h = self.qhists[name] = [
                    0, 0.0, value, value,
                    [0] * (len(QUANTILE_BOUNDS) + 1),
                ]
            h[0] += 1
            h[1] += value
            h[2] = min(h[2], value)
            h[3] = max(h[3], value)
            for i, bound in enumerate(QUANTILE_BOUNDS):
                if value <= bound:
                    h[4][i] += 1
                    break
            else:
                h[4][-1] += 1  # overflow

    # -- sink ----------------------------------------------------------
    def emit(self, payload: dict) -> None:
        with self.lock:
            if self.sink is None:
                return
            line = json.dumps(payload) + "\n"
            try:
                self.sink.write(line)
            except (OSError, ValueError):
                # a full disk / closed sink must never take the pipeline
                # down; drop the event and keep computing
                self.sink = None
                return
            self.sink_bytes += len(line)
            if 0 < self.max_sink_bytes < self.sink_bytes:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Size-capped rotation (caller holds the lock): generations
        shift up one suffix (``<path>.1`` is the youngest rotation,
        ``<path>.N`` the oldest) and a fresh file opens at ``<path>``
        — a long-lived worker keeps at most ``CHUNKFLOW_TELEMETRY_KEEP``
        generations on disk (default 2: live + ``.1``), anything older
        is swept, including stale generations left by a previously
        higher KEEP. ``load_telemetry_dir`` reads every surviving
        generation oldest-first (flow/log_summary.py), so the
        time-series/SLO history window is KEEP × MAX_MB, not one file."""
        try:
            self.sink.close()
        except OSError:
            pass
        base = self.sink_path
        rotations = _keep_generations() - 1
        try:
            # shift from the oldest kept slot down so nothing clobbers
            for n in range(rotations, 1, -1):
                if os.path.exists(f"{base}.{n - 1}"):
                    os.replace(f"{base}.{n - 1}", f"{base}.{n}")
            if rotations >= 1:
                os.replace(base, base + ".1")
            else:
                os.remove(base)  # KEEP=1: truncate, keep no history
            n = rotations + 1
            while os.path.exists(f"{base}.{n}"):
                os.remove(f"{base}.{n}")
                n += 1
            self.sink = open(base, "a", buffering=1)
            self.sink_bytes = 0
        except OSError:
            self.sink = None  # unrotatable sink: stop emitting, keep computing


_REG = _Registry()


def configure(metrics_dir: Optional[str]) -> Optional[str]:
    """Open (or close, with None) the per-worker JSONL sink under
    ``metrics_dir``. Returns the file path in effect, or None when
    disabled — with ``CHUNKFLOW_TELEMETRY=0`` nothing is created, so an
    off run leaves no trace on disk. The file is named by
    :func:`worker_id` (host+pid by default, so one file per process as
    before); when it outgrows ``CHUNKFLOW_TELEMETRY_MAX_MB`` it rotates
    to a ``.1`` suffix."""
    with _REG.lock:
        if _REG.sink is not None:
            try:
                _REG.sink.close()
            except OSError:
                pass
            _REG.sink, _REG.sink_path = None, None
    if metrics_dir is None or not enabled():
        return None
    os.makedirs(metrics_dir, exist_ok=True)
    safe = "".join(
        ch if ch.isalnum() or ch in "._-" else "_" for ch in worker_id()
    )
    path = os.path.join(metrics_dir, f"telemetry-{safe}.jsonl")
    # line-buffered: each event line reaches the OS page cache as it is
    # emitted (no fsync — this is cheap), so a worker that dies by
    # SIGKILL / spot preemption still leaves its span and task events on
    # disk for crash-recovery trace reconstruction (parallel/fleet.py;
    # a block-buffered sink would lose the tail silently)
    sink = open(path, "a", buffering=1)
    try:
        existing = os.path.getsize(path)
    except OSError:
        existing = 0
    with _REG.lock:
        _REG.sink, _REG.sink_path = sink, path
        _REG.sink_bytes = existing
        _REG.max_sink_bytes = _max_sink_bytes()
    return path


def configured_path() -> Optional[str]:
    return _REG.sink_path


def inc(name: str, n: float = 1) -> None:
    """Increment a counter. Counters are aggregate-only: they ride the
    end-of-run snapshot event, not one line per increment."""
    if not enabled():
        return
    _REG.add_counter(name, n)


def gauge(name: str, value: float) -> None:
    """Record an instantaneous level (ring occupancy, queue depth). Kept
    as last-value in the registry AND folded into the histogram of the
    same name so mean occupancy is queryable offline; emits one event
    when a sink is configured."""
    if not enabled():
        return
    _REG.set_gauge(name, value)
    _REG.add_hist(name, value)
    if _REG.sink is not None:
        _REG.emit(_stamp({"kind": "gauge", "name": name, "t": time.time(),
                          "value": value}))


def chip_gauge(plane: str, chip: int, metric: str, value: float) -> None:
    """Record a per-chip instantaneous level under the
    ``<plane>/chip/<i>/<metric>`` convention (:data:`CHIP_METRIC_RE`).
    A thin veneer over :func:`gauge`, so per-chip values get everything
    plain gauges get — last-value registry entry, occupancy histogram,
    one JSONL event, and a ``gauge:<name>`` timeseries ring — while
    keeping the name shape readers can fold into a ``chip`` label."""
    gauge(f"{plane}/chip/{int(chip)}/{metric}", value)


def observe(name: str, value: float) -> None:
    """Fold a sample into a histogram without emitting an event."""
    if not enabled():
        return
    _REG.add_hist(name, value)


def observe_quantile(name: str, value: float) -> None:
    """Fold a sample (seconds) into a fixed-bound quantile histogram —
    the p50/p99 substrate for request latencies (docs/serving.md).
    Bucket counts ride the snapshot event (summable across workers) and
    render as a Prometheus ``histogram`` on ``/metrics``; no per-sample
    event is emitted."""
    if not enabled():
        return
    _REG.add_qhist(name, value)


def quantile(name: str, q: float) -> Optional[float]:
    """Live ``q``-quantile estimate (seconds) of a quantile histogram in
    this process's registry; None when the histogram has no samples (or
    telemetry is off)."""
    if not enabled():
        return None
    with _REG.lock:
        h = _REG.qhists.get(name)
        if h is None:
            return None
        snap = {"count": h[0], "buckets": list(h[4])}
    return quantile_from_buckets(snap, q)


def event(kind: str, name: str, **attrs) -> None:
    """Emit a free-form event line (sink configured and telemetry on)."""
    if not enabled() or _REG.sink is None:
        return
    payload = {"kind": kind, "name": name, "t": time.time()}
    payload.update(attrs)
    _REG.emit(_stamp(payload))


class _NullSpan:
    """The disabled span: a shared, stateless context manager."""

    __slots__ = ()
    duration = 0.0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "attrs", "t0", "duration")

    def __init__(self, name: str, attrs):
        self.name = name
        self.attrs = attrs
        self.duration = 0.0

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.duration = time.perf_counter() - self.t0
        _REG.add_hist(self.name, self.duration)
        if _REG.sink is not None:
            payload = {"kind": "span", "name": self.name, "t": time.time(),
                       "dur_s": self.duration, "pid": os.getpid()}
            if self.attrs:
                payload.update(self.attrs)
            _REG.emit(_stamp(payload))
        return False


def span(name: str, **attrs):
    """Time a block: ``with span("pipeline/drain"): ...``. Feeds the
    histogram registry and (sink configured) emits one JSONL event. The
    span object exposes ``.duration`` after exit for callers that keep a
    legacy timer view."""
    if not enabled():
        return _NULL_SPAN
    return _Span(name, attrs)


def hist_totals(names) -> Dict[str, float]:
    """Cumulative histogram totals (seconds for span histograms) for the
    given names; 0.0 for a name with no samples yet. The adaptive
    scheduler's depth controller (flow/scheduler.py) polls per-phase
    stall totals through this every few tasks — one lock, no per-name
    dict rebuild — instead of materializing a full :func:`snapshot`.
    Disabled telemetry returns all-zero totals, which the controller
    reads as "no stall signal": depths stay at their static initial
    values (the documented graceful fallback)."""
    if not enabled():
        return {name: 0.0 for name in names}
    with _REG.lock:
        return {
            name: (_REG.hists[name][1] if name in _REG.hists else 0.0)
            for name in names
        }


def snapshot() -> dict:
    """Copy of all aggregated metrics:
    ``{"counters": {...}, "gauges": {...}, "hists": {name:
    {"count", "total", "min", "max", "mean"}}, "qhists": {name:
    {"count", "total", "min", "max", "buckets"}}}`` (``qhists`` only
    when quantile histograms were recorded — older streams stay
    schema-stable)."""
    with _REG.lock:
        hists = {
            name: {
                "count": h[0],
                "total": h[1],
                "min": h[2],
                "max": h[3],
                "mean": h[1] / h[0] if h[0] else 0.0,
            }
            for name, h in _REG.hists.items()
        }
        snap = {
            "counters": dict(_REG.counters),
            "gauges": dict(_REG.gauges),
            "hists": hists,
        }
        if _REG.qhists:
            snap["qhists"] = {
                name: {
                    "count": h[0],
                    "total": h[1],
                    "min": h[2],
                    "max": h[3],
                    "buckets": list(h[4]),
                }
                for name, h in _REG.qhists.items()
            }
        return snap


# ---------------------------------------------------------------------------
# time-series ring sampler (the SLO plane's history substrate)
# ---------------------------------------------------------------------------
def ts_interval() -> float:
    """Seconds between time-series samples (``CHUNKFLOW_TS_INTERVAL``,
    default 10.0; <=0 disables the sampler entirely)."""
    raw = os.environ.get("CHUNKFLOW_TS_INTERVAL", "")
    try:
        return float(raw) if raw else 10.0
    except ValueError:
        return 10.0


def ts_points() -> int:
    """Ring capacity per sampled metric (``CHUNKFLOW_TS_POINTS``,
    default 360 — an hour of history at the default interval)."""
    raw = os.environ.get("CHUNKFLOW_TS_POINTS", "")
    try:
        return max(2, int(raw)) if raw else 360
    except ValueError:
        return 360


# tick hooks survive sampler restarts (the sampler reads the list each
# tick); cleared by reset() — a hooked plane's state is per-run
_TICK_HOOKS: list = []


def add_tick_hook(fn) -> None:
    """Register ``fn(now: float)`` to run after every time-series
    sample (idempotent by identity) — how the SLO evaluator
    (core/slo.py) gets its periodic record/evaluate clock without a
    second thread. Hooks run outside all telemetry locks and are
    best-effort: a raising hook is dropped from that tick, never the
    pipeline."""
    if fn not in _TICK_HOOKS:
        _TICK_HOOKS.append(fn)


def remove_tick_hook(fn) -> None:
    try:
        _TICK_HOOKS.remove(fn)
    except ValueError:
        pass


class _TimeSeriesSampler:
    """Bounded in-memory (t, value) rings over the registry, fed by one
    daemon thread. Each sample derives counters-as-rates against the
    previous tick, copies gauges, and estimates qhist p50/p99; when a
    sink is configured it also flushes one ``timeseries``-kind event
    carrying the sampled values plus the raw cumulative qhist buckets
    (fixed bounds: summable across workers, so ``log-summary --slo``
    can reconstruct a fleet p99 timeline from merged JSONL alone)."""

    def __init__(self, interval: float, points: int):
        self.interval = max(0.01, float(interval))
        self.points = int(points)
        self._lock = threading.Lock()
        self._rings: Dict[str, deque] = {}
        self._prev: Optional[Tuple[float, dict]] = None
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        # baseline sample: establishes the counter snapshot rates are
        # derived against, so a run shorter than one interval still
        # gets a meaningful sample out of the final flush()
        try:
            self.sample()
        except Exception:
            pass
        self._thread = threading.Thread(
            target=self._run, name="chunkflow-timeseries", daemon=True,
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop_evt.wait(self.interval):
            if not enabled():
                continue  # mid-run disable: stop sampling, keep idling
            try:
                self.sample()
            except Exception:
                pass  # a sampling hiccup must never take a worker down

    def sample(self, now: Optional[float] = None) -> Dict[str, float]:
        """One sample tick (the thread's body; tests and flush() call it
        directly). Returns the sampled ``{name: value}`` map."""
        if now is None:
            now = time.time()
        snap = snapshot()
        qhists = snap.get("qhists") or {}
        values: Dict[str, float] = {}
        with self._lock:
            prev = self._prev
            if prev is not None and now > prev[0]:
                dt = now - prev[0]
                for name, value in snap["counters"].items():
                    values[f"rate:{name}"] = round(
                        (value - prev[1].get(name, 0.0)) / dt, 6)
            self._prev = (now, dict(snap["counters"]))
            for name, value in snap["gauges"].items():
                values[f"gauge:{name}"] = value
            for name, h in qhists.items():
                p50 = quantile_from_buckets(h, 0.5)
                if p50 is not None:
                    values[f"p50:{name}"] = p50
                    values[f"p99:{name}"] = quantile_from_buckets(h, 0.99)
            for name, value in values.items():
                ring = self._rings.get(name)
                if ring is None:
                    ring = self._rings[name] = deque(maxlen=self.points)
                ring.append((now, value))
        if values or qhists:
            event(
                "timeseries", "timeseries/sample", interval_s=self.interval,
                values=values,
                qhists={
                    name: {"count": h["count"], "buckets": h["buckets"]}
                    for name, h in qhists.items()
                },
            )
        for hook in list(_TICK_HOOKS):
            try:
                hook(now)
            except Exception:
                pass
        return values

    def series(self) -> Dict[str, List[Tuple[float, float]]]:
        with self._lock:
            return {name: list(ring) for name, ring in self._rings.items()}

    def stop(self, timeout: float = 5.0) -> None:
        self._stop_evt.set()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=timeout)


_SAMPLER_LOCK = threading.Lock()
_SAMPLER: Optional[_TimeSeriesSampler] = None


def start_timeseries(interval: Optional[float] = None,
                     points: Optional[int] = None):
    """Start the time-series sampler thread (idempotent: an already
    running sampler is returned as-is). Returns None — creating **no
    thread and no rings** — when telemetry is disabled or the interval
    knob is <=0; the CLI calls this whenever a metrics dir is
    configured, so every instrumented run gets history for free."""
    global _SAMPLER
    if not enabled():
        return None
    if interval is None:
        interval = ts_interval()
    if interval <= 0:
        return None
    with _SAMPLER_LOCK:
        if _SAMPLER is not None:
            return _SAMPLER
        sampler = _TimeSeriesSampler(interval,
                                     ts_points() if points is None
                                     else points)
        _SAMPLER = sampler
    sampler.start()
    return sampler


def stop_timeseries() -> None:
    """Stop and join the sampler thread (reset() calls this)."""
    global _SAMPLER
    with _SAMPLER_LOCK:
        sampler, _SAMPLER = _SAMPLER, None
    if sampler is not None:
        sampler.stop()


def timeseries_running() -> bool:
    return _SAMPLER is not None


def timeseries() -> Dict[str, List[Tuple[float, float]]]:
    """Copy of the per-metric ``[(t, value), ...]`` rings — ``rate:<counter>``,
    ``gauge:<name>``, ``p50:<qhist>``/``p99:<qhist>`` — or ``{}`` when no
    sampler is running (telemetry off, or never started)."""
    sampler = _SAMPLER
    if sampler is None:
        return {}
    return sampler.series()


# Layer hooks: other observability planes (core/profiling.py's program
# cost ledger) ride the same flush/reset lifecycle without telemetry
# importing them (this module stays zero-dependency). Flush hooks get
# the metrics dir in effect (None when no sink); both hook kinds are
# best-effort — a failing hook must never take the pipeline down.
_FLUSH_HOOKS: list = []
_RESET_HOOKS: list = []


def add_flush_hook(fn) -> None:
    """Register ``fn(metrics_dir_or_None)`` to run at every
    :func:`flush` (idempotent by identity). Skipped entirely when
    telemetry is disabled — the kill switch silences hooked planes too."""
    if fn not in _FLUSH_HOOKS:
        _FLUSH_HOOKS.append(fn)


def add_reset_hook(fn) -> None:
    """Register ``fn()`` to run at every :func:`reset` (idempotent by
    identity) so hooked planes drop their per-run state with ours."""
    if fn not in _RESET_HOOKS:
        _RESET_HOOKS.append(fn)


def flush() -> None:
    """Write the aggregate snapshot as a final event and flush the sink.
    Counters (builds/hits, task counts) reach the JSONL stream here —
    they are aggregate-only during the run."""
    if not enabled():
        return
    # one last time-series sample (and SLO tick) so a run shorter than
    # the sampling interval still leaves history + a final evaluation
    sampler = _SAMPLER
    if sampler is not None:
        try:
            sampler.sample()
        except Exception:
            pass
    metrics_dir = (
        os.path.dirname(_REG.sink_path) if _REG.sink_path else None
    )
    for hook in list(_FLUSH_HOOKS):
        try:
            hook(metrics_dir)
        except Exception:
            pass
    snap = snapshot()
    if _REG.sink is not None:
        _REG.emit(_stamp({"kind": "snapshot", "t": time.time(),
                          "pid": os.getpid(), **snap}))
        with _REG.lock:
            if _REG.sink is not None:
                try:
                    _REG.sink.flush()
                except OSError:
                    pass


def reset() -> None:
    """Clear all metrics, close the sink, stop the time-series sampler,
    and drop the cached worker identity (tests; each CLI invocation is
    one process, so production never needs this)."""
    global _WORKER_ID
    stop_timeseries()
    _TICK_HOOKS.clear()
    with _REG.lock:
        _REG.counters.clear()
        _REG.gauges.clear()
        _REG.hists.clear()
        _REG.qhists.clear()
        if _REG.sink is not None:
            try:
                _REG.sink.close()
            except OSError:
                pass
        _REG.sink, _REG.sink_path = None, None
        _REG.sink_bytes = 0
    with _WORKER_ID_LOCK:
        _WORKER_ID = None
    for hook in list(_RESET_HOOKS):
        try:
            hook()
        except Exception:
            pass


# -- end-of-run reporting ----------------------------------------------
def summary_table() -> str:
    """Fixed-width end-of-run table of spans (count/total/mean/max),
    counters and last-value gauges — the CLI prints this under ``-v``.
    Empty string when nothing was recorded."""
    snap = snapshot()
    lines = []
    if snap["hists"]:
        lines.append(
            f"  {'span':<28} {'count':>7} {'total_s':>9} {'mean_s':>9} "
            f"{'max_s':>9}"
        )
        for name in sorted(snap["hists"]):
            h = snap["hists"][name]
            lines.append(
                f"  {name:<28} {h['count']:>7} {h['total']:>9.3f} "
                f"{h['mean']:>9.4f} {h['max']:>9.4f}"
            )
    if snap.get("qhists"):
        lines.append(
            f"  {'latency hist':<28} {'count':>7} {'p50_s':>9} {'p99_s':>9}"
        )
        for name in sorted(snap["qhists"]):
            h = snap["qhists"][name]
            p50 = quantile_from_buckets(h, 0.5)
            p99 = quantile_from_buckets(h, 0.99)
            lines.append(
                f"  {name:<28} {h['count']:>7} "
                f"{p50 if p50 is not None else 0.0:>9.4f} "
                f"{p99 if p99 is not None else 0.0:>9.4f}"
            )
    if snap["counters"]:
        lines.append(f"  {'counter':<28} {'value':>7}")
        for name in sorted(snap["counters"]):
            value = snap["counters"][name]
            lines.append(f"  {name:<28} {value:>7g}")
    if snap["gauges"]:
        lines.append(f"  {'gauge (last)':<28} {'value':>7}")
        for name in sorted(snap["gauges"]):
            lines.append(f"  {name:<28} {snap['gauges'][name]:>7g}")
    if not lines:
        return ""
    return "\n".join(["telemetry summary:"] + lines)
