from chunkflow_tpu.core.cartesian import Cartesian, to_cartesian
from chunkflow_tpu.core.bbox import (
    BoundingBox,
    BoundingBoxes,
    PhysicalBoundingBox,
)

__all__ = [
    "Cartesian",
    "to_cartesian",
    "BoundingBox",
    "BoundingBoxes",
    "PhysicalBoundingBox",
]
