"""Geometry of one stitching job: grid, global-id scheme, task bodies.

The plan is pure, deterministic arithmetic over (roi bbox, chunk size)
— every worker and the coordinator rebuild the identical plan from the
job spec, so nothing about the grid or the id space ever needs a
round-trip:

* **Grid**: the roi partitions into chunks of ``chunk_size`` anchored
  at ``bbox.start`` (trailing chunks clamp at ``bbox.stop`` — ragged
  grids are first-class). The grid is exactly the leaf set of
  ``SpatialTaskTree(bbox, chunk_size)``: the tree splits on block
  boundaries, so every internal grid interface is the split plane of
  exactly one interior node — the invariant the merge reduce rests on.
* **Global ids**: chunk ``i`` (raster linear index) owns the id range
  ``(i * stride, (i + 1) * stride]`` with ``stride = prod(chunk_size)``
  — an upper bound on per-chunk label count for both the host
  (consecutive 1..n) and device (linear-index-seeded, <= voxels) legs.
  A pure function of the grid index: no allocator round-trip on the hot
  path (``task_tree.GlobalIdAllocator`` stays reserved for dynamic
  consumers).
* **Task bodies**: ``seg-label_<bbox>`` / ``seg-merge_<bbox>`` /
  ``seg-relabel_<bbox>`` — plain queue bodies whose trailing bbox
  ``BoundingBox.from_string`` parses (it takes the LAST three ``a-b``
  groups), so the standard ``fetch-task-from-queue`` loop carries them
  unmodified and the ledger keys them as-is.
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

from chunkflow_tpu.core.bbox import BoundingBox
from chunkflow_tpu.core.cartesian import to_cartesian
from chunkflow_tpu.parallel.task_tree import SpatialTaskTree

LABEL_PREFIX = "seg-label_"
MERGE_PREFIX = "seg-merge_"
RELABEL_PREFIX = "seg-relabel_"

AXIS_NAMES = "zyx"


def face_key(chunk_bbox: BoundingBox, axis: int, positive: bool) -> str:
    """KV sidecar key of one boundary face strip of one chunk."""
    sign = "+" if positive else "-"
    return f"face/{chunk_bbox.string}/{AXIS_NAMES[axis]}{sign}.npy"


def value_face_key(chunk_bbox: BoundingBox, axis: int, positive: bool) -> str:
    """KV sidecar key of one boundary INPUT-VALUE strip — written only
    in multivalue mode, where a cross-face merge additionally requires
    the two voxels to carry the same input id."""
    sign = "+" if positive else "-"
    return f"face/{chunk_bbox.string}/{AXIS_NAMES[axis]}{sign}.val.npy"


def merge_key(node_bbox: BoundingBox) -> str:
    """KV sidecar key of one interior node's merge table."""
    return f"merge/{node_bbox.string}.npy"


REMAP_KEY = "remap/table.npy"


class SegmentPlan:
    """Deterministic grid + id-space + task-body layout of one job."""

    def __init__(self, bbox: BoundingBox, chunk_size):
        self.bbox = bbox
        self.chunk_size = tuple(int(v) for v in to_cartesian(chunk_size))
        if any(s <= 0 for s in self.chunk_size):
            raise ValueError(f"bad chunk size {self.chunk_size}")
        shape = tuple(int(s) for s in bbox.shape)
        self.grid_shape = tuple(
            -(-shape[d] // self.chunk_size[d]) for d in range(3)
        )
        #: per-chunk global-id stride (see module docstring)
        self.id_stride = 1
        for s in self.chunk_size:
            self.id_stride *= int(s)
        self.chunks: List[BoundingBox] = []
        self._chunk_index: Dict[str, Tuple[int, int, int]] = {}
        for idx in itertools.product(*(range(g) for g in self.grid_shape)):
            lo = tuple(
                int(bbox.start[d]) + idx[d] * self.chunk_size[d]
                for d in range(3)
            )
            hi = tuple(
                min(lo[d] + self.chunk_size[d], int(bbox.stop[d]))
                for d in range(3)
            )
            chunk = BoundingBox(lo, hi)
            self.chunks.append(chunk)
            self._chunk_index[chunk.string] = idx
        # geometry template: NEVER state-mutated — schedulers build their
        # own trees via make_tree(); this one answers structural queries
        self._template = SpatialTaskTree(bbox, self.chunk_size)
        self._nodes: Dict[str, SpatialTaskTree] = {
            node.bbox.string: node for node in self._template.walk()
        }

    # ---- grid ----------------------------------------------------------
    def grid_index(self, chunk_bbox: BoundingBox) -> Tuple[int, int, int]:
        try:
            return self._chunk_index[chunk_bbox.string]
        except KeyError:
            raise ValueError(
                f"{chunk_bbox.string} is not a grid chunk of this plan"
            ) from None

    def linear_index(self, chunk_bbox: BoundingBox) -> int:
        iz, iy, ix = self.grid_index(chunk_bbox)
        _, gy, gx = self.grid_shape
        return (iz * gy + iy) * gx + ix

    def id_offset(self, chunk_bbox: BoundingBox) -> int:
        """Base of the chunk's global-id range (local labels 1..n map to
        ``offset + 1 .. offset + n``)."""
        return self.linear_index(chunk_bbox) * self.id_stride

    # ---- tree ----------------------------------------------------------
    def make_tree(self) -> SpatialTaskTree:
        """A fresh (all-READY) scheduling tree for this job."""
        return SpatialTaskTree(self.bbox, self.chunk_size)

    def node(self, bbox: BoundingBox) -> SpatialTaskTree:
        """The template node at ``bbox`` (structural queries only)."""
        try:
            return self._nodes[bbox.string]
        except KeyError:
            raise ValueError(
                f"{bbox.string} is not a tree node of this plan"
            ) from None

    def split_axis(self, node: SpatialTaskTree) -> int:
        """The axis an interior node's interface plane is normal to."""
        if node.is_leaf:
            raise ValueError("leaves have no split plane")
        for axis in range(3):
            if int(node.left.bbox.stop[axis]) != int(node.bbox.stop[axis]):
                return axis
        raise AssertionError("degenerate split")  # pragma: no cover

    def plane_chunks(
        self, node: SpatialTaskTree
    ) -> Tuple[int, int, List[BoundingBox], List[BoundingBox]]:
        """The interface of one interior node: ``(axis, coordinate,
        low_chunks, high_chunks)`` — the grid chunks whose ``+axis``
        (resp. ``-axis``) faces tile the node's split plane."""
        axis = self.split_axis(node)
        split = int(node.left.bbox.stop[axis])
        def inside(c: BoundingBox) -> bool:
            return all(
                int(node.bbox.start[d]) <= int(c.start[d])
                and int(c.stop[d]) <= int(node.bbox.stop[d])
                for d in range(3)
            )
        low = [
            c for c in self.chunks
            if int(c.stop[axis]) == split and inside(c)
        ]
        high = [
            c for c in self.chunks
            if int(c.start[axis]) == split and inside(c)
        ]
        return axis, split, low, high

    # ---- task bodies ---------------------------------------------------
    def label_body(self, chunk_bbox: BoundingBox) -> str:
        return LABEL_PREFIX + chunk_bbox.string

    def merge_body(self, node_bbox: BoundingBox) -> str:
        return MERGE_PREFIX + node_bbox.string

    def relabel_body(self, chunk_bbox: BoundingBox) -> str:
        return RELABEL_PREFIX + chunk_bbox.string

    def node_body(self, node: SpatialTaskTree) -> str:
        """The queue body of one tree node: leaves label, interior
        nodes merge (the body doubles as the ledger key)."""
        if node.is_leaf:
            return self.label_body(node.bbox)
        return self.merge_body(node.bbox)

    @staticmethod
    def parse_body(body: str) -> Optional[Tuple[str, BoundingBox]]:
        """``(kind, bbox)`` for a segmentation task body, None for any
        other queue traffic (the stages pass those through untouched)."""
        for kind, prefix in (
            ("label", LABEL_PREFIX),
            ("merge", MERGE_PREFIX),
            ("relabel", RELABEL_PREFIX),
        ):
            if body.startswith(prefix):
                return kind, BoundingBox.from_string(body[len(prefix):])
        return None

    # ---- spec ----------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "bbox": self.bbox.string,
            "chunk_size": list(self.chunk_size),
        }

    @classmethod
    def from_dict(cls, data: dict) -> "SegmentPlan":
        return cls(
            BoundingBox.from_string(data["bbox"]), data["chunk_size"]
        )
