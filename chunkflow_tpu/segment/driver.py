"""Drivers composing the map -> reduce -> map into one job.

Two execution shapes over the same stage functions (segment/stages.py):

* :func:`run_local` — in-process: the label and relabel map phases fan
  out over a thread pool (per-chunk storage I/O overlaps; the native
  labeling kernel releases the GIL), the reduce runs as a post-order
  tree walk. This is the bench leg and the single-machine CLI path.
* :func:`run_coordinator` — distributed: a
  :class:`parallel.tree_source.TreeTaskSource` pumps the label+merge
  tree through an ordinary queue+ledger, then the relabel wave goes out
  as flat tasks gated on the root's ledger commit. Workers are plain
  ``fetch-task-from-queue`` pipelines chaining the ``label-chunk`` /
  ``merge-seg`` / ``relabel`` stages (flow/cli.py) — the coordinator
  never executes a task itself and can die and resume at any point
  (everything it does is derived from the plan + the ledger).

:func:`init_store` / :func:`open_store` persist a job spec
(``spec.json``) in a job directory so every worker process rebuilds the
identical :class:`SegmentStore` from the directory alone; the label
volume lives in a :class:`volume.storage.KVArrayBackend` under the same
root, faces/merge tables/remap in the sibling KV namespace.
"""
from __future__ import annotations

import json
import os
import time
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

import numpy as np

from chunkflow_tpu.core.bbox import BoundingBox
from chunkflow_tpu.segment.plan import SegmentPlan
from chunkflow_tpu.segment.stages import (
    LABEL_DTYPE,
    SegmentStore,
    label_chunk,
    merge_node,
    relabel_chunk,
)
from chunkflow_tpu.volume.storage import (
    FileKV,
    KVArrayBackend,
    MemoryBackend,
    MemoryKV,
    blockwise_cutout,
)

SPEC_NAME = "spec.json"


# ---------------------------------------------------------------------------
# local (in-process) execution
# ---------------------------------------------------------------------------
def _map_phase(fn, store: SegmentStore, bboxes, workers: int) -> None:
    if workers <= 1:
        for bbox in bboxes:
            fn(store, bbox)
        return
    with ThreadPoolExecutor(
        max_workers=workers, thread_name_prefix="chunkflow-segment"
    ) as pool:
        futures = [pool.submit(fn, store, bbox) for bbox in bboxes]
        for future in futures:
            future.result()


def run_local(store: SegmentStore, workers: int = 4) -> dict:
    """The whole job in this process. Returns phase counters."""
    plan = store.plan
    _map_phase(label_chunk, store, plan.chunks, workers)
    tree = plan.make_tree()
    merges = 0
    for node in tree.post_order():
        if not node.is_leaf:
            merge_node(store, node.bbox)
            merges += 1
    _map_phase(relabel_chunk, store, plan.chunks, workers)
    return {
        "chunks": len(plan.chunks),
        "merge_nodes": merges,
    }


def segment_volume(
    array: np.ndarray,
    chunk_size,
    *,
    threshold: float = 0.5,
    connectivity: int = 26,
    multivalue: bool = False,
    device: bool = False,
    workers: int = 4,
    mesh_dir: Optional[str] = None,
) -> np.ndarray:
    """Convenience one-shot: stitch-label a host array through an
    in-memory store and return the merged uint64 segmentation. The
    heavy lifting (and every knob) is :func:`run_local`; tests and the
    bench build their own stores for latency-charged backends."""
    bbox = BoundingBox((0, 0, 0), tuple(int(s) for s in array.shape))
    plan = SegmentPlan(bbox, chunk_size)
    seg_array = np.zeros(array.shape, dtype=LABEL_DTYPE)
    store = SegmentStore(
        plan,
        input_backend=MemoryBackend(array, block_shape=plan.chunk_size),
        seg_backend=MemoryBackend(seg_array, block_shape=plan.chunk_size),
        kv=MemoryKV(),
        threshold=threshold,
        connectivity=connectivity,
        multivalue=multivalue,
        device=device,
        mesh_dir=mesh_dir,
    )
    run_local(store, workers=workers)
    return seg_array


# ---------------------------------------------------------------------------
# job directory (spec + file-backed store) for multi-process runs
# ---------------------------------------------------------------------------
def init_store(
    seg_dir: str,
    input_npy: str,
    chunk_size,
    *,
    threshold: float = 0.5,
    connectivity: int = 26,
    multivalue: bool = False,
    device: bool = False,
    mesh_dir: Optional[str] = None,
) -> SegmentStore:
    """Create a job directory: write ``spec.json`` and return the
    opened store. ``input_npy`` is kept as a path so worker processes
    map it read-only instead of copying the volume around."""
    os.makedirs(seg_dir, exist_ok=True)
    shape = np.load(input_npy, mmap_mode="r").shape
    if len(shape) != 3:
        raise ValueError(f"segmentation input must be 3D, got {shape}")
    spec = {
        "bbox": BoundingBox((0, 0, 0), tuple(int(s) for s in shape)).string,
        "chunk_size": [int(v) for v in chunk_size],
        "input_npy": os.path.abspath(input_npy),
        "threshold": float(threshold),
        "connectivity": int(connectivity),
        "multivalue": bool(multivalue),
        "device": bool(device),
        "mesh_dir": mesh_dir,
    }
    path = os.path.join(seg_dir, SPEC_NAME)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(spec, f, indent=1)
    os.replace(tmp, path)
    return open_store(seg_dir)


def open_store(seg_dir: str) -> SegmentStore:
    """Rebuild the store of a job directory from its ``spec.json`` —
    what every worker stage does once per process."""
    with open(os.path.join(seg_dir, SPEC_NAME)) as f:
        spec = json.load(f)
    plan = SegmentPlan(
        BoundingBox.from_string(spec["bbox"]), spec["chunk_size"]
    )
    source = np.load(spec["input_npy"], mmap_mode="r")
    kv = FileKV(os.path.join(seg_dir, "kv"))
    seg_backend = KVArrayBackend(
        kv,
        domain=(plan.bbox.start, plan.bbox.stop),
        dtype=LABEL_DTYPE,
        block_shape=plan.chunk_size,
        prefix="seg",
    )
    return SegmentStore(
        plan,
        input_backend=MemoryBackend(
            source, block_shape=plan.chunk_size
        ),
        seg_backend=seg_backend,
        kv=kv,
        threshold=spec["threshold"],
        connectivity=spec["connectivity"],
        multivalue=spec["multivalue"],
        device=spec.get("device", False),
        mesh_dir=spec.get("mesh_dir"),
    )


def export_segmentation(store: SegmentStore) -> np.ndarray:
    """Materialize the (relabeled) whole-volume segmentation."""
    return blockwise_cutout(
        store.seg_backend, store.plan.bbox.start, store.plan.bbox.stop
    )


# ---------------------------------------------------------------------------
# distributed coordination
# ---------------------------------------------------------------------------
def run_coordinator(
    store: SegmentStore,
    queue,
    ledger,
    *,
    poll_interval: float = 0.05,
    timeout: Optional[float] = None,
) -> dict:
    """Drive the job through a queue + ledger: the label+merge tree via
    :class:`TreeTaskSource`, then the relabel wave gated on the root's
    commit. Fully resumable — a restarted coordinator re-derives its
    whole state from plan + ledger (already-committed nodes fold to
    done; duplicate enqueues ledger-skip at the workers)."""
    from chunkflow_tpu.parallel.tree_source import TreeTaskSource

    plan = store.plan
    deadline = None if timeout is None else time.monotonic() + timeout

    source = TreeTaskSource(
        plan.make_tree(), queue, ledger, body=plan.node_body
    )
    source.run(
        poll_interval=poll_interval,
        timeout=None if deadline is None else deadline - time.monotonic(),
    )

    relabel_bodies: List[str] = [
        plan.relabel_body(chunk) for chunk in plan.chunks
    ]
    outstanding = [
        body for body in relabel_bodies if not ledger.is_done(body)
    ]
    if outstanding:
        queue.send_messages(outstanding)
    while any(not ledger.is_done(body) for body in relabel_bodies):
        if deadline is not None and time.monotonic() > deadline:
            raise TimeoutError(
                "relabel wave incomplete: "
                f"{sum(1 for b in relabel_bodies if not ledger.is_done(b))}"
                " chunks outstanding"
            )
        time.sleep(poll_interval)
    return {
        "tree_tasks": source.enqueued,
        "relabel_tasks": len(outstanding),
    }
