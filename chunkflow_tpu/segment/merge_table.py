"""Merge tables: face-pair equivalence edges + vectorized union-find.

The reduce half of the stitching algebra, all numpy, no python loops
over voxels or ids:

* :func:`face_pair_edges` — two adjacent one-voxel label planes in, the
  unique set of (low-side id, high-side id) equivalence edges out. The
  in-plane neighborhood per connectivity matters: with 26-connectivity
  a voxel touches the far side of the interface diagonally, so chunks
  adjacent only across a grid *edge or corner* still exchange edges —
  provided the planes compared are the FULL interface planes of a tree
  node, not single chunk-pair strips (segment/stages.py assembles them
  per node; every grid interface is the split plane of exactly one
  interior node, so coverage is exact — the label-isomorphism tests
  pin this for 6 and 26 on ragged grids).
* :func:`union_find` — path-compressed, fully vectorized: pointer
  jumping to a fixpoint, then edge-root relinking by minimum, repeated
  until no edge spans two roots. Canonical representative = the minimum
  global id of the component, which makes the final remap table a
  *fixpoint* table (roots map to themselves) — the property the
  idempotent relabel pass rests on (docs/segmentation.md).
* :func:`labels_isomorphic` — exact bijective agreement between two
  labelings (the acceptance oracle: stitched vs monolithic).
"""
from __future__ import annotations

from typing import Iterable, Sequence, Tuple

import numpy as np

EDGE_DTYPE = np.uint64

_EMPTY_EDGES = np.empty((0, 2), dtype=EDGE_DTYPE)


def _inplane_offsets(connectivity: int) -> Tuple[Tuple[int, int], ...]:
    """In-plane (du, dv) neighbor offsets a voxel reaches on the far
    side of a face, per 3D connectivity: crossing the face spends one
    axis step, leaving Chebyshev<=1 (26), Manhattan<=1 (18) or exactly
    zero (6) in-plane displacement."""
    if connectivity == 6:
        return ((0, 0),)
    if connectivity == 18:
        return ((0, 0), (1, 0), (-1, 0), (0, 1), (0, -1))
    if connectivity == 26:
        return tuple((du, dv) for du in (-1, 0, 1) for dv in (-1, 0, 1))
    raise ValueError(
        f"connectivity must be 6, 18 or 26, got {connectivity}"
    )


def face_pair_edges(
    low: np.ndarray,
    high: np.ndarray,
    connectivity: int = 26,
    low_values: np.ndarray = None,
    high_values: np.ndarray = None,
) -> np.ndarray:
    """Equivalence edges across one interface: ``low`` is the label
    plane on the low-coordinate side (the chunks' ``+`` faces), ``high``
    the plane one voxel across (the ``-`` faces). Returns the unique
    ``(N, 2)`` uint64 edge set; zero (background) and identity pairs are
    dropped. Vectorized: one shifted-overlap comparison per in-plane
    offset, then one ``np.unique`` over the stacked pairs.

    ``low_values``/``high_values`` (multivalue mode) carry the INPUT ids
    under the same planes: an edge then also requires the two voxels to
    hold the same input value — two touching but differently-valued
    objects must stay separate, exactly as within one chunk."""
    low = np.asarray(low)
    high = np.asarray(high)
    if low.shape != high.shape or low.ndim != 2:
        raise ValueError(
            f"face planes must be equal-shape 2D, got {low.shape} "
            f"vs {high.shape}"
        )
    if (low_values is None) != (high_values is None):
        raise ValueError("value planes must come as a pair")
    h, w = low.shape
    pairs = []
    for du, dv in _inplane_offsets(connectivity):
        lo_sel = (
            slice(max(0, -du), h - max(0, du)),
            slice(max(0, -dv), w - max(0, dv)),
        )
        hi_sel = (
            slice(max(0, du), h - max(0, -du)),
            slice(max(0, dv), w - max(0, -dv)),
        )
        a = low[lo_sel]
        b = high[hi_sel]
        mask = (a != 0) & (b != 0)
        if low_values is not None:
            mask &= low_values[lo_sel] == high_values[hi_sel]
        if mask.any():
            pairs.append(
                np.stack(
                    [a[mask].astype(EDGE_DTYPE),
                     b[mask].astype(EDGE_DTYPE)],
                    axis=1,
                )
            )
    if not pairs:
        return _EMPTY_EDGES.copy()
    edges = np.unique(np.concatenate(pairs, axis=0), axis=0)
    return edges[edges[:, 0] != edges[:, 1]]


def merge_edge_sets(edge_sets: Iterable[np.ndarray]) -> np.ndarray:
    """Concatenate + dedupe edge sets (a child's merge table is itself
    a set of equivalence pairs, so tables and fresh face edges combine
    through the same path)."""
    stacked = [
        np.asarray(e, dtype=EDGE_DTYPE).reshape(-1, 2)
        for e in edge_sets
    ]
    stacked = [e for e in stacked if e.size]
    if not stacked:
        return _EMPTY_EDGES.copy()
    return np.unique(np.concatenate(stacked, axis=0), axis=0)


def union_find(edges: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized union-find over an ``(N, 2)`` edge set.

    Returns ``(ids, roots)``: the sorted unique ids appearing in any
    edge and, positionally, each id's canonical representative — the
    MINIMUM id of its connected component. Implementation: compress ids
    to dense indices (searchsorted), then alternate full pointer-jumping
    path compression with min-relinking of every edge's two roots until
    no edge spans two components. Each outer round at least halves the
    surviving component count along every merging chain, so convergence
    is logarithmic in the longest merge chain."""
    edges = np.asarray(edges, dtype=EDGE_DTYPE).reshape(-1, 2)
    ids = np.unique(edges)
    if ids.size == 0:
        return ids, ids.copy()
    idx = np.searchsorted(ids, edges)
    parent = np.arange(ids.size, dtype=np.int64)
    while True:
        while True:  # full path compression by pointer jumping
            jumped = parent[parent]
            if np.array_equal(jumped, parent):
                break
            parent = jumped
        root_a = parent[idx[:, 0]]
        root_b = parent[idx[:, 1]]
        merged = root_a != root_b
        if not merged.any():
            break
        lo = np.minimum(root_a[merged], root_b[merged])
        hi = np.maximum(root_a[merged], root_b[merged])
        # min-relink: several edges may target one root — np.minimum.at
        # keeps the smallest, the next compression round absorbs chains
        np.minimum.at(parent, hi, lo)
    return ids, ids[parent]


def merge_table(edge_sets: Iterable[np.ndarray]) -> np.ndarray:
    """The reduce step of one tree node: combine edge sets, run
    union-find, return the non-identity ``(N, 2)`` (id -> canonical)
    rows. A pure function of its inputs — re-running a replayed merge
    writes byte-identical output (the idempotence argument,
    docs/segmentation.md)."""
    edges = merge_edge_sets(edge_sets)
    ids, roots = union_find(edges)
    moved = ids != roots
    return np.stack([ids[moved], roots[moved]], axis=1)


def labels_isomorphic(a: np.ndarray, b: np.ndarray) -> bool:
    """Exact bijective agreement of two labelings: same background
    support, and the nonzero (a, b) value pairs form a one-to-one
    mapping in both directions."""
    a = np.asarray(a)
    b = np.asarray(b)
    if a.shape != b.shape:
        return False
    a = a.ravel()
    b = b.ravel()
    zero_a = a == 0
    if not np.array_equal(zero_a, b == 0):
        return False
    nz = ~zero_a
    pairs = np.stack(
        [a[nz].astype(np.uint64), b[nz].astype(np.uint64)], axis=1
    )
    pairs = np.unique(pairs, axis=0)
    return bool(
        np.unique(pairs[:, 0]).size == pairs.shape[0]
        and np.unique(pairs[:, 1]).size == pairs.shape[0]
    )


def apply_mapping(
    arr: np.ndarray, keys: Sequence[int], values: Sequence[int]
) -> np.ndarray:
    """Thin re-export of :func:`ops.remap.remap_arrays` kept here so the
    reduce plane has one import surface (stages, bench, tests)."""
    from chunkflow_tpu.ops.remap import remap_arrays

    return remap_arrays(arr, keys, values, preserve_missing=True)
