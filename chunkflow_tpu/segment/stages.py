"""The three task stages of the stitching job, as pure(-ish) functions
over one :class:`SegmentStore`.

Every stage is **idempotent by construction** — the exactly-once story
(docs/fault_tolerance.md) only dedupes the ledger *commit*; the effect
must survive a replay after a mid-task SIGKILL:

* ``label_chunk`` writes are pure functions of (input chunk, plan) —
  a replay rewrites identical bytes.
* ``merge_node`` output is a pure function of its children's tables and
  the face sidecars (all written before the children committed) — a
  replay rewrites identical bytes. The ``segment/merge`` chaos point
  sits mid-merge, after the reads and before the table write, so
  ``CHUNKFLOW_CHAOS=once=segment/merge:action=kill`` exercises exactly
  the replay the argument covers.
* ``relabel_chunk`` applies a fixpoint table (canonical ids map to
  themselves, every other id maps onto a canonical one, and no
  canonical id appears as a non-identity key) — applying it to
  already-relabeled data is the identity, so an in-place replay is a
  no-op rewrite.

Telemetry (docs/observability.md SEGMENT block): ``segment/chunks_labeled``,
``segment/faces_written``, ``segment/faces_exchanged``,
``segment/edges_found``, ``segment/merges_applied``,
``segment/voxels_relabeled``.
"""
from __future__ import annotations

import io
from typing import Optional

import numpy as np

from chunkflow_tpu.core import telemetry
from chunkflow_tpu.core.bbox import BoundingBox
from chunkflow_tpu.segment import merge_table as mt
from chunkflow_tpu.segment.plan import (
    REMAP_KEY,
    SegmentPlan,
    face_key,
    merge_key,
    value_face_key,
)
from chunkflow_tpu.testing import chaos
from chunkflow_tpu.volume.storage import (
    KVBackend,
    StorageBackend,
    blockwise_cutout,
    blockwise_save,
)

LABEL_DTYPE = np.uint64


def _to_npy_bytes(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, arr, allow_pickle=False)
    return buf.getvalue()


def _from_npy_bytes(data: bytes) -> np.ndarray:
    return np.load(io.BytesIO(data), allow_pickle=False)


class SegmentStore:
    """One stitching job's state: plan + backends + labeling knobs.

    ``input_backend`` holds the source volume (probability map, binary
    mask or multi-valued ids), ``seg_backend`` the evolving uint64 label
    volume (block grid == chunk grid, so parallel chunk writes are
    aligned and conflict-free), ``kv`` the sidecar plane (faces, merge
    tables, the remap table)."""

    def __init__(
        self,
        plan: SegmentPlan,
        input_backend: StorageBackend,
        seg_backend: StorageBackend,
        kv: KVBackend,
        *,
        threshold: float = 0.5,
        connectivity: int = 26,
        multivalue: bool = False,
        device: bool = False,
        mesh_dir: Optional[str] = None,
        voxel_size=(1, 1, 1),
    ):
        if connectivity not in (6, 18, 26):
            raise ValueError(
                f"connectivity must be 6, 18 or 26, got {connectivity}"
            )
        self.plan = plan
        self.input_backend = input_backend
        self.seg_backend = seg_backend
        self.kv = kv
        self.threshold = float(threshold)
        self.connectivity = int(connectivity)
        self.multivalue = bool(multivalue)
        self.device = bool(device)
        self.mesh_dir = mesh_dir
        self.voxel_size = tuple(voxel_size)
        self._remap_cache: Optional[tuple] = None

    # ---- sidecar helpers ----------------------------------------------
    def write_array(self, key: str, arr: np.ndarray) -> None:
        self.kv.write_bytes(key, _to_npy_bytes(arr))

    def read_array(self, key: str) -> Optional[np.ndarray]:
        data = self.kv.read_bytes(key)
        return None if data is None else _from_npy_bytes(data)

    def remap_table(self) -> tuple:
        """The root's (keys, values) remap table; cached per process —
        it is written exactly once, before any relabel task exists."""
        if self._remap_cache is None:
            table = self.read_array(REMAP_KEY)
            if table is None:
                if len(self.plan.chunks) == 1:
                    # degenerate single-chunk grid: no interface, no
                    # merge node, nothing to remap
                    table = np.empty((0, 2), dtype=LABEL_DTYPE)
                else:
                    raise RuntimeError(
                        "remap table not written yet — the root merge "
                        "must commit before relabel tasks run"
                    )
            self._remap_cache = (table[:, 0], table[:, 1])
        return self._remap_cache


# ---------------------------------------------------------------------------
# map 1: per-chunk labeling
# ---------------------------------------------------------------------------
def _label_local(store: SegmentStore, src: np.ndarray) -> np.ndarray:
    """One chunk's local labels (host scipy/native union-find, or the
    device min-propagation leg for binary-eligible input)."""
    from chunkflow_tpu.ops import connected_components as cc

    kind = np.dtype(src.dtype).kind
    if store.multivalue:
        return cc.label_multivalue(src, connectivity=store.connectivity)
    if kind == "f":
        binary = src > store.threshold
    else:
        binary = src != 0
    if store.device:
        return np.asarray(
            cc.label_binary_device(binary, connectivity=store.connectivity)
        )
    return cc.label_binary(binary, connectivity=store.connectivity)


def label_chunk(store: SegmentStore, bbox: BoundingBox) -> int:
    """Map stage 1: label one grid chunk, lift into the global id
    space, save the interior blockwise and the boundary faces as KV
    sidecars. Returns the number of local labels."""
    plan = store.plan
    offset = plan.id_offset(bbox)
    src = blockwise_cutout(store.input_backend, bbox.start, bbox.stop)
    local = _label_local(store, src)
    labels = local.astype(LABEL_DTYPE)
    nonzero = labels != 0
    labels[nonzero] += LABEL_DTYPE(offset)
    blockwise_save(store.seg_backend, bbox.start, labels)
    faces = 0
    for axis in range(3):
        for positive in (False, True):
            edge = (
                int(bbox.stop[axis]) < int(plan.bbox.stop[axis])
                if positive
                else int(bbox.start[axis]) > int(plan.bbox.start[axis])
            )
            if not edge:
                continue  # roi boundary: nothing on the far side
            sel = [slice(None)] * 3
            sel[axis] = -1 if positive else 0
            store.write_array(
                face_key(bbox, axis, positive), labels[tuple(sel)]
            )
            if store.multivalue:
                # merge eligibility across the face needs the INPUT ids
                # too: touching-but-different objects must stay separate
                store.write_array(
                    value_face_key(bbox, axis, positive),
                    src[tuple(sel)].astype(LABEL_DTYPE),
                )
            faces += 1
    count = int(np.unique(local).size - (1 if nonzero.any() else 0))
    telemetry.inc("segment/chunks_labeled")
    if faces:
        telemetry.inc("segment/faces_written", faces)
    return count


# ---------------------------------------------------------------------------
# reduce: hierarchical merge over the spatial task tree
# ---------------------------------------------------------------------------
def _interface_planes(store: SegmentStore, node) -> tuple:
    """Assemble the two FULL label planes of one interior node's split
    interface from the chunk face sidecars (low side ``+`` faces, high
    side ``-`` faces). Full planes — not per-chunk-pair strips — so
    diagonal contacts across grid edges/corners fall out of the
    in-plane neighborhood for free (merge_table.face_pair_edges)."""
    plan = store.plan
    axis, _split, low_chunks, high_chunks = plan.plane_chunks(node)
    inplane = [d for d in range(3) if d != axis]
    shape = tuple(
        int(node.bbox.stop[d]) - int(node.bbox.start[d]) for d in inplane
    )
    planes = []
    value_planes = []
    exchanged = 0
    for side_chunks, positive in ((low_chunks, True), (high_chunks, False)):
        plane = np.zeros(shape, dtype=LABEL_DTYPE)
        values = (
            np.zeros(shape, dtype=LABEL_DTYPE) if store.multivalue else None
        )
        for chunk in side_chunks:
            strip = store.read_array(face_key(chunk, axis, positive))
            if strip is None:  # pragma: no cover — scheduling bug guard
                raise RuntimeError(
                    f"missing face sidecar {face_key(chunk, axis, positive)}"
                )
            anchor = tuple(
                int(chunk.start[d]) - int(node.bbox.start[d])
                for d in inplane
            )
            window = (
                slice(anchor[0], anchor[0] + strip.shape[0]),
                slice(anchor[1], anchor[1] + strip.shape[1]),
            )
            plane[window] = strip
            if values is not None:
                vstrip = store.read_array(
                    value_face_key(chunk, axis, positive)
                )
                if vstrip is None:  # pragma: no cover — scheduling guard
                    raise RuntimeError(
                        "missing value face sidecar "
                        f"{value_face_key(chunk, axis, positive)}"
                    )
                values[window] = vstrip
            exchanged += 1
        planes.append(plane)
        value_planes.append(values)
    telemetry.inc("segment/faces_exchanged", exchanged)
    return planes[0], planes[1], value_planes[0], value_planes[1]


def merge_node(store: SegmentStore, bbox: BoundingBox) -> int:
    """Reduce stage: one interior node's merge — its interface edges
    combined with both children's tables through union-find; the root
    additionally emits the global remap table. Returns the number of
    non-identity rows in the node's table."""
    plan = store.plan
    node = plan.node(bbox)
    low, high, low_values, high_values = _interface_planes(store, node)
    edges = mt.face_pair_edges(
        low,
        high,
        connectivity=store.connectivity,
        low_values=low_values,
        high_values=high_values,
    )
    telemetry.inc("segment/edges_found", int(edges.shape[0]))
    edge_sets = [edges]
    for child in (node.left, node.right):
        if child.is_leaf:
            continue
        table = store.read_array(merge_key(child.bbox))
        if table is None:  # pragma: no cover — scheduling bug guard
            raise RuntimeError(
                f"missing child merge table {merge_key(child.bbox)}"
            )
        edge_sets.append(table)
    # the kill window of the chaos satellite: inputs read, output not
    # yet written — a SIGKILL here replays to byte-identical output
    chaos.chaos_point("segment/merge")
    table = mt.merge_table(edge_sets)
    store.write_array(merge_key(bbox), table)
    if node.parent is None:  # root: the table IS the global remap
        store.write_array(REMAP_KEY, table)
        telemetry.inc("segment/merges_applied", int(table.shape[0]))
    return int(table.shape[0])


# ---------------------------------------------------------------------------
# map 2: streaming relabel (+ optional meshing)
# ---------------------------------------------------------------------------
def relabel_chunk(store: SegmentStore, bbox: BoundingBox) -> int:
    """Map stage 2: apply the root remap to one chunk in place, then
    mesh the merged labels when a mesh sink is configured. Returns the
    number of voxels whose id changed."""
    from chunkflow_tpu.ops.remap import remap_arrays

    keys, values = store.remap_table()
    labels = blockwise_cutout(store.seg_backend, bbox.start, bbox.stop)
    merged = remap_arrays(labels, keys, values, preserve_missing=True)
    changed = int((merged != labels).sum())
    if changed:
        blockwise_save(store.seg_backend, bbox.start, merged)
    telemetry.inc("segment/voxels_relabeled", changed)
    if store.mesh_dir is not None:
        _mesh_chunk(store, bbox, merged)
    return changed


def _mesh_chunk(store: SegmentStore, bbox: BoundingBox,
                merged: np.ndarray) -> None:
    """Mesh one relabeled chunk: fragments carry the merged global ids,
    so one object's fragments from different chunks share a manifest —
    no chunk-seam splits (flow/mesh.py)."""
    from chunkflow_tpu.chunk.base import Chunk, LayerType
    from chunkflow_tpu.flow.mesh import MeshOperator

    seg = Chunk(
        merged,
        voxel_offset=tuple(int(v) for v in bbox.start),
        voxel_size=store.voxel_size,
        layer_type=LayerType.SEGMENTATION,
    )
    MeshOperator(store.mesh_dir, manifest=True)(seg)


# ---------------------------------------------------------------------------
# body dispatch (the CLI stages and the local driver share this)
# ---------------------------------------------------------------------------
_STAGES = {
    "label": label_chunk,
    "merge": merge_node,
    "relabel": relabel_chunk,
}


def execute_body(store: SegmentStore, body: str) -> bool:
    """Run the stage a queue body names; False for non-segmentation
    traffic (callers pass the task through untouched)."""
    parsed = SegmentPlan.parse_body(body)
    if parsed is None:
        return False
    kind, bbox = parsed
    _STAGES[kind](store, bbox)
    return True
