"""The whole-volume segmentation plane: cross-chunk label stitching.

The reference pipeline's production output is mask -> **segment** -> mesh
(PAPER.md, the cc3d/fastremap/zmesh C++ leg); this package closes the
cross-chunk story that ops/connected_components.py (one chunk at a time)
could not express. The job is the repo's first real task *graph* — a
map -> reduce -> map pipeline over a chunk grid:

1. **Map — label** (:func:`segment.stages.label_chunk`): each grid chunk
   is labeled independently, labels lifted into a collision-free global
   id space by a deterministic per-chunk offset, interior labels written
   ``blockwise_save``, the six boundary faces written as sidecar KV
   objects.
2. **Reduce — merge tree** (:func:`segment.stages.merge_node`):
   adjacent face planes produce equivalence edges; merges run bottom-up
   over a :class:`parallel.task_tree.SpatialTaskTree` (one interface
   plane per interior node), culminating in a root union-find that
   emits the global remap table to KV.
3. **Map — relabel** (:func:`segment.stages.relabel_chunk`): the remap
   is applied per chunk via ops/remap.py and the final segmentation
   written back (idempotently — canonical ids are fixpoints of the
   table, so a replayed relabel is a no-op rewrite).

See docs/segmentation.md for the full phase diagram, the global-id
scheme and the exactly-once merge argument.
"""
from chunkflow_tpu.segment.merge_table import (  # noqa: F401
    face_pair_edges,
    labels_isomorphic,
    union_find,
)
from chunkflow_tpu.segment.plan import SegmentPlan  # noqa: F401
from chunkflow_tpu.segment.stages import SegmentStore, execute_body  # noqa: F401
from chunkflow_tpu.segment.driver import (  # noqa: F401
    init_store,
    open_store,
    run_coordinator,
    run_local,
    segment_volume,
)
