from chunkflow_tpu.inference.inferencer import Inferencer

__all__ = ["Inferencer"]
