"""The fused patch-inference engine: one XLA program per chunk.

Parity target: reference flow/divid_conquer/inferencer.py — chunk -> patch
decomposition, batched convnet forward, bump-weighted overlap-add, chunk
weight-mask normalization. The reference runs this as a Python loop with a
host<->GPU round trip per batch (its acknowledged hot spot, SURVEY §3.2);
here the whole thing — patch gather (dynamic_slice), forward pass, bump
multiply, scatter-add blend, reciprocal normalization — is a single
jit-compiled program over an HBM-resident chunk:

    lax.scan over patch batches
      -> vmap(dynamic_slice) gather         [B, Ci, *Pi]
      -> engine.apply (MXU matmuls/convs)   [B, Co, *Po]
      -> (optional 8x TTA average, scanned)
      -> bump multiply + validity mask
      -> single scatter-add / pallas DMA accumulation (ops/blend.py)
    -> out / weight  (exact everywhere, including chunk edges)

Design deltas from the reference, on purpose:
- no separate "aligned" vs "mask_output_chunk" modes: the weight mask is
  always accumulated on device and reciprocal-applied, which is exact for
  arbitrary chunk sizes (the reference's aligned mode is the special case
  where the mask is uniform in the interior);
- patch grids pad to a batch multiple with zero-validity entries instead of
  a dynamic trailing batch, keeping shapes static for XLA.
"""
from __future__ import annotations

import itertools
import sys
import time
from typing import Optional, Tuple

import numpy as np

from chunkflow_tpu.chunk.base import Chunk, LayerType
from chunkflow_tpu.core.cartesian import Cartesian, to_cartesian
from chunkflow_tpu.core import telemetry
from chunkflow_tpu.core.compile_cache import (
    ProgramCache,
    enable_persistent_cache,
)
from chunkflow_tpu.core.contracts import Spec, contract
from chunkflow_tpu.inference import engines
from chunkflow_tpu.inference.bump import bump_map
from chunkflow_tpu.inference.patching import enumerate_patches, pad_to_batch


class Inferencer:
    def __init__(
        self,
        input_patch_size,
        output_patch_size=None,
        output_patch_overlap=(0, 0, 0),
        num_output_channels: int = 1,
        num_input_channels: int = 1,
        framework: str = "identity",
        model_path: str = "",
        weight_path: Optional[str] = None,
        batch_size: int = 1,
        augment: bool = False,
        bump: str = "wu",
        crop_output_margin: bool = True,
        mask_myelin_threshold: Optional[float] = None,
        dtype: str = "float32",
        output_dtype: str = "float32",
        model_variant: str = "parity",
        engine=None,
        sharding: str = "none",
        mesh: Optional[str] = None,
        precision: Optional[str] = None,
        shape_bucket=None,
        blend: str = "auto",
        dry_run: bool = False,
    ):
        self.input_patch_size = Cartesian.from_collection(input_patch_size)
        self.output_patch_size = (
            Cartesian.from_collection(output_patch_size)
            if output_patch_size is not None
            else self.input_patch_size
        )
        self.output_patch_overlap = Cartesian.from_collection(output_patch_overlap)
        self.crop_margin = (self.input_patch_size - self.output_patch_size) // 2
        self.num_output_channels = num_output_channels
        self.num_input_channels = num_input_channels
        self.batch_size = batch_size
        self.augment = augment
        self.crop_output_margin = crop_output_margin
        self.mask_myelin_threshold = mask_myelin_threshold
        self.dry_run = dry_run
        self.framework = framework
        # Accumulation/normalization stay float32 (blend exactness); this
        # only narrows the RESULT before it leaves the device. bfloat16
        # halves D2H bytes — on this environment's tunneled chip the
        # device->host link, not compute, bounds end-to-end throughput —
        # and uint8 quantizes on device exactly like the reference's
        # save-time float->uint8 conversion (save_precomputed.py:90-92),
        # quartering the bytes.
        if output_dtype not in ("float32", "bfloat16", "uint8"):
            raise ValueError(
                f"output_dtype must be float32, bfloat16 or uint8, got "
                f"{output_dtype!r}"
            )
        if output_dtype == "uint8" and mask_myelin_threshold is not None:
            raise ValueError(
                "mask_myelin_threshold compares [0,1] probabilities; "
                "combine it with float output_dtype, not uint8"
            )
        self.output_dtype = output_dtype
        if sharding not in ("none", "patch", "spatial", "spatial2d"):
            raise ValueError(f"unknown sharding mode {sharding!r}")
        self.sharding = sharding
        # Multi-chip mesh spec (docs/multichip.md): an explicit ``mesh``
        # argument ("data=8" / "y=4,x=2" / "auto") wins over the
        # CHUNKFLOW_MESH env var, which is re-read per chunk so the
        # ``CHUNKFLOW_MESH=1`` kill switch restores the single-device
        # path bit-identically at any moment. The legacy ``sharding``
        # names map onto the same unified engine (parallel/engine.py).
        if mesh is not None and sharding != "none":
            raise ValueError(
                f"mesh={mesh!r} does not compose with the legacy "
                f"sharding={sharding!r}; pick one"
            )
        self.mesh_spec = mesh
        self._shard_engines: dict = {}
        self._fold_mesh_noted = False
        # Optional shape bucketing (SURVEY §7 hard parts): pad every chunk
        # up to multiples of this zyx quantum so ragged edge chunks reuse
        # the same compiled program instead of recompiling per shape.
        # Trade-off: the convnet sees edge-replicated padding past the
        # true edge instead of the reference's edge-snapped real context,
        # so predictions within one patch of a padded face can differ —
        # hence opt-in.
        self.shape_bucket = (
            Cartesian.from_collection(shape_bucket)
            if shape_bucket is not None and any(shape_bucket)
            else None
        )
        if self.shape_bucket is not None and not self.shape_bucket.all_positive():
            raise ValueError(
                f"shape_bucket must be all-positive (or all-zero to "
                f"disable), got {tuple(self.shape_bucket)}"
            )
        # Blend strategy: "scatter" (runtime-coordinate scatter-add /
        # pallas, ops/blend.py), "fold" (static parity-class dense
        # overlap-add, ops/fold_blend.py; pads the chunk to a uniform
        # grid), "auto" (env CHUNKFLOW_BLEND or scatter). Fold applies to
        # the single-device path; sharded paths keep scatter.
        import os as _os

        if blend == "auto":
            blend = _os.environ.get("CHUNKFLOW_BLEND", "scatter").lower()
        if blend not in ("scatter", "fold"):
            raise ValueError(f"unknown blend mode {blend!r}")
        if blend == "fold" and sharding != "none":
            # loud, not silent: sharded programs use the scatter blend;
            # quietly running scatter would misattribute numbers to fold
            raise ValueError(
                f"blend='fold' applies to the single-device path only "
                f"(got sharding={sharding!r}); use blend='scatter' or "
                f"sharding='none'"
            )
        self.blend_mode = blend
        # optional explicit device set for the mesh engine (tests /
        # multihost bring-up inject a mesh here; its devices are used)
        self._mesh = None
        # one keyed cache for every program family this inferencer builds
        # (scatter/fold/patch/spatial/spatial2d); keys derive from the
        # BUCKETED run shape, so ragged edge chunks that pad into the
        # same bucket share one compiled program and never retrace. The
        # retrace watchdog warns past CHUNKFLOW_EXPECTED_PROGRAMS builds
        # (default 8: one per family plus a few fold/spatial geometries)
        # — the signature of a silent retrace per chunk.
        self._programs = ProgramCache(
            label="inferencer",
            expected_builds=int(
                _os.environ.get("CHUNKFLOW_EXPECTED_PROGRAMS", "8")
            ),
        )
        # persistent on-disk XLA cache: a worker restart skips the
        # multi-minute UNet compile (CHUNKFLOW_JAX_CACHE=0 disables)
        enable_persistent_cache()
        if bump != "wu":
            raise ValueError(f"only the 'wu' bump is implemented, got {bump!r}")
        if augment and (
            self.input_patch_size.y != self.input_patch_size.x
            or self.output_patch_size.y != self.output_patch_size.x
        ):
            raise ValueError(
                "test-time augmentation needs square yx input AND output patches"
            )

        self.engine = engines.create_engine(
            framework,
            engine=engine,
            input_patch_size=tuple(self.input_patch_size),
            output_patch_size=tuple(self.output_patch_size),
            num_output_channels=num_output_channels,
            num_input_channels=num_input_channels,
            model_path=model_path,
            weight_path=weight_path,
            dtype=dtype,
            model_variant=model_variant,
        )
        # Forward precision (inference/precision.py): an explicit
        # ``precision`` argument is strict; otherwise CHUNKFLOW_PRECISION
        # resolves once here (a per-chunk re-read would retrace every
        # program on a flip). float32 keeps engine.apply ITSELF — the
        # default path stays bitwise untouched; bf16/int8 wrap the
        # forward only, while blend accumulation stays float32. The
        # serving packer and the sharded engine both build on
        # ``_forward``, so every execution path shares one precision.
        from chunkflow_tpu.inference.precision import (
            resolve_precision,
            wrap_apply,
        )

        self.precision = resolve_precision(precision)
        self._apply = wrap_apply(self.engine.apply, self.precision)
        self._device_params = None

    # ------------------------------------------------------------------
    def _scatter_key(self) -> tuple:
        """ProgramCache key for the single-device blend program. The
        accumulation-kernel selection (XLA scatter vs the fused Pallas
        kernel, ops/blend.kernel_tag) AND the gather-front selection
        (``CHUNKFLOW_GATHER``, ops/pallas_gather.gather_key — empty for
        the default device leg) are part of the key, so flipping either
        env mid-stream builds the right program instead of reusing a
        stale one — the same re-read-per-chunk convention as
        ``CHUNKFLOW_MESH``. ``CHUNKFLOW_FUSED_PIPELINE`` joins too
        (ops/blend.pipeline_key): the pipeline forces both kernel legs,
        so a user already running PALLAS=interpret + GATHER=interpret
        would otherwise flip the pipeline without changing the key."""
        from chunkflow_tpu.ops.blend import kernel_tag, pipeline_key
        from chunkflow_tpu.ops.pallas_gather import gather_key

        tag = kernel_tag()
        base = ("scatter",) if tag == "scatter" else ("scatter_fused", tag)
        return base + gather_key() + pipeline_key()

    @property
    def _program(self):
        """The compiled single-device blend program, if built (tests) —
        whichever accumulation kernel and gather front it selected."""
        prog = self._programs.peek(("scatter",))
        if prog is not None:
            return prog
        for key, cached in self._programs.items():
            if key and key[0] in ("scatter", "scatter_fused"):
                return cached
        return None

    @property
    def _fold_programs(self) -> dict:
        """padded-shape -> program view of the fold family (tests)."""
        return {
            key[1]: prog
            for key, prog in self._programs.items()
            if key[0] == "fold"
        }

    # ------------------------------------------------------------------
    def _bucketed_shape(self, zyx) -> Cartesian:
        """Round a zyx shape up to the bucket quantum (and at least one
        input patch)."""
        return (
            Cartesian.from_collection(zyx).ceildiv(self.shape_bucket)
            * self.shape_bucket
        ).maximum(self.input_patch_size)

    def _run_shape(self, zyx) -> tuple:
        """The shape actually executed for an incoming chunk shape:
        bucketing, then (fold mode) a min-pad to one input patch so thin
        chunks work in BOTH the fold path and its scatter budget
        fallback. Shared by _infer and patch_grid_shape so the asserted
        grid can never drift from the executed one."""
        run = tuple(zyx)[-3:]
        if self.shape_bucket is not None:
            run = tuple(self._bucketed_shape(run))
        if self.blend_mode == "fold":
            run = tuple(
                max(length, p)
                for length, p in zip(run, tuple(self.input_patch_size))
            )
        return run

    def patch_grid_shape(self, chunk_shape) -> Tuple[int, int, int]:
        """Patches per axis for a chunk shape (reference --patch-num
        contract: the caller may assert the grid it planned for). Derived
        from the same enumerate_patches call the engine runs — including
        shape bucketing — so the asserted grid can never drift from the
        executed one."""
        shape = self._run_shape(chunk_shape)
        if self._use_fold(shape):
            _, grid_shape = self._fold_geometry(shape)
            return grid_shape
        grid = enumerate_patches(
            shape,
            self.input_patch_size,
            self.output_patch_size,
            self.output_patch_overlap,
        )
        return tuple(
            int(np.unique(grid.input_starts[:, i]).size) for i in range(3)
        )

    # ------------------------------------------------------------------
    @property
    def compute_device(self) -> str:
        import jax

        dev = jax.devices()[0]
        return f"{dev.platform}:{dev.device_kind}"

    # ------------------------------------------------------------------
    def _forward(self, params, patches):
        """Engine forward with optional 8-fold test-time augmentation.

        TTA variants are the product of {yx-transpose, y-flip, x-flip}
        (reference transform.py:114-156). The eight forwards run as a
        ``lax.scan`` over the stacked pre-transformed variants so XLA
        compiles the engine once (instead of unrolling eight compiled
        UNet copies into the program); the per-variant inverse transforms
        are static ops applied to the stacked scan output.
        """
        import jax.numpy as jnp
        from jax import lax

        if not self.augment:
            return self._apply(params, patches)

        combos = list(itertools.product((False, True), repeat=3))
        variants = []
        for transpose, flip_y, flip_x in combos:
            x = patches
            if flip_y:
                x = jnp.flip(x, axis=-2)
            if flip_x:
                x = jnp.flip(x, axis=-1)
            if transpose:
                x = jnp.swapaxes(x, -1, -2)
            variants.append(x)
        xs = jnp.stack(variants)  # [8, B, ci, *pin]

        _, ys = lax.scan(
            lambda c, x: (c, self._apply(params, x)), None, xs
        )

        acc = None
        for i, (transpose, flip_y, flip_x) in enumerate(combos):
            y = ys[i]
            if transpose:
                y = jnp.swapaxes(y, -1, -2)
            if flip_x:
                y = jnp.flip(y, axis=-1)
            if flip_y:
                y = jnp.flip(y, axis=-2)
            acc = y if acc is None else acc + y
        return acc / 8.0

    # ------------------------------------------------------------------
    def _build_program(self):
        import jax

        from chunkflow_tpu.ops.blend import build_local_blend, normalize_blend

        local_blend = build_local_blend(
            self._forward,
            self.num_input_channels,
            self.num_output_channels,
            tuple(self.input_patch_size),
            tuple(self.output_patch_size),
            self.batch_size,
            bump_map(tuple(self.output_patch_size)),
        )

        out_dtype = self.output_dtype

        def program(chunk, in_starts, out_starts, valid, params):
            out, weight = local_blend(chunk, in_starts, out_starts, valid, params)
            return normalize_blend(out, weight, out_dtype)

        # the chunk buffer is dead after the call (GL005): XLA may alias
        # it into the blend accumulator/output instead of allocating per
        # chunk — _infer guarantees the buffer is program-owned
        return jax.jit(program, donate_argnums=(0,))

    # ------------------------------------------------------------------
    def _fold_geometry(self, zyx):
        """(padded_shape, grid_shape) for the fold path — the ONE place
        fold geometry is derived, shared by patch_grid_shape, the fit
        check, and execution so the asserted grid never drifts from the
        executed one."""
        from chunkflow_tpu.ops.fold_blend import fold_grid, fold_pad_shape

        pin = tuple(self.input_patch_size)
        stride = tuple(self.output_patch_size - self.output_patch_overlap)
        padded = fold_pad_shape(tuple(zyx), pin, stride)
        return padded, fold_grid(padded, pin, stride)

    def _use_fold(self, zyx) -> bool:
        """Fold applies when selected AND the patch stacks fit the same
        byte budget that gates the stacked scatter path — jumbo chunks
        (e.g. 108x2048x2048 production tasks) fall back to the scan
        accumulate instead of OOMing HBM."""
        if self.blend_mode != "fold" or self.sharding != "none":
            return False
        from chunkflow_tpu.ops.blend import stack_budget_bytes

        budget = stack_budget_bytes()
        padded, grid = self._fold_geometry(zyx)
        n = int(np.prod(grid))
        pin = tuple(self.input_patch_size)
        pout = tuple(self.output_patch_size)
        co = self.num_output_channels
        # per patch: the input-patch stack, the prediction stack, its
        # bump-weighted float32 copy (fold materializes both), and the
        # weight-patch stack
        per_patch = 4 * (
            self.num_input_channels * int(np.prod(pin))
            + (2 * co + 1) * int(np.prod(pout))
        )
        # fixed: the parity-class accumulation buffers — two (co+1)-channel
        # float32 volumes at the padded shape (out+weight, double-buffered
        # across the dense adds)
        fixed = 8 * (co + 1) * int(np.prod(padded))
        return n * per_patch + fixed <= budget

    @contract(arr=Spec(None, "z", "y", "x", dtype="float32"))
    def _run_fold(self, arr):
        """Static-geometry scatter-free path (ops/fold_blend.py): pad to
        a uniform patch grid, run the cached per-shape fold program, crop
        back. Edge predictions within one patch of a padded face see
        EDGE-REPLICATED context (the closest uniform-grid analog of the
        reference's edge-snapped real context) rather than true snapped
        data — still a face-adjacent approximation, which is why fold is
        opt-in."""
        import jax.numpy as jnp

        from chunkflow_tpu.ops.fold_blend import build_fold_program

        pin = tuple(self.input_patch_size)
        pout = tuple(self.output_patch_size)
        stride = tuple(self.output_patch_size - self.output_patch_overlap)
        zyx = tuple(arr.shape[-3:])
        padded, _ = self._fold_geometry(zyx)
        if padded != zyx:
            pad = [(0, 0)] + [(0, p - s) for p, s in zip(padded, zyx)]
            # edge-replicate, not zeros: grid-edge patches then see real
            # boundary context (the closest uniform-grid analog of the
            # reference's edge-snapped patch starts,
            # inferencer.py:404-455); padded voxels are cropped below
            arr = jnp.pad(arr, pad, mode="edge")
        program = self._programs.get(
            ("fold", padded),
            lambda: build_fold_program(
                self._forward,
                self.num_input_channels,
                self.num_output_channels,
                pin,
                pout,
                stride,
                self.batch_size,
                bump_map(pout),
                padded,
                out_dtype=self.output_dtype,
            ),
        )
        result = program(arr, self._device_params)
        return result[:, : zyx[0], : zyx[1], : zyx[2]]

    # ------------------------------------------------------------------
    def _resolve_shard_spec(self):
        """The effective mesh spec for this call: legacy ``sharding``
        names map to fixed layouts over the local devices; otherwise the
        explicit ``mesh`` argument wins over ``CHUNKFLOW_MESH`` (env is
        re-read per chunk — the kill switch works mid-stream)."""
        from chunkflow_tpu.parallel.engine import MeshSpec, parse_mesh_spec

        if self.sharding != "none":
            import jax

            n = (self._mesh.devices.size if self._mesh is not None
                 else len(jax.local_devices()))
            if self.sharding == "patch":
                return (MeshSpec("data", (n,)) if n > 1
                        else MeshSpec("single", (1,)))
            if self.sharding == "spatial":
                return (MeshSpec("spatial", (n, 1)) if n > 1
                        else MeshSpec("single", (1,)))
            # spatial2d: near-square (y, x) factorization, y outer
            from chunkflow_tpu.parallel.spatial2d import near_square_shape

            return (MeshSpec("spatial", near_square_shape(n)) if n > 1
                    else MeshSpec("single", (1,)))
        if self.mesh_spec is not None:
            return parse_mesh_spec(self.mesh_spec)
        import os as _os

        return parse_mesh_spec(_os.environ.get("CHUNKFLOW_MESH", "1"))

    def shard_engine(self):
        """The unified sharded engine for the resolved mesh spec, or
        None for the single-device path (the ``CHUNKFLOW_MESH=1`` kill
        switch). Engines are cached per spec; their programs live in the
        shared :class:`ProgramCache`, so they get donation, shape-bucket
        keying and the roofline ledger like every other family."""
        from chunkflow_tpu.parallel.engine import ShardedEngine

        spec = self._resolve_shard_spec()
        if spec.kind == "single":
            return None
        engine = self._shard_engines.get(spec)
        if engine is None:
            devices = (
                self._mesh.devices.reshape(-1)
                if self._mesh is not None else None
            )
            engine = ShardedEngine.for_inferencer(
                self, spec, devices=devices
            )
            self._shard_engines[spec] = engine
        return engine

    def _run_sharded(self, arr, grid, shard_engine=None):
        """Multi-chip execution through the unified engine
        (parallel/engine.py): every mesh kind — patch-parallel 'data',
        1D y slabs, 2D (y, x) — produces output bitwise identical to the
        single-device program (forward sharded, reference accumulation
        replayed; see the engine docstring for the argument)."""
        engine = shard_engine if shard_engine is not None \
            else self.shard_engine()
        return engine.run(arr, grid, self._device_params,
                          host_params=self.engine.params)

    # ------------------------------------------------------------------
    def __call__(self, chunk: Chunk) -> Chunk:
        # host-side span around the whole dispatch+wait (never inside
        # the compiled program, GL007); blend mode labels the event so
        # fold-vs-scatter time is separable offline
        with telemetry.span("inference/infer", blend=self.blend_mode):
            result = self._infer(chunk, block=True)
        # achieved-Mvox/s numerator (host-side, GL007): the pipelined
        # paths count in flow/pipeline._drain_host instead
        shape = getattr(getattr(result, "array", None), "shape", None)
        if shape:
            voxels = 1
            for length in shape[-3:]:
                voxels *= int(length)
            telemetry.inc("inference/voxels", float(voxels))
        return result

    def stream(self, chunks, postprocess=None, post_depth: int = 2,
               ring: int = 2, prefetch_depth: int = 2, adaptive=None):
        """Pipelined inference over an iterable of chunks.

        While chunk *k* computes on device, chunk *k+1* is staged
        host→device into a ``ring``-slot staging ring and chunk *k−1*'s
        output drains device→host asynchronously. Yields host-resident
        output chunks in input order. Same-shape (or same-bucket) chunks
        reuse one compiled program.

        ``postprocess`` (optional callable ``Chunk -> T``) runs the host
        post-processing stage — e.g. watershed agglomeration, the stage
        the reference ships to separate CPU fleets
        (plugins/agglomerate.py:35-43) — in a background thread while the
        NEXT chunk's program executes on device, so host work hides
        behind chip time instead of serializing after it (VERDICT r4 #3).
        At most ``post_depth`` tasks in flight; abandoning the generator
        early cancels queued (not-yet-started) postprocess tasks.

        By default this routes through the adaptive scheduler
        (:func:`chunkflow_tpu.flow.scheduler.schedule_chunks`): the
        ``chunks`` iterable's own IO additionally runs
        ``prefetch_depth`` items ahead in a producer thread, and all
        depths widen under telemetry-driven control (docs/performance.md
        "Adaptive scheduler"). ``adaptive=False`` — or the
        ``CHUNKFLOW_SCHED=static`` kill switch — pins the PR 2
        double-buffered executor with the static depths given here.
        Outputs are bit-identical either way.
        """
        from chunkflow_tpu.flow.scheduler import (
            schedule_chunks,
            scheduler_mode,
        )

        if adaptive is None:
            adaptive = scheduler_mode() == "adaptive"
        if adaptive:
            return schedule_chunks(
                self, chunks, ring=ring, postprocess=postprocess,
                post_depth=post_depth, prefetch_depth=prefetch_depth,
            )
        from chunkflow_tpu.flow.pipeline import pipeline_chunks

        return pipeline_chunks(
            self, chunks, ring=ring, postprocess=postprocess,
            post_depth=post_depth,
        )

    def stage(self, chunk: Chunk) -> Chunk:
        """Start the chunk's async H2D transfer; returns a device-backed
        chunk whose payload buffer is OWNED BY THE PIPELINE — hand it to
        ``infer_async(..., consume=True)`` and drop the reference (the
        program donates and invalidates it). ``jax.device_put`` is async,
        so staging chunk k+1 overlaps chunk k's compute; narrow int
        dtypes ride the wire narrow (float conversion happens on device
        at infer time)."""
        if chunk.is_on_device:
            return chunk
        return chunk.device()

    def infer_async(self, chunk: Chunk, crop=None, consume: bool = False
                    ) -> Chunk:
        """Dispatch the fused program and start the result's D2H copy
        without blocking; materialize later with ``.host()``. Building
        block for pipelined drivers (``stream``, flow/pipeline.py, CLI
        --async-depth). ``crop`` applies an explicit margin crop ON
        DEVICE before the copy starts, so discarded margin voxels never
        ride D2H. ``consume`` transfers ownership of a device-resident
        input buffer to the program (donation: the caller's array is
        dead after the call) — only pass it for buffers you staged
        yourself and will not touch again."""
        out = self._infer(chunk, block=False, consume=consume)
        if crop is not None:
            out = out.crop_margin(crop)
        arr = out.array
        if hasattr(arr, "copy_to_host_async"):
            arr.copy_to_host_async()
        return out

    @property
    def _out_layer(self):
        return (
            LayerType.AFFINITY_MAP
            if self.num_output_channels == 3
            else LayerType.PROBABILITY_MAP
        )

    def _blank_output(self, chunk: Chunk) -> Chunk:
        """The dry-run / all-zero-input result: a zero chunk with the
        real path's channel count and dtype. Shared with the serving
        packer (chunkflow_tpu/serve/packer.py) so packed and per-chunk
        execution agree on the blank fast path too."""
        # channel count must match the real path, which drops the myelin
        # channel when mask_myelin_threshold is set
        nchan = self.num_output_channels
        if self.mask_myelin_threshold is not None:
            nchan -= 1
        import ml_dtypes

        blank_dtype = {
            "float32": np.float32,
            "bfloat16": ml_dtypes.bfloat16,
            "uint8": np.uint8,
        }[self.output_dtype]
        out = Chunk.from_bbox(
            chunk.bbox,
            # match the real path's result dtype so a volume mixing
            # blank and real chunks stays dtype-consistent
            dtype=blank_dtype,
            nchannels=nchan,
            voxel_size=chunk.voxel_size,
        )
        out.layer_type = self._out_layer
        if self.crop_output_margin:
            out = out.crop_margin(self.crop_margin)
        return out

    def _postprocess_result(self, result, chunk: Chunk,
                            orig_zyx, run_zyx) -> Chunk:
        """Crop bucket padding, wrap, myelin-mask and margin-crop a raw
        program result — the single definition of "what happens after
        the blend", shared by :meth:`_infer` and the serving packer so
        the two paths cannot drift."""
        if run_zyx != orig_zyx:
            result = result[
                :, : orig_zyx[0], : orig_zyx[1], : orig_zyx[2]
            ]
        out = Chunk(
            result,
            voxel_offset=chunk.voxel_offset,
            voxel_size=chunk.voxel_size,
            layer_type=self._out_layer,
        )
        if self.mask_myelin_threshold is not None:
            out = out.mask_using_last_channel(
                threshold=self.mask_myelin_threshold
            )
        if self.crop_output_margin:
            out = out.crop_margin(self.crop_margin)
        return out

    @contract(chunk=Spec(ndim=(3, 4)))
    def _infer(self, chunk: Chunk, block: bool, consume: bool = False) -> Chunk:
        import jax
        import jax.numpy as jnp

        if self.dry_run or chunk.all_zero():
            return self._blank_output(chunk)

        orig_zyx = tuple(chunk.shape[-3:])
        run_zyx = self._run_shape(orig_zyx)

        use_fold = self._use_fold(run_zyx)
        if self.blend_mode == "fold" and not use_fold:
            # loud, not silent: numbers measured under this config belong
            # to the scatter fallback, not fold (same misattribution
            # guard as the pallas/fold selection errors)
            print(
                f"fold blend gated off for shape {run_zyx}: patch stacks "
                f"exceed CHUNKFLOW_BLEND_STACK_MAX_GB; using per-batch "
                f"scatter fallback",
                file=sys.stderr,
            )
        shard_engine = None
        if use_fold:
            if not self._fold_mesh_noted:
                self._fold_mesh_noted = True
                if self._resolve_shard_spec().kind != "single":
                    print(
                        "fold blend is a single-device program; the "
                        "configured mesh spec is ignored for fold "
                        "traffic (use blend='scatter' to shard)",
                        file=sys.stderr,
                    )
        else:
            shard_engine = self.shard_engine()
        grid = None
        if not use_fold:
            # the scatter grid; fold derives its own (and supports chunks
            # thinner than the input patch via padding, which
            # enumerate_patches rejects)
            grid = enumerate_patches(
                run_zyx,
                self.input_patch_size,
                self.output_patch_size,
                self.output_patch_overlap,
            )

        from chunkflow_tpu.core import profiling
        from chunkflow_tpu.ops import pallas_gather

        arr = chunk.array
        was_on_device = chunk.is_on_device
        if not was_on_device:
            arr = np.asarray(arr)
        # int images normalize to [0, 1] float32 (reference :395-399).
        # Transfer the NARROW dtype: a uint8 EM chunk rides H2D at 1/4
        # the bytes of a host-side float32 conversion. With the
        # device-resident front half (ISSUE 15, the default) the chunk
        # stays RAW past this point too — the selected gather leg
        # (ops/pallas_gather.py) converts inside the program (whole-chunk
        # on the XLA leg, per-tile in VMEM on the Pallas leg).
        # CHUNKFLOW_GATHER=off restores the eager pre-program conversion
        # below bit-identically (conversion and edge-padding commute
        # exactly with slicing); fold keeps it — its program family
        # contracts on float32 input.
        dt = np.dtype(chunk.dtype)
        raw_front = (
            not use_fold
            and pallas_gather.gather_mode() != "host"
            and pallas_gather.raw_eligible(dt)
        )
        if raw_front:
            arr = jnp.asarray(arr)
            h2d_nbytes = arr.nbytes
        elif dt.kind in "iu":
            scale = np.float32(1.0 / np.iinfo(dt).max)
            if dt.itemsize <= 4:
                h2d_nbytes = arr.nbytes
                arr = jnp.asarray(arr).astype(jnp.float32) * scale
            else:
                # 64-bit ints would silently wrap in jnp.asarray (x64
                # disabled downcasts to 32-bit first); convert on host
                arr = jnp.asarray(np.asarray(arr, dtype=np.float32)) * scale
                h2d_nbytes = arr.nbytes
        else:
            arr = jnp.asarray(arr, dtype=jnp.float32)
            h2d_nbytes = arr.nbytes
        if arr is chunk.array and not consume:
            # every inference program donates its chunk argument (GL005):
            # the buffer is dead after the call. A device-resident float32
            # chunk passes through jnp.asarray unchanged, so donating it
            # would invalidate the CALLER's array mid-flight — copy unless
            # the caller declared ownership transfer (consume=True, the
            # pipelined executor's staged ring slots).
            arr = arr.copy()
        if arr.ndim == 3:
            arr = arr[None]
        if run_zyx != orig_zyx:
            pad = [(0, 0)] + [
                (0, r - s) for r, s in zip(run_zyx, orig_zyx)
            ]
            # shape-bucket padding replicates the boundary plane so the
            # net sees plausible context instead of a zero wall
            arr = jnp.pad(arr, pad, mode="edge")

        if self._device_params is None:
            self._device_params = jax.device_put(self.engine.params)

        if not was_on_device:
            # the staging seam: per-chunk H2D bytes (transfer/h2d_*;
            # pipeline-staged chunks count in Chunk.device instead),
            # attributed to the program family about to consume them
            if use_fold:
                h2d_key = ("fold",)
            elif shard_engine is None:
                h2d_key = self._scatter_key()
            else:
                h2d_key = ("shard",)
            profiling.note_h2d(h2d_nbytes, key=h2d_key)

        if use_fold:
            result = self._run_fold(arr)
        elif shard_engine is None:
            in_starts, out_starts, valid = pad_to_batch(grid, self.batch_size)
            program = self._programs.get(self._scatter_key(),
                                         self._build_program)
            result = program(
                arr,
                jnp.asarray(in_starts),
                jnp.asarray(out_starts),
                jnp.asarray(valid),
                self._device_params,
            )
        else:
            result = self._run_sharded(arr, grid, shard_engine)
        if block:
            result.block_until_ready()
        return self._postprocess_result(result, chunk, orig_zyx, run_zyx)
