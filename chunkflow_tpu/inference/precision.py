"""Low-precision forward variants behind the ``CHUNKFLOW_PRECISION`` spec.

"Improving Diffusion Model Efficiency Through Patching" (PAPERS.md)
motivates the patch-size/precision trade-off for exactly this patch-wise
workload: the convnet forward is the FLOPs side of the roofline, and
narrowing its compute dtype buys MXU throughput and HBM bandwidth at a
bounded output-error cost. This module is the single seam where that
trade is made:

- ``float32`` (default): the wrapper returns the engine's apply
  UNTOUCHED — the same callable object — so the default path stays
  bitwise identical to the pre-precision code (the measured-winner rule:
  no unmeasured variant ships as default).
- ``bfloat16``: the patch batch and every floating-point parameter leaf
  are rounded to bfloat16 at the engine boundary; engines built with a
  bfloat16 compute dtype (``Inferencer(dtype="bfloat16")``) then run
  their matmuls/convs natively narrow, and float32-dtype engines still
  see bfloat16-rounded values (the quantization-error model the test
  suite bounds). The result is cast back to float32.
- ``int8``: symmetric fake quantization (round-to-nearest-even onto a
  255-level [-127, 127] grid) of the patch batch and every
  floating-point parameter leaf, computed in float32 — the standard W8A8
  simulation. Parameters quantize per-tensor; activations quantize
  PER-ROW (one scale per patch), which keeps quantization independent of
  batch composition — the property the packed-serve and mesh bitwise
  parity contracts rest on. Real int8 matmul kernels are an engine-level
  concern; this wrapper is supported wherever the engine's parameters
  are ordinary float arrays, which is every in-repo engine.

What precision does NOT touch: the blend. Accumulation and weight
buffers stay float32 (``ops/blend.py``), ``normalize_blend``'s uint8
quantization contract is unchanged, and the packed-serve/mesh parity
contracts survive — the wrapper replaces the forward uniformly at the
``Inferencer._forward`` seam, which the serving packer and the sharded
engine both inherit, so packed-vs-per-chunk and mesh-vs-single stay
bitwise identical AT EVERY PRECISION (same wrapped forward, same
replayed accumulation).

Selection: explicit ``Inferencer(precision=...)`` wins (strict —
unknown values raise); otherwise the ``CHUNKFLOW_PRECISION`` env var,
resolved once at Inferencer construction (a per-chunk re-read would
retrace every program on a flip). Unrecognized env values warn ONCE on
stderr and fall back to float32 — a typo must not silently select a
quantized path, mirroring the ``CHUNKFLOW_PALLAS`` convention.

Gates: the quantization-error suite (tests/inference/test_precision.py)
bounds bf16/int8 output error against the float32 reference on the
identity AND conv engines, including ragged and crop-margin traffic.
"""
from __future__ import annotations

from typing import Callable, Optional

from chunkflow_tpu.core import envmode

__all__ = ["PRECISIONS", "resolve_precision", "wrap_apply"]

PRECISIONS = ("float32", "bfloat16", "int8")

_ALIASES = {"f32": "float32", "fp32": "float32", "bf16": "bfloat16",
            "i8": "int8"}

_MODE_CHOICES = {
    "float32": ("", "float32"),
    "bfloat16": ("bfloat16",),
    "int8": ("int8",),
}

_WARNED_VALUES: set = set()


def resolve_precision(value: Optional[str] = None) -> str:
    """The effective forward precision. An explicit ``value`` is strict
    (unknown -> ``ValueError``); the ``CHUNKFLOW_PRECISION`` env var is
    lenient (unknown -> one-time stderr warning, float32 — the shared
    warn-once contract in core/envmode.py)."""
    if value is not None:
        v = str(value).lower()
        v = _ALIASES.get(v, v)
        if v not in PRECISIONS:
            raise ValueError(
                f"precision must be one of {PRECISIONS} (got {value!r})"
            )
        return v
    return envmode.resolve(
        "CHUNKFLOW_PRECISION", _MODE_CHOICES, default="float32",
        note="running the float32 default — a typo must not silently "
             "select a quantized forward",
        warned=_WARNED_VALUES,
        normalize=lambda env: _ALIASES.get(env, env),
    )


def _cast_float_leaves(tree, dtype):
    import jax
    import jax.numpy as jnp

    def cast(leaf):
        if hasattr(leaf, "dtype") and jnp.issubdtype(
                jnp.asarray(leaf).dtype, jnp.floating):
            return jnp.asarray(leaf, dtype)
        return leaf

    return jax.tree_util.tree_map(cast, tree)


def _fake_quant_int8(x, per_row: bool = False):
    """Symmetric int8 fake quantization in float32: round-to-nearest-even
    onto the [-127, 127] grid at scale absmax/127 — per-tensor for
    parameters, PER-ROW (``per_row=True``, one scale per leading-axis
    entry) for activation batches. Per-row matters for more than
    accuracy: a per-tensor activation scale would depend on which rows
    share a batch, breaking the row-independence property the serving
    packer's and the sharded engine's bitwise parity contracts rest on;
    with one scale per patch, quantization commutes with batch
    composition. An all-zero tensor (or row — the packer's filler slots)
    maps to exact zeros (the eps floor keeps the divide defined)."""
    import jax.numpy as jnp

    x = jnp.asarray(x, jnp.float32)
    if per_row and x.ndim > 1:
        axes = tuple(range(1, x.ndim))
        amax = jnp.max(jnp.abs(x), axis=axes, keepdims=True)
    else:
        amax = jnp.max(jnp.abs(x))
    scale = jnp.maximum(amax, jnp.float32(1e-12)) / jnp.float32(127.0)
    q = jnp.clip(jnp.round(x / scale), -127.0, 127.0)
    return q * scale


def _quant_float_leaves(tree):
    import jax
    import jax.numpy as jnp

    def quant(leaf):
        if hasattr(leaf, "dtype") and jnp.issubdtype(
                jnp.asarray(leaf).dtype, jnp.floating):
            return _fake_quant_int8(leaf)
        return leaf

    return jax.tree_util.tree_map(quant, tree)


def wrap_apply(apply: Callable, precision: str) -> Callable:
    """Wrap an engine ``apply(params, batch)`` for the given precision.
    ``float32`` returns ``apply`` ITSELF (same object — the bitwise
    guarantee of the default path); the narrow variants quantize the
    batch and the float parameter leaves at the boundary and return
    float32 results for the float32 blend accumulation."""
    if precision == "float32":
        return apply
    if precision == "bfloat16":
        def bf16_apply(params, batch):
            import jax.numpy as jnp

            p = _cast_float_leaves(params, jnp.bfloat16)
            out = apply(p, jnp.asarray(batch, jnp.bfloat16))
            return jnp.asarray(out, jnp.float32)

        return bf16_apply
    if precision == "int8":
        def int8_apply(params, batch):
            import jax.numpy as jnp

            p = _quant_float_leaves(params)
            out = apply(p, _fake_quant_int8(batch, per_row=True))
            return jnp.asarray(out, jnp.float32)

        return int8_apply
    raise ValueError(f"unknown precision {precision!r}")
