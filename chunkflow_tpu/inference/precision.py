"""Low-precision forward variants behind the ``CHUNKFLOW_PRECISION`` spec.

"Improving Diffusion Model Efficiency Through Patching" (PAPERS.md)
motivates the patch-size/precision trade-off for exactly this patch-wise
workload: the convnet forward is the FLOPs side of the roofline, and
narrowing its compute dtype buys MXU throughput and HBM bandwidth at a
bounded output-error cost. This module is the single seam where that
trade is made:

- ``float32`` (default): the wrapper returns the engine's apply
  UNTOUCHED — the same callable object — so the default path stays
  bitwise identical to the pre-precision code (the measured-winner rule:
  no unmeasured variant ships as default).
- ``bfloat16``: the patch batch and every floating-point parameter leaf
  are rounded to bfloat16 at the engine boundary; engines built with a
  bfloat16 compute dtype (``Inferencer(dtype="bfloat16")``) then run
  their matmuls/convs natively narrow, and float32-dtype engines still
  see bfloat16-rounded values (the quantization-error model the test
  suite bounds). The result is cast back to float32.
- ``int8``: W8A8 in two legs behind ``CHUNKFLOW_INT8`` (ISSUE 17).
  ``fake`` (the default — the reference/kill-switch leg): symmetric fake
  quantization (round-to-nearest-even onto a 255-level [-127, 127]
  grid) of the patch batch and every floating-point parameter leaf at
  the engine boundary, computed in float32 — the standard W8A8
  simulation running f32 matmuls. ``real``: the engine's jaxpr is
  re-evaluated with every ``dot_general``/``conv_general_dilated``
  replaced by a REAL integer MXU op — int8 operands,
  ``preferred_element_type=jnp.int32`` accumulation — with weights
  quantized per-tensor and activations per-row at each matmul, then
  dequantized ``prod_f32 * (s_act * s_w)``. ``fakeint`` is the real
  leg's f32 twin (same interpreter, same integer-grid operands, f32
  arithmetic): where the integer dot's accumulator sums stay below
  2^24 the f32 products are exact, so ``real`` and ``fakeint`` agree
  BITWISE — the agreement oracle tests/inference/test_precision.py
  pins on the identity and small-conv engines. In every leg parameters
  quantize per-tensor and activations PER-ROW (one scale per
  leading-axis/batch entry), which keeps quantization independent of
  batch composition — the property the packed-serve and mesh bitwise
  parity contracts rest on.

What precision does NOT touch: the blend. Accumulation and weight
buffers stay float32 (``ops/blend.py``), ``normalize_blend``'s uint8
quantization contract is unchanged, and the packed-serve/mesh parity
contracts survive — the wrapper replaces the forward uniformly at the
``Inferencer._forward`` seam, which the serving packer and the sharded
engine both inherit, so packed-vs-per-chunk and mesh-vs-single stay
bitwise identical AT EVERY PRECISION (same wrapped forward, same
replayed accumulation).

Selection: explicit ``Inferencer(precision=...)`` wins (strict —
unknown values raise); otherwise the ``CHUNKFLOW_PRECISION`` env var,
resolved once at Inferencer construction (a per-chunk re-read would
retrace every program on a flip). Unrecognized env values warn ONCE on
stderr and fall back to float32 — a typo must not silently select a
quantized path, mirroring the ``CHUNKFLOW_PALLAS`` convention.

Gates: the quantization-error suite (tests/inference/test_precision.py)
bounds bf16/int8 output error against the float32 reference on the
identity AND conv engines, including ragged and crop-margin traffic.
"""
from __future__ import annotations

from typing import Callable, Optional

from chunkflow_tpu.core import envmode

__all__ = ["PRECISIONS", "resolve_precision", "wrap_apply", "int8_mode",
           "wrap_stages", "precision_tag"]

PRECISIONS = ("float32", "bfloat16", "int8")

_ALIASES = {"f32": "float32", "fp32": "float32", "bf16": "bfloat16",
            "i8": "int8"}

_MODE_CHOICES = {
    "float32": ("", "float32"),
    "bfloat16": ("bfloat16",),
    "int8": ("int8",),
}

_WARNED_VALUES: set = set()


def resolve_precision(value: Optional[str] = None) -> str:
    """The effective forward precision. An explicit ``value`` is strict
    (unknown -> ``ValueError``); the ``CHUNKFLOW_PRECISION`` env var is
    lenient (unknown -> one-time stderr warning, float32 — the shared
    warn-once contract in core/envmode.py)."""
    if value is not None:
        v = str(value).lower()
        v = _ALIASES.get(v, v)
        if v not in PRECISIONS:
            raise ValueError(
                f"precision must be one of {PRECISIONS} (got {value!r})"
            )
        return v
    return envmode.resolve(
        "CHUNKFLOW_PRECISION", _MODE_CHOICES, default="float32",
        note="running the float32 default — a typo must not silently "
             "select a quantized forward",
        warned=_WARNED_VALUES,
        normalize=lambda env: _ALIASES.get(env, env),
    )


def _cast_float_leaves(tree, dtype):
    import jax
    import jax.numpy as jnp

    def cast(leaf):
        if hasattr(leaf, "dtype") and jnp.issubdtype(
                jnp.asarray(leaf).dtype, jnp.floating):
            return jnp.asarray(leaf, dtype)
        return leaf

    return jax.tree_util.tree_map(cast, tree)


def _fake_quant_int8(x, per_row: bool = False):
    """Symmetric int8 fake quantization in float32: round-to-nearest-even
    onto the [-127, 127] grid at scale absmax/127 — per-tensor for
    parameters, PER-ROW (``per_row=True``, one scale per leading-axis
    entry) for activation batches. Per-row matters for more than
    accuracy: a per-tensor activation scale would depend on which rows
    share a batch, breaking the row-independence property the serving
    packer's and the sharded engine's bitwise parity contracts rest on;
    with one scale per patch, quantization commutes with batch
    composition. An all-zero tensor (or row — the packer's filler slots)
    maps to exact zeros (the eps floor keeps the divide defined)."""
    import jax.numpy as jnp

    x = jnp.asarray(x, jnp.float32)
    if per_row and x.ndim > 1:
        axes = tuple(range(1, x.ndim))
        amax = jnp.max(jnp.abs(x), axis=axes, keepdims=True)
    else:
        amax = jnp.max(jnp.abs(x))
    scale = jnp.maximum(amax, jnp.float32(1e-12)) / jnp.float32(127.0)
    q = jnp.clip(jnp.round(x / scale), -127.0, 127.0)
    return q * scale


def _quant_float_leaves(tree):
    import jax
    import jax.numpy as jnp

    def quant(leaf):
        if hasattr(leaf, "dtype") and jnp.issubdtype(
                jnp.asarray(leaf).dtype, jnp.floating):
            return _fake_quant_int8(leaf)
        return leaf

    return jax.tree_util.tree_map(quant, tree)


_INT8_CHOICES = {
    "fake": ("", "fake", "0", "off"),
    "real": ("real", "1", "on"),
    "fakeint": ("fakeint",),
}
_INT8_WARNED: set = set()


def int8_mode() -> str:
    """'fake' | 'real' | 'fakeint' — the ``CHUNKFLOW_INT8`` leg of the
    int8 precision (resolved at :func:`wrap_apply` time, i.e. once per
    Inferencer, like ``CHUNKFLOW_PRECISION`` itself — a per-chunk
    re-read would retrace every program on a flip). ``fake`` is the
    measured default (boundary fake-quant, f32 matmuls — the
    reference/kill-switch leg); ``real`` runs integer-accumulating MXU
    matmuls (``preferred_element_type=jnp.int32``); ``fakeint`` is the
    real leg's exact-f32 twin for the bitwise agreement oracle."""
    return envmode.resolve(
        "CHUNKFLOW_INT8", _INT8_CHOICES, default="fake",
        note="running the fake-quant reference leg — a typo must not "
             "silently select the real integer matmul path",
        warned=_INT8_WARNED,
    )


def _quant_rows_axis(x, axis: int):
    """Integer grid + scale for a tainted (activation) operand: one
    scale per index along ``axis``, reduced over every other axis —
    the same 255-level grid expression as :func:`_fake_quant_int8`
    (identical rounding, identical eps floor), factored so the real
    and fake legs quantize onto IDENTICAL integer values. Returns
    ``(q, scale)`` with ``q`` float32-valued integers in [-127, 127]
    and ``scale`` keeping ``keepdims`` shape."""
    import jax.numpy as jnp

    x = jnp.asarray(x, jnp.float32)
    axes = tuple(i for i in range(x.ndim) if i != axis)
    if axes:
        amax = jnp.max(jnp.abs(x), axis=axes, keepdims=True)
    else:
        amax = jnp.abs(x)
    scale = jnp.maximum(amax, jnp.float32(1e-12)) / jnp.float32(127.0)
    q = jnp.clip(jnp.round(x / scale), -127.0, 127.0)
    return q, scale


def _quant_tensor(x):
    """Per-tensor integer grid + scalar scale (the weight-side rule)."""
    import jax.numpy as jnp

    x = jnp.asarray(x, jnp.float32)
    amax = jnp.max(jnp.abs(x))
    scale = jnp.maximum(amax, jnp.float32(1e-12)) / jnp.float32(127.0)
    q = jnp.clip(jnp.round(x / scale), -127.0, 127.0)
    return q, scale


def _scale_to_out(scale, out_ndim: int, out_axis: int):
    """Reshape a per-row scale (keepdims shape) to broadcast along the
    output's ``out_axis``; scalars pass through."""
    import jax.numpy as jnp

    s = jnp.asarray(scale)
    if s.size == 1:
        return s.reshape(())
    shape = [1] * out_ndim
    shape[out_axis] = s.size
    return s.reshape(shape)


def _int8_dot(params, lhs, rhs, lhs_tainted, rhs_tainted, integer):
    """One ``dot_general`` at W8A8: tainted (activation) operands
    quantize per-row over their leading axis when it is a free
    (non-contracting, non-batch) dim — the batch-composition-safe rule
    — otherwise per-tensor; untainted (weight) operands per-tensor.
    ``integer=True`` runs int8 operands with int32 accumulation (the
    real MXU op); ``integer=False`` is the exact-f32 twin on the same
    integer grid. Dequant is ``prod_f32 * (s_lhs * s_rhs)`` — one
    expression, one order, so the two legs agree bitwise wherever the
    integer sums stay below 2^24 (exact in f32)."""
    import jax.numpy as jnp
    from jax import lax

    dn = params["dimension_numbers"]
    (lc, rc), (lb, rb) = dn
    free_l = sorted(set(range(jnp.ndim(lhs))) - set(lc) - set(lb))
    free_r = sorted(set(range(jnp.ndim(rhs))) - set(rc) - set(rb))

    def quant(x, tainted, free):
        if tainted and jnp.ndim(x) > 1 and 0 in free:
            return _quant_rows_axis(x, 0)
        return _quant_tensor(x)

    ql, sl = quant(lhs, lhs_tainted, free_l)
    qr, sr = quant(rhs, rhs_tainted, free_r)
    if integer:
        prod = lax.dot_general(
            ql.astype(jnp.int8), qr.astype(jnp.int8),
            dimension_numbers=dn,
            preferred_element_type=jnp.int32,
        ).astype(jnp.float32)
    else:
        prod = lax.dot_general(
            ql, qr, dimension_numbers=dn,
            preferred_element_type=jnp.float32,
        )
    # output layout: batch dims, then lhs free dims, then rhs free dims
    sl_b = _scale_to_out(sl, prod.ndim,
                         len(lb) + (free_l.index(0) if 0 in free_l else 0))
    sr_b = _scale_to_out(
        sr, prod.ndim,
        len(lb) + len(free_l) + (free_r.index(0) if 0 in free_r else 0))
    return prod * (sl_b * sr_b)


def _int8_conv(params, lhs, rhs, lhs_tainted, integer):
    """One ``conv_general_dilated`` at W8A8: the image (lhs) quantizes
    per-row over its batch axis (``dimension_numbers.lhs_spec[0]``)
    when tainted, the kernel (rhs) per-tensor; same integer/f32-twin
    and dequant contract as :func:`_int8_dot`."""
    import jax.numpy as jnp
    from jax import lax

    dn = params["dimension_numbers"]
    if lhs_tainted:
        ql, sl = _quant_rows_axis(lhs, dn.lhs_spec[0])
    else:
        ql, sl = _quant_tensor(lhs)
    qr, sr = _quant_tensor(rhs)
    kwargs = dict(
        window_strides=params["window_strides"],
        padding=params["padding"],
        lhs_dilation=params["lhs_dilation"],
        rhs_dilation=params["rhs_dilation"],
        dimension_numbers=dn,
        feature_group_count=params["feature_group_count"],
        batch_group_count=params.get("batch_group_count", 1),
    )
    if integer:
        prod = lax.conv_general_dilated(
            ql.astype(jnp.int8), qr.astype(jnp.int8),
            preferred_element_type=jnp.int32, **kwargs,
        ).astype(jnp.float32)
    else:
        prod = lax.conv_general_dilated(
            ql, qr, preferred_element_type=jnp.float32, **kwargs,
        )
    sl_b = _scale_to_out(sl, prod.ndim, dn.out_spec[0])
    return prod * (sl_b * sr)


def _eval_int8_jaxpr(jaxpr, consts, in_pairs, integer, Literal):
    """Evaluate a jaxpr with every matmul/conv touched by activation
    data replaced by its W8A8 form. ``in_pairs`` is ``[(value, taint)]``
    per invar; taint marks values derived from the patch batch (the
    activations) — untainted values are parameters and their derived
    tensors (the weights). Every other primitive binds unchanged (f32
    math on the dequantized values, exactly like the fake leg's body).
    ``pjit`` and ``custom_jvp/vjp`` bodies are evaluated recursively so
    matmuls inside jitted/custom-gradient engine blocks are still
    intercepted; other higher-order primitives (scan, while) bind
    as-is — none of the in-repo engines put matmuls inside them."""
    env = {}

    def read(v):
        if isinstance(v, Literal):
            return v.val, False
        return env[v]

    for var, val in zip(jaxpr.constvars, consts):
        env[var] = (val, False)
    for var, pair in zip(jaxpr.invars, in_pairs):
        env[var] = pair

    for eqn in jaxpr.eqns:
        pairs = [read(v) for v in eqn.invars]
        vals = [p[0] for p in pairs]
        taints = [p[1] for p in pairs]
        out_taint = any(taints)
        name = eqn.primitive.name
        if name == "dot_general" and out_taint:
            outs = [_int8_dot(eqn.params, vals[0], vals[1],
                              taints[0], taints[1], integer)]
        elif name == "conv_general_dilated" and out_taint:
            outs = [_int8_conv(eqn.params, vals[0], vals[1],
                               taints[0], integer)]
        elif name == "pjit" and out_taint:
            inner = eqn.params["jaxpr"]
            results = _eval_int8_jaxpr(inner.jaxpr, inner.consts,
                                       pairs, integer, Literal)
            outs = [val for val, _ in results]
        elif (name in ("custom_jvp_call", "custom_vjp_call")
              and out_taint
              and "call_jaxpr" in eqn.params
              and len(eqn.params["call_jaxpr"].jaxpr.invars)
              == len(pairs)):
            inner = eqn.params["call_jaxpr"]
            results = _eval_int8_jaxpr(inner.jaxpr, inner.consts,
                                       pairs, integer, Literal)
            outs = [val for val, _ in results]
        else:
            subfuns, bind_params = eqn.primitive.get_bind_params(
                eqn.params)
            result = eqn.primitive.bind(*subfuns, *vals, **bind_params)
            outs = (list(result) if eqn.primitive.multiple_results
                    else [result])
        for var, out in zip(eqn.outvars, outs):
            env[var] = (out, out_taint)

    return [read(v) for v in jaxpr.outvars]


def _int8_graph_apply(apply: Callable, params, batch, integer: bool):
    """The real-int8 forward: trace ``apply`` to a jaxpr, then replay
    it with activation-touched matmuls in W8A8 (``integer=True`` for
    int32-accumulating int8 ops, ``False`` for the exact-f32 twin).
    Runs under the caller's jit — the integer ops land in the outer
    program's jaxpr, where the test suite probes
    ``preferred_element_type=int32``."""
    import jax

    try:
        from jax.extend.core import Literal
    except ImportError:  # older jax layouts
        from jax.core import Literal

    closed, out_shape = jax.make_jaxpr(apply, return_shape=True)(
        params, batch)
    n_params = len(jax.tree_util.tree_leaves(params))
    flat = jax.tree_util.tree_leaves((params, batch))
    pairs = [(v, i >= n_params) for i, v in enumerate(flat)]
    out_pairs = _eval_int8_jaxpr(closed.jaxpr, closed.consts, pairs,
                                 integer, Literal)
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(out_shape),
        [val for val, _ in out_pairs])


def wrap_apply(apply: Callable, precision: str) -> Callable:
    """Wrap an engine ``apply(params, batch)`` for the given precision.
    ``float32`` returns ``apply`` ITSELF (same object — the bitwise
    guarantee of the default path); the narrow variants quantize the
    batch and the float parameter leaves at the boundary and return
    float32 results for the float32 blend accumulation."""
    if precision == "float32":
        return apply
    if precision == "bfloat16":
        def bf16_apply(params, batch):
            import jax.numpy as jnp

            p = _cast_float_leaves(params, jnp.bfloat16)
            out = apply(p, jnp.asarray(batch, jnp.bfloat16))
            return jnp.asarray(out, jnp.float32)

        return bf16_apply
    if precision == "int8":
        mode = int8_mode()  # resolved once, at wrap time
        if mode == "fake":
            def int8_apply(params, batch):
                import jax.numpy as jnp

                p = _quant_float_leaves(params)
                out = apply(p, _fake_quant_int8(batch, per_row=True))
                return jnp.asarray(out, jnp.float32)

            return int8_apply

        integer = mode == "real"

        def int8_real_apply(params, batch):
            import jax.numpy as jnp

            out = _int8_graph_apply(apply, params, batch, integer)
            return jnp.asarray(out, jnp.float32)

        return int8_real_apply
    raise ValueError(f"unknown precision {precision!r}")


def precision_tag(precision: str) -> str:
    """The resolved forward precision as a ProgramCache key component:
    ``""`` for the float32 default (the no-suffix-for-the-default
    convention every knob shares), ``"prec-bfloat16"``, or
    ``"prec-int8-<fake|real|fakeint>"`` with the ``CHUNKFLOW_INT8`` leg
    folded in (the leg changes the traced program, so it is program
    identity). Joined into the sharded-engine program keys (ISSUE 19:
    precision tags compose with the pipeline/gather/kernel tags in
    shard cache keys)."""
    if precision == "float32":
        return ""
    if precision == "int8":
        return f"prec-int8-{int8_mode()}"
    return f"prec-{precision}"


def wrap_stages(stage_bodies, stage_tail, precision: str):
    """Precision-wrap a staged engine (the stage protocol,
    parallel/pipeline.py) so that the composition of the wrapped pieces
    is BITWISE :func:`wrap_apply` of the unwrapped composition — the
    identity the pipeline mesh's parity contract rests on. Returns
    ``(entry, bodies, tail)``:

    - ``entry(x)`` — the one-time activation boundary cast, applied to
      the gathered patch batch BEFORE it enters stage 0 (so the ring
      activation dtype is uniform: the ``where(stage==0, ...)`` merge
      of fresh patches and ``ppermute``-received activations sees one
      dtype);
    - ``bodies`` — per-stage wrapped bodies (parameter leaves cast at
      each stage, activations untouched — they already carry the entry
      cast);
    - ``tail`` — the wrapped tail (parameter cast + the float32 result
      cast the blend accumulation requires).

    float32 returns everything UNTOUCHED (same objects — the bitwise
    default-path rule). The int8 ``real``/``fakeint`` legs re-evaluate
    the whole forward's jaxpr (:func:`_int8_graph_apply`) and cannot be
    split at stage seams; they return ``(None, None, None)`` and a
    pipeline mesh fails loudly naming the constraint."""
    if stage_bodies is None or stage_tail is None:
        return None, None, None
    if precision == "float32":
        return (lambda x: x), tuple(stage_bodies), stage_tail
    if precision == "bfloat16":
        import jax.numpy as jnp

        def entry(x):
            return jnp.asarray(x, jnp.bfloat16)

        bodies = tuple(
            (lambda params, x, _b=body:
             _b(_cast_float_leaves(params, jnp.bfloat16), x))
            for body in stage_bodies
        )

        def tail(params, x):
            out = stage_tail(_cast_float_leaves(params, jnp.bfloat16), x)
            return jnp.asarray(out, jnp.float32)

        return entry, bodies, tail
    if precision == "int8":
        if int8_mode() != "fake":
            # real/fakeint rewrite the whole jaxpr — not stage-splittable
            return None, None, None
        import jax.numpy as jnp

        def entry(x):
            return _fake_quant_int8(x, per_row=True)

        bodies = tuple(
            (lambda params, x, _b=body: _b(_quant_float_leaves(params), x))
            for body in stage_bodies
        )

        def tail(params, x):
            out = stage_tail(_quant_float_leaves(params), x)
            return jnp.asarray(out, jnp.float32)

        return entry, bodies, tail
    raise ValueError(f"unknown precision {precision!r}")
