"""Bump-function patch weighting for seamless overlap blending.

Parity target: reference flow/divid_conquer/patch/patch_mask.py — the "wu"
bump ``exp(-1/(1-z^2) - 1/(1-y^2) - 1/(1-x^2))`` evaluated on the open
(-1, 1)^3 grid, conditioned into float32 range, with the sum-to-one
normalization invariant for overlapped tiling.

Computed once per patch size on host in float64 (the raw bump spans ~1e-190
at 256-wide patches, far below float32), affinely rescaled to [1, 1e6], and
cast to float32 for device use. The fused inference program divides the
blended output by the accumulated weight mask, so any monotone conditioning
of the bump preserves exactness.
"""
from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

from chunkflow_tpu.core.contracts import Spec, contract


@functools.lru_cache(maxsize=None)
@contract(_result=Spec("z", "y", "x", dtype="float32"))
def bump_map(patch_size: Tuple[int, int, int]) -> np.ndarray:
    """Raw bump weights, float32, conditioned to [1, 1e6]."""
    # float64 on purpose: the raw bump underflows float32 long before the
    # conditioning rescale (module docstring)
    coords = [np.linspace(-1.0, 1.0, s + 2)[1:-1]  # graftlint: disable=GL004
              for s in patch_size]
    zz, yy, xx = np.meshgrid(*coords, indexing="ij")
    with np.errstate(under="ignore"):
        bump = np.exp(
            -1.0 / (1.0 - zz ** 2)
            - 1.0 / (1.0 - yy ** 2)
            - 1.0 / (1.0 - xx ** 2)
        )
    # affine conditioning into float32-friendly range; relative ordering of
    # weights is preserved, which is all reciprocal normalization needs
    lo, hi = bump.min(), bump.max()
    bump = (bump - lo) / (hi - lo) * (1e6 - 1.0) + 1.0
    return bump.astype(np.float32)


def bump_const(patch_size: Tuple[int, int, int]):
    """The bump map as a jax constant — the ONE device-side form every
    blend-program builder closes over (ops/blend.py, serve/packer.py,
    parallel/engine.py). In the fused Pallas kernel
    (ops/pallas_blend.py) this array becomes the constant-index VMEM
    block that rides on-chip memory once for the whole accumulation
    grid instead of being re-materialized per patch; on the XLA leg it
    is the broadcast operand of the bump-weight multiply. Same values
    either way — the weighting is bitwise identical across kernels."""
    import jax.numpy as jnp

    return jnp.asarray(bump_map(tuple(patch_size)))


@functools.lru_cache(maxsize=None)
@contract(_result=Spec("z", "y", "x", dtype="float32"))
def normalized_patch_mask(
    patch_size: Tuple[int, int, int], overlap: Tuple[int, int, int]
) -> np.ndarray:
    """Bump mask pre-normalized so overlapped tiling sums to exactly 1.

    Simulates a 3x3x3 neighborhood of patches at stride ``size - overlap``
    accumulating bump weights, then divides the center patch's bump by the
    accumulated sum. Interior voxels of an infinite tiling then satisfy
    ``sum of overlapping masks == 1`` (the reference's make_patch_mask
    invariant, patch_mask.py:43-46).
    """
    patch_size = tuple(patch_size)
    overlap = tuple(overlap)
    stride = tuple(p - o for p, o in zip(patch_size, overlap))
    # float64 on purpose: 27 overlapping adds of ~1e6-range weights need
    # the headroom before the final normalize
    bump = bump_map(patch_size).astype(np.float64)  # graftlint: disable=GL004
    # accumulate 27 shifted copies around the center patch
    buf_shape = tuple(p + 2 * s for p, s in zip(patch_size, stride))
    buf = np.zeros(buf_shape, dtype=np.float64)  # graftlint: disable=GL004
    for dz in range(3):
        for dy in range(3):
            for dx in range(3):
                start = (dz * stride[0], dy * stride[1], dx * stride[2])
                sl = tuple(
                    slice(st, st + p) for st, p in zip(start, patch_size)
                )
                buf[sl] += bump
    center = tuple(slice(s, s + p) for s, p in zip(stride, patch_size))
    mask = bump / buf[center]
    return mask.astype(np.float32)
