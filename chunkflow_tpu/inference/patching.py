"""Patch-grid enumeration: chunk -> static arrays of patch start coords.

Parity target: reference inferencer.py geometry (:109-122, :255-292) —
crop margin (input - output)//2, stride = output size - output overlap,
edge snapping so the last patch ends exactly at the chunk boundary. The
output is a static [N, 3] coordinate array that the fused XLA program scans
over, instead of the reference's Python list of slice pairs.

This starts table IS the device-resident front half's index structure
(ISSUE 15): every gather leg — the per-chunk program, the serving
packer's cross-request batch assembler, and each chip of a sharded
mesh — walks the resident chunk by these coordinates
(ops/pallas_gather.py), so no per-patch host slicing exists anywhere.
"""
from __future__ import annotations

from typing import List, NamedTuple, Tuple

import numpy as np

from chunkflow_tpu.core.cartesian import Cartesian
from chunkflow_tpu.core.contracts import Spec, contract


class PatchGrid(NamedTuple):
    """Static patch geometry for one (chunk shape, patch config) pair."""

    input_starts: np.ndarray   # [N, 3] int32, zyx corner of each input patch
    output_starts: np.ndarray  # [N, 3] int32, zyx corner of each output patch
    crop_margin: Cartesian     # (input - output) // 2 per axis
    input_patch_size: Cartesian
    output_patch_size: Cartesian

    @property
    def num_patches(self) -> int:
        return self.input_starts.shape[0]


def starts_1d(extent: int, patch: int, stride: int) -> List[int]:
    """Start offsets covering [0, extent) with the last patch snapped flush."""
    if patch > extent:
        raise ValueError(f"patch ({patch}) larger than chunk extent ({extent})")
    starts = list(range(0, extent - patch + 1, max(stride, 1)))
    if starts[-1] != extent - patch:
        starts.append(extent - patch)
    return starts


def enumerate_patches(
    chunk_size,
    input_patch_size,
    output_patch_size=None,
    output_patch_overlap=(0, 0, 0),
) -> PatchGrid:
    chunk_size = Cartesian.from_collection(tuple(chunk_size)[-3:])
    input_patch_size = Cartesian.from_collection(input_patch_size)
    if output_patch_size is None:
        output_patch_size = input_patch_size
    output_patch_size = Cartesian.from_collection(output_patch_size)
    overlap = Cartesian.from_collection(output_patch_overlap)

    margin = (input_patch_size - output_patch_size) // 2
    if (margin * 2) != (input_patch_size - output_patch_size):
        raise ValueError(
            f"input-output patch size difference must be even, got "
            f"{input_patch_size} vs {output_patch_size}"
        )
    stride = output_patch_size - overlap
    if not stride.all_positive():
        raise ValueError(
            f"output overlap {overlap} must be smaller than output patch "
            f"size {output_patch_size}"
        )

    axes = [
        starts_1d(chunk_size[i], input_patch_size[i], stride[i])
        for i in range(3)
    ]
    grid = np.stack(
        np.meshgrid(*[np.asarray(a, dtype=np.int32) for a in axes], indexing="ij"),
        axis=-1,
    ).reshape(-1, 3)
    return PatchGrid(
        input_starts=grid,
        output_starts=grid + np.asarray(margin, dtype=np.int32),
        crop_margin=margin,
        input_patch_size=input_patch_size,
        output_patch_size=output_patch_size,
    )


@contract(
    _result=(
        Spec("n", 3, dtype="int32"),
        Spec("n", 3, dtype="int32"),
        Spec("n", dtype="float32"),
    ),
)
def pad_to_batch(grid: PatchGrid, batch_size: int):
    """Pad the patch list to a batch multiple; returns (in, out, valid).

    Padded entries repeat the first patch with validity 0, so the fused
    program masks their contribution instead of branching on a dynamic
    patch count (static shapes keep XLA happy).
    """
    n = grid.num_patches
    padded = -n % batch_size
    valid = np.ones(n + padded, dtype=np.float32)
    if padded:
        pad_in = np.repeat(grid.input_starts[:1], padded, axis=0)
        pad_out = np.repeat(grid.output_starts[:1], padded, axis=0)
        in_starts = np.concatenate([grid.input_starts, pad_in], axis=0)
        out_starts = np.concatenate([grid.output_starts, pad_out], axis=0)
        valid[n:] = 0.0
    else:
        in_starts, out_starts = grid.input_starts, grid.output_starts
    return in_starts, out_starts, valid
