"""Patch inference engines: pure-jax batch-forward callables.

An engine is (params, apply) where ``apply(params, batch)`` maps a
``[B, Cin, *in_patch]`` float32 batch to ``[B, Cout, *out_patch]``; it must
be jax-traceable so the fused inference program can inline it. Engine
registry parity: reference _prepare_patch_inferencer (inferencer.py:206-241)
with frameworks identity/pytorch/universal; here the native framework is
``flax`` (pytorch checkpoints load through the weight converter in
chunkflow_tpu.models.converter), ``identity`` is the test oracle, and
``universal`` loads a user python file (reference patch/universal.py — the
engine contract explicitly designed for device-side masking, incl. TPU).
"""
from __future__ import annotations

import importlib.util
import os
from typing import Callable, NamedTuple, Optional, Tuple

import jax.numpy as jnp


class Engine(NamedTuple):
    params: object
    apply: Callable  # (params, [B, Cin, *pin]) -> [B, Cout, *pout]
    num_input_channels: int
    num_output_channels: int
    # The stage protocol (parallel/pipeline.py, ISSUE 19): engines that
    # can be staged across a ``pipeline=N`` mesh declare their layer
    # stack as uniform-activation bodies plus a tail, with ``apply``
    # being their LITERAL composition (bitwise — the pipelined and
    # non-pipelined programs then run the same per-row expression).
    # ``None`` (the default) means the forward is opaque and a pipeline
    # mesh fails loudly instead of silently de-pipelining.
    stage_bodies: Optional[Tuple[Callable, ...]] = None
    stage_tail: Optional[Callable] = None


def create_identity_engine(
    input_patch_size,
    output_patch_size,
    num_output_channels: int = 1,
    num_input_channels: int = 1,
) -> Engine:
    """Crop-and-repeat oracle: output is the input's central crop, repeated
    across output channels. Identity through the whole blend path must
    reproduce the input exactly — the linchpin of inference testing
    (reference patch/identity.py)."""
    pin = tuple(input_patch_size)
    pout = tuple(output_patch_size)
    margin = tuple((i - o) // 2 for i, o in zip(pin, pout))

    # stage protocol (parallel/pipeline.py): one identity body (the
    # uniform-activation [B, ci, *pin] -> same shape/dtype rule) and the
    # crop/broadcast tail; ``apply`` is their literal composition, so
    # the pipelined program runs bitwise the same expression.
    def stage_body(params, x):
        return x

    def stage_tail(params, batch):
        sl = (slice(None), slice(0, 1)) + tuple(
            slice(m, m + o) for m, o in zip(margin, pout)
        )
        center = batch[sl]
        return jnp.broadcast_to(
            center,
            (batch.shape[0], num_output_channels) + pout,
        )

    def apply(params, batch):
        return stage_tail(params, stage_body(params, batch))

    return Engine(
        params=(),
        apply=apply,
        num_input_channels=num_input_channels,
        num_output_channels=num_output_channels,
        stage_bodies=(stage_body,),
        stage_tail=stage_tail,
    )


def create_flax_engine(
    model_path: str,
    weight_path: Optional[str],
    input_patch_size,
    num_input_channels: int = 1,
    num_output_channels: int = 3,
    dtype: str = "float32",
    model_variant: str = "parity",
) -> Engine:
    """The native convnet engine: a Flax 3D UNet (or user model file).

    ``model_path`` may be empty (use the built-in model), a python file
    exposing ``create_model(num_input_channels, num_output_channels)`` that
    returns a Flax module, or a reference-chunkflow pytorch ``model.py``
    (``InstantiatedModel`` / ``load_model`` contract, patch/pytorch.py:48-83)
    whose weights are converted by name into the Flax mirror selected by
    ``model_variant``. ``weight_path`` may be a ``.pt`` torch state dict
    (converted) or an orbax/msgpack flax checkpoint. ``model_variant``:
    'parity' is the reference-class UNet; 'rsunet' the production RSUNet
    mirror (models/rsunet.py); 'tpu' the space-to-depth flagship
    (unet3d.create_tpu_optimized_model); 'tpu_mxu' the same flagship with
    every conv lowered as z-decomposed 2D convs / GEMM upsampling
    (identical parameters, different XLA lowering).
    """
    from chunkflow_tpu.models import rsunet, unet3d

    compute_dtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    module = None
    if model_path:
        module = _load_user_module(model_path, "chunkflow_user_model")

    if module is not None and hasattr(module, "create_model"):
        model = module.create_model(num_input_channels, num_output_channels)
    elif model_variant in ("tpu", "tpu_mxu", "tpu_s2d4"):
        model = unet3d.create_tpu_optimized_model(
            in_channels=num_input_channels,
            out_channels=num_output_channels,
            dtype=compute_dtype,
            # same parameters, different XLA lowering (z-decomposed 2D
            # convs + GEMM upsampling) — see unet3d.MxuConv
            conv_impl="mxu" if model_variant == "tpu_mxu" else "native",
            # aggressive stem: 112-256 channels at 1/16 positions
            s2d_factor=(1, 4, 4) if model_variant == "tpu_s2d4"
            else (1, 2, 2),
        )
    elif model_variant == "rsunet":
        model = rsunet.RSUNet(
            in_channels=num_input_channels,
            out_channels=num_output_channels,
            dtype=compute_dtype,
        )
    else:
        model = unet3d.UNet3D(
            in_channels=num_input_channels,
            out_channels=num_output_channels,
            dtype=compute_dtype,
        )

    if module is not None and not hasattr(module, "create_model"):
        # reference pytorch engine contract: migrate the torch weights
        from chunkflow_tpu.models.migrate import (
            flax_params_from_reference_model,
        )

        params = flax_params_from_reference_model(
            model_path, weight_path, model, input_patch_size,
            num_input_channels, module=module,
        )
    else:
        params = unet3d.init_or_load_params(
            model, weight_path, input_patch_size, num_input_channels
        )

    def apply(params, batch):
        # batch: [B, C, z, y, x] float32 -> channels-last for TPU conv
        x = jnp.moveaxis(batch, 1, -1)
        y = model.apply({"params": params}, x)
        out = jnp.moveaxis(y, -1, 1)
        return out.astype(jnp.float32)

    return Engine(
        params=params,
        apply=apply,
        num_input_channels=num_input_channels,
        num_output_channels=num_output_channels,
    )


def create_universal_engine(
    model_path: str,
    weight_path: Optional[str],
    input_patch_size,
    output_patch_size,
    num_input_channels: int = 1,
    num_output_channels: int = 3,
) -> Engine:
    """User-supplied engine file exposing
    ``create_engine(weight_path, input_patch_size, output_patch_size,
    num_input_channels, num_output_channels) -> (params, apply)``."""
    module = _load_user_module(model_path, "chunkflow_universal_engine")
    params, apply = module.create_engine(
        weight_path,
        tuple(input_patch_size),
        tuple(output_patch_size),
        num_input_channels,
        num_output_channels,
    )
    return Engine(
        params=params,
        apply=apply,
        num_input_channels=num_input_channels,
        num_output_channels=num_output_channels,
    )


def _load_user_module(path: str, name: str):
    if not os.path.exists(path):
        raise FileNotFoundError(f"model file not found: {path}")
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def create_engine(framework: str, **kwargs) -> Engine:
    if framework == "prebuilt":
        # pass an Engine object directly via the dedicated kwarg (reference
        # inferencer.py:209-211); programmatic use only — not on the CLI
        engine = kwargs.get("engine")
        if not isinstance(engine, Engine):
            raise TypeError(
                "framework='prebuilt' needs an Engine instance as engine="
            )
        return engine
    if framework == "identity":
        return create_identity_engine(
            kwargs["input_patch_size"],
            kwargs["output_patch_size"],
            num_output_channels=kwargs.get("num_output_channels", 1),
            num_input_channels=kwargs.get("num_input_channels", 1),
        )
    if framework in ("flax", "jax", "pytorch"):
        # pytorch checkpoints route through the same flax engine via the
        # state-dict converter; framework name kept for CLI parity
        return create_flax_engine(
            kwargs.get("model_path", ""),
            kwargs.get("weight_path"),
            kwargs["input_patch_size"],
            num_input_channels=kwargs.get("num_input_channels", 1),
            num_output_channels=kwargs.get("num_output_channels", 3),
            dtype=kwargs.get("dtype", "float32"),
            model_variant=kwargs.get("model_variant", "parity"),
        )
    if framework == "universal":
        return create_universal_engine(
            kwargs["model_path"],
            kwargs.get("weight_path"),
            kwargs["input_patch_size"],
            kwargs["output_patch_size"],
            num_input_channels=kwargs.get("num_input_channels", 1),
            num_output_channels=kwargs.get("num_output_channels", 3),
        )
    raise ValueError(f"unknown inference framework: {framework!r}")
