"""Point cloud: N zyx points + voxel size (reference point_cloud.py:8-47)."""
from __future__ import annotations

import numpy as np

from chunkflow_tpu.core.bbox import BoundingBox
from chunkflow_tpu.core.cartesian import Cartesian, to_cartesian


class PointCloud:
    def __init__(self, points: np.ndarray, voxel_size=(1, 1, 1)):
        points = np.asarray(points)
        if points.ndim != 2 or points.shape[1] != 3:
            raise ValueError(f"points must be [N, 3] zyx, got {points.shape}")
        self.points = points
        self.voxel_size = to_cartesian(voxel_size)

    def __len__(self) -> int:
        return self.points.shape[0]

    @property
    def bbox(self) -> BoundingBox:
        return BoundingBox.from_points(self.points)

    # reference spellings (point_cloud.py:8-47)
    @property
    def bounding_box(self) -> BoundingBox:
        return self.bbox

    @property
    def point_num(self) -> int:
        return self.points.shape[0]

    @classmethod
    def from_swc(cls, path: str, voxel_size=(1, 1, 1)) -> "PointCloud":
        from chunkflow_tpu.annotations.skeleton import Skeleton

        skel = Skeleton.from_swc(path)
        # Skeleton nodes are physical nm; PointCloud points are voxel
        # coordinates (physical = points * voxel_size)
        vs = np.asarray(to_cartesian(voxel_size).vec, dtype=np.float64)
        return cls(skel.nodes / vs, voxel_size=voxel_size)

    @property
    def physical(self) -> np.ndarray:
        return self.points * self.voxel_size.vec

    def filter_by_bbox(self, bbox: BoundingBox) -> "PointCloud":
        keep = np.all(
            (self.points >= np.asarray(bbox.start))
            & (self.points < np.asarray(bbox.stop)),
            axis=1,
        )
        return PointCloud(self.points[keep], self.voxel_size)

    # ---- I/O -----------------------------------------------------------
    def to_h5(self, path: str) -> str:
        import h5py

        with h5py.File(path, "w") as f:
            f.create_dataset("points", data=self.points)
            f.create_dataset("voxel_size", data=self.voxel_size.vec)
        return path

    @classmethod
    def from_h5(cls, path: str) -> "PointCloud":
        import h5py

        with h5py.File(path, "r") as f:
            points = f["points"][()]
            voxel_size = (
                Cartesian(*f["voxel_size"][()].tolist())
                if "voxel_size" in f
                else (1, 1, 1)
            )
        return cls(points, voxel_size)

    def to_npy(self, path: str) -> str:
        np.save(path, self.points)
        return path
