"""Synapse annotations: T-bars (pre) and their post-synaptic partners.

Parity target: reference synapses.py (:19-794) — pre is an [N, 3] int32
zyx array, post is [M, 4] int32 (pre_index, z, y, x), with optional
confidences and user attributions; JSON/HDF5 round trips; KDTree distance
queries (pre->post distances, redundant-post detection, per-neuron
duplicate detection against a segmentation); bbox cropping with pre-index
remapping.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

import numpy as np

from chunkflow_tpu.core.bbox import BoundingBox
from chunkflow_tpu.core.cartesian import Cartesian, to_cartesian


class Synapses:
    def __init__(
        self,
        pre: np.ndarray,
        post: Optional[np.ndarray] = None,
        pre_confidence: Optional[np.ndarray] = None,
        post_confidence: Optional[np.ndarray] = None,
        resolution=(1, 1, 1),
        users: Optional[List[str]] = None,
    ):
        pre = np.asarray(pre, dtype=np.int32)
        if pre.size == 0:
            pre = pre.reshape(0, 3)  # json round-trips [] as shape (0,)
        if pre.ndim != 2 or pre.shape[1] != 3:
            raise ValueError(f"pre must be [N, 3] zyx, got {pre.shape}")
        if post is not None:
            post = np.asarray(post, dtype=np.int32)
            if post.size == 0:
                post = post.reshape(0, 4)
            if post.ndim != 2 or post.shape[1] != 4:
                raise ValueError(f"post must be [M, 4] (pre_idx, z, y, x)")
            if post.size and (
                post[:, 0].min() < 0 or post[:, 0].max() >= pre.shape[0]
            ):
                raise ValueError("post pre_index out of range")
        if pre_confidence is not None:
            pre_confidence = np.asarray(pre_confidence, dtype=np.float32)
            assert pre_confidence.shape[0] == pre.shape[0]
        self.pre = pre
        self.post = post
        self.pre_confidence = pre_confidence
        self.post_confidence = (
            np.asarray(post_confidence, dtype=np.float32)
            if post_confidence is not None
            else None
        )
        self.resolution = to_cartesian(resolution)
        self.users = users

    # ---- basic properties ---------------------------------------------
    @property
    def pre_num(self) -> int:
        return self.pre.shape[0]

    @property
    def post_num(self) -> int:
        return 0 if self.post is None else self.post.shape[0]

    def __len__(self) -> int:
        return self.pre_num

    def __eq__(self, other) -> bool:
        if not isinstance(other, Synapses):
            return NotImplemented
        same_pre = np.array_equal(self.pre, other.pre)
        same_post = (
            (self.post is None) == (other.post is None)
        ) and (self.post is None or np.array_equal(self.post, other.post))
        return same_pre and same_post

    @property
    def pre_bbox(self) -> BoundingBox:
        start = Cartesian(*self.pre.min(axis=0).tolist())
        stop = Cartesian(*(self.pre.max(axis=0) + 1).tolist())
        return BoundingBox(start, stop)

    @property
    def post_positions(self) -> np.ndarray:
        return self.post[:, 1:] if self.post is not None else np.zeros((0, 3))

    def post_indices_of_pre(self, pre_index: int) -> np.ndarray:
        if self.post is None:
            return np.zeros((0,), dtype=np.int64)
        return np.nonzero(self.post[:, 0] == pre_index)[0]

    @property
    def pre_with_post_num(self) -> int:
        if self.post is None:
            return 0
        return np.unique(self.post[:, 0]).size

    # ---- queries (KDTree) ---------------------------------------------
    def distances_from_pre_to_post(self) -> np.ndarray:
        """Physical distance of each post partner to its own T-bar."""
        if self.post is None or self.post_num == 0:
            return np.zeros((0,), dtype=np.float32)
        res = self.resolution.vec
        pre_pos = self.pre[self.post[:, 0]] * res
        post_pos = self.post[:, 1:] * res
        return np.linalg.norm(post_pos - pre_pos, axis=1)

    def find_redundant_post(self, distance_threshold: float) -> np.ndarray:
        """Indices of posts closer than the PHYSICAL threshold to an
        earlier post of the same T-bar (near-duplicate annotations). For
        the reference method of that (similar) name, use
        ``find_redundent_post`` — different signature and semantics."""
        from scipy.spatial import KDTree

        if self.post is None or self.post_num == 0:
            return np.zeros((0,), dtype=np.int64)
        redundant = []
        res = self.resolution.vec
        for pre_index in np.unique(self.post[:, 0]):
            indices = np.nonzero(self.post[:, 0] == pre_index)[0]
            if indices.size < 2:
                continue
            positions = self.post[indices, 1:] * res
            tree = KDTree(positions)
            pairs = tree.query_pairs(distance_threshold)
            for a, b in pairs:
                redundant.append(indices[max(a, b)])
        return np.unique(np.asarray(redundant, dtype=np.int64))

    def find_duplicate_post_on_same_neuron(self, seg) -> np.ndarray:
        """Posts of one T-bar landing on the same segment id (reference
        per-neuron duplicate detection against a Segmentation)."""
        if self.post is None or self.post_num == 0:
            return np.zeros((0,), dtype=np.int64)
        arr = np.asarray(seg.array)
        if arr.ndim == 4:
            arr = arr[0]  # czyx single-channel segmentation
        offset = seg.voxel_offset.vec
        duplicates = []
        for pre_index in np.unique(self.post[:, 0]):
            indices = np.nonzero(self.post[:, 0] == pre_index)[0]
            if indices.size < 2:
                continue
            coords = self.post[indices, 1:] - offset
            valid = np.all(
                (coords >= 0) & (coords < np.asarray(arr.shape)), axis=1
            )
            seen: Dict[int, int] = {}
            for local_i, ok in zip(indices[valid], coords[valid]):
                seg_id = int(arr[tuple(ok)])
                if seg_id == 0:
                    continue
                if seg_id in seen:
                    duplicates.append(local_i)
                else:
                    seen[seg_id] = local_i
        return np.asarray(sorted(set(duplicates)), dtype=np.int64)

    # ---- editing -------------------------------------------------------
    def filter_by_bbox(self, bbox: BoundingBox) -> "Synapses":
        """Keep T-bars inside bbox (and their posts), remapping pre indices."""
        keep = np.all(
            (self.pre >= np.asarray(bbox.start))
            & (self.pre < np.asarray(bbox.stop)),
            axis=1,
        )
        new_index = np.full(self.pre_num, -1, dtype=np.int64)
        new_index[keep] = np.arange(int(keep.sum()))
        post = None
        post_conf = None
        if self.post is not None:
            post_keep = keep[self.post[:, 0]]
            post = self.post[post_keep].copy()
            post[:, 0] = new_index[post[:, 0]]
            if self.post_confidence is not None:
                post_conf = self.post_confidence[post_keep]
        return Synapses(
            self.pre[keep],
            post=post,
            pre_confidence=(
                self.pre_confidence[keep]
                if self.pre_confidence is not None
                else None
            ),
            post_confidence=post_conf,
            resolution=self.resolution,
        )

    def remove_pre_without_post(self) -> "Synapses":
        if self.post is None:
            return self
        has_post = np.zeros(self.pre_num, dtype=bool)
        has_post[np.unique(self.post[:, 0])] = True
        new_index = np.full(self.pre_num, -1, dtype=np.int64)
        new_index[has_post] = np.arange(int(has_post.sum()))
        post = self.post.copy()
        post[:, 0] = new_index[post[:, 0]]
        return Synapses(
            self.pre[has_post],
            post=post,
            pre_confidence=(
                self.pre_confidence[has_post]
                if self.pre_confidence is not None
                else None
            ),
            post_confidence=self.post_confidence,
            resolution=self.resolution,
        )

    # ---- reference-spelling compatibility surface ----------------------
    # drop-in names from reference synapses.py:461-700 for user code that
    # migrates verbatim; the mutating editors delegate to vectorized cores
    @property
    def pre_bounding_box(self) -> BoundingBox:
        return self.pre_bbox

    def post_bounding_box(self) -> BoundingBox:
        # plain method, matching the reference's calling convention (:536)
        pos = self.post_positions
        if pos.shape[0] == 0:
            return self.pre_bbox
        return BoundingBox.from_points(pos)

    @property
    def bounding_box(self) -> BoundingBox:
        return self.pre_bounding_box.union(self.post_bounding_box())

    @property
    def post_coordinates(self) -> np.ndarray:
        return self.post_positions

    @property
    def pre_with_physical_coordinate(self) -> np.ndarray:
        return self.pre * self.resolution.vec

    @property
    def post_with_physical_coordinate(self) -> Optional[np.ndarray]:
        if self.post is None:
            return None
        # multiply in the post dtype (reference behavior) so column 0
        # stays an integer pre-index usable for fancy indexing
        post = self.post.copy()
        post[:, 1:] = post[:, 1:] * np.asarray(
            self.resolution.vec, dtype=post.dtype
        )
        return post

    @property
    def pre_point_cloud(self):
        from chunkflow_tpu.annotations.point_cloud import PointCloud

        return PointCloud(self.pre, voxel_size=self.resolution)

    @property
    def post_point_cloud(self):
        from chunkflow_tpu.annotations.point_cloud import PointCloud

        return PointCloud(self.post_positions, voxel_size=self.resolution)

    @property
    def pre_index2post_indices(self) -> List[List[int]]:
        if self.post is None:
            return [[] for _ in range(self.pre_num)]
        buckets: List[List[int]] = [[] for _ in range(self.pre_num)]
        for post_idx, pre_idx in enumerate(self.post[:, 0].tolist()):
            buckets[pre_idx].append(post_idx)
        return buckets

    @property
    def post_synapse_num_list(self) -> List[int]:
        if self.post is None:
            return [0] * self.pre_num
        counts = np.bincount(self.post[:, 0], minlength=self.pre_num)
        return counts.tolist()

    @property
    def pre_indices_without_post(self) -> List[int]:
        if self.post is None:
            return list(range(self.pre_num))
        has_post = np.zeros(self.pre_num, dtype=bool)
        has_post[np.unique(self.post[:, 0])] = True
        return np.nonzero(~has_post)[0].tolist()

    def add_pre(self, pre: np.ndarray, confidence: float = 1.0) -> "Synapses":
        pre = np.asarray(pre, dtype=np.int32).reshape(-1, 3)
        self.pre = np.vstack([self.pre, pre])
        if self.pre_confidence is not None:
            self.pre_confidence = np.concatenate([
                self.pre_confidence,
                np.full(pre.shape[0], confidence, dtype=np.float32),
            ])
        return self

    def remove_pre(self, indices) -> None:
        """Delete T-bars in place, dropping their posts and remapping the
        surviving posts' pre indices (reference synapses.py:633-658)."""
        indices = np.asarray(list(indices), dtype=np.int64)
        keep = np.ones(self.pre_num, dtype=bool)
        keep[indices] = False
        new_index = np.full(self.pre_num, -1, dtype=np.int64)
        new_index[keep] = np.arange(int(keep.sum()))
        self.pre = self.pre[keep]
        if self.pre_confidence is not None:
            self.pre_confidence = self.pre_confidence[keep]
        if self.post is not None:
            post_keep = keep[self.post[:, 0]]
            self.post = self.post[post_keep].copy()
            self.post[:, 0] = new_index[self.post[:, 0]]
            if self.post_confidence is not None:
                self.post_confidence = self.post_confidence[post_keep]

    def remove_pre_duplicates(self) -> None:
        """Drop T-bars at identical coordinates (keep first occurrence);
        posts of a dropped duplicate re-attach to the surviving T-bar."""
        _, first, inverse = np.unique(
            self.pre, axis=0, return_index=True, return_inverse=True
        )
        keep_set = set(first.tolist())
        dupes = [i for i in range(self.pre_num) if i not in keep_set]
        if not dupes:
            return
        if self.post is not None:
            # route each post to the first occurrence of its T-bar coords
            canonical = first[inverse.reshape(-1)]
            self.post = self.post.copy()
            self.post[:, 0] = canonical[self.post[:, 0]]
        self.remove_pre(dupes)

    def remove_synapses_without_post(self) -> None:
        if self.post is None:
            # match remove_pre_without_post: pre-only sets are a no-op,
            # not a wipe
            return
        self.remove_pre(self.pre_indices_without_post)

    def remove_synapses_outside_bounding_box(self, bbox: BoundingBox) -> None:
        outside = ~np.all(
            (self.pre >= np.asarray(bbox.start))
            & (self.pre < np.asarray(bbox.stop)),
            axis=1,
        )
        self.remove_pre(np.nonzero(outside)[0])

    def transpose_axis(self) -> None:
        """Flip zyx <-> xyz in place."""
        self.pre = np.ascontiguousarray(self.pre[:, ::-1])
        self.resolution = Cartesian(*reversed(tuple(self.resolution)))
        if self.post is not None:
            self.post = self.post.copy()
            self.post[:, 1:] = self.post[:, 1:][:, ::-1]

    def user_id(self, user: str) -> Optional[int]:
        if self.users is None:
            return None
        for idx, item in enumerate(self.users):
            if user == item:
                return idx
        return None

    def find_redundent_post(self, num_threshold: int = 15,
                            distance_threshold: float = 50.0) -> set:
        """Reference signature and semantics (synapses.py:686-736): posts
        farther than distance_threshold VOXELS from their T-bar, plus the
        worst posts beyond num_threshold per T-bar (by distance, or
        distance/confidence when confidences exist). Returns a set of post
        indices to remove. (find_redundant_post is this framework's
        physical-distance near-duplicate finder — different question.)"""
        if self.post is None or self.post_num == 0:
            return set()
        dv = np.linalg.norm(
            (self.post[:, 1:] - self.pre[self.post[:, 0]]).astype(np.float64),
            axis=1,
        )
        to_remove = set(np.nonzero(dv > distance_threshold)[0].tolist())
        for post_indices in self.pre_index2post_indices:
            if len(post_indices) > num_threshold:
                idx = np.asarray(post_indices, dtype=np.int64)
                costs = dv[idx]
                if self.post_confidence is not None:
                    costs = costs / self.post_confidence[idx]
                order = np.argsort(costs)
                to_remove |= set(idx[order[num_threshold:]].tolist())
        return to_remove

    @property
    def json_dict(self) -> dict:
        data = {
            "resolution": list(self.resolution),
            "pre": self.pre.tolist(),
        }
        if self.post is not None:
            data["post"] = self.post.tolist()
        if self.pre_confidence is not None:
            data["pre_confidence"] = self.pre_confidence.tolist()
        if self.post_confidence is not None:
            data["post_confidence"] = self.post_confidence.tolist()
        if self.users is not None:
            data["users"] = self.users
        return data

    @classmethod
    def from_dict(cls, data: dict) -> "Synapses":
        return cls(
            np.asarray(data["pre"], dtype=np.int32),
            post=(
                np.asarray(data["post"], dtype=np.int32)
                if data.get("post") is not None
                else None
            ),
            pre_confidence=data.get("pre_confidence"),
            post_confidence=data.get("post_confidence"),
            resolution=tuple(data.get("resolution", (1, 1, 1))),
            users=data.get("users"),
        )

    # ---- I/O -----------------------------------------------------------
    def to_json(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.json_dict, f)
        return path

    @classmethod
    def from_json(cls, path: str) -> "Synapses":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def to_h5(self, path: str) -> str:
        import h5py

        with h5py.File(path, "w") as f:
            f.create_dataset("pre", data=self.pre)
            if self.post is not None:
                f.create_dataset("post", data=self.post)
            if self.pre_confidence is not None:
                f.create_dataset("pre_confidence", data=self.pre_confidence)
            if self.post_confidence is not None:
                f.create_dataset("post_confidence", data=self.post_confidence)
            f.create_dataset("resolution", data=self.resolution.vec)
        return path

    @classmethod
    def from_h5(cls, path: str) -> "Synapses":
        import h5py

        with h5py.File(path, "r") as f:
            return cls(
                f["pre"][()],
                post=f["post"][()] if "post" in f else None,
                pre_confidence=(
                    f["pre_confidence"][()] if "pre_confidence" in f else None
                ),
                post_confidence=(
                    f["post_confidence"][()] if "post_confidence" in f else None
                ),
                resolution=(
                    tuple(f["resolution"][()].tolist())
                    if "resolution" in f
                    else (1, 1, 1)
                ),
            )

    @classmethod
    def from_file(cls, path: str) -> "Synapses":
        if path.endswith(".json"):
            return cls.from_json(path)
        if path.endswith((".h5", ".hdf5")):
            return cls.from_h5(path)
        raise ValueError(f"unsupported synapse file format: {path}")

    def to_file(self, path: str) -> str:
        if path.endswith(".json"):
            return self.to_json(path)
        if path.endswith((".h5", ".hdf5")):
            return self.to_h5(path)
        raise ValueError(f"unsupported synapse file format: {path}")

    # ---- DVID / NeuTu interop (reference synapses.py:128-224,364-455) ----
    @classmethod
    def from_dvid_list(cls, syns: List[dict],
                       resolution=(1, 1, 1)) -> "Synapses":
        """Build from a DVID annotation-element list (as fetched with
        fivol/DVID's elements API): dicts with 'Kind' ('PreSyn'/'PostSyn'),
        'Pos' [x, y, z], 'Prop' {'conf', 'user', ...}, and 'Rels'
        [{'Rel': 'PostSynTo', 'To': [x, y, z]}].

        Post elements whose presynapse is absent from the list are dropped
        (the reference logs and skips them the same way)."""
        pre_list, pre_conf, users = [], [], []
        for syn in syns:
            if "Pre" in syn.get("Kind", ""):
                pre_list.append(tuple(syn["Pos"][::-1]))  # xyz -> zyx
                prop = syn.get("Prop", {}) or {}
                pre_conf.append(float(prop.get("conf", 1.0)))
                users.append(prop.get("user", ""))
        pre_pos2idx = {pos: i for i, pos in enumerate(pre_list)}

        post_rows = []
        for syn in syns:
            if "Post" in syn.get("Kind", ""):
                rels = syn.get("Rels") or []
                if not rels:
                    continue  # post without a presynapse
                pre_pos = tuple(rels[0]["To"][::-1])
                pre_idx = pre_pos2idx.get(pre_pos)
                if pre_idx is None:
                    continue  # presynapse was deleted
                z, y, x = syn["Pos"][::-1]
                post_rows.append((pre_idx, z, y, x))

        pre = np.asarray(pre_list, dtype=np.int32).reshape(-1, 3)
        post = (
            np.asarray(post_rows, dtype=np.int32)
            if post_rows else None
        )
        return cls(
            pre,
            post=post,
            pre_confidence=np.asarray(pre_conf, dtype=np.float32),
            resolution=resolution,
            users=sorted(set(users)) if users else None,
        )

    def to_dvid_list_of_dict(self, user: str = "chunkflow",
                             comment: str = "ingested using chunkflow",
                             ) -> List[dict]:
        """Element list for DVID bulk ingestion: one PostSyn dict per post
        partner (with a PostSynTo relation) and one PreSyn dict per T-bar
        (with PreSynTo relations to all its partners)."""
        def xyz(zyx_row):
            return [int(v) for v in zyx_row[::-1]]

        data = []
        for post_idx in range(self.post_num):
            pre_idx = int(self.post[post_idx, 0])
            conf = (
                float(self.post_confidence[post_idx])
                if self.post_confidence is not None else 1.0
            )
            data.append({
                "Kind": "PostSyn",
                "Pos": xyz(self.post[post_idx, 1:]),
                "Prop": {"annotation": comment, "conf": str(conf),
                         "user": user},
                "Rels": [{"Rel": "PostSynTo", "To": xyz(self.pre[pre_idx])}],
                "Tags": [],
            })
        for pre_idx in range(self.pre_num):
            rels = [
                {"Rel": "PreSynTo", "To": xyz(self.post[post_idx, 1:])}
                for post_idx in self.post_indices_of_pre(pre_idx)
            ]
            conf = (
                float(self.pre_confidence[pre_idx])
                if self.pre_confidence is not None else 1.0
            )
            data.append({
                "Kind": "PreSyn",
                "Pos": xyz(self.pre[pre_idx]),
                "Prop": {"annotation": comment, "conf": str(conf),
                         "user": user},
                "Rels": rels,
                "Tags": [],
            })
        return data

    def to_neutu_task(self, path: str,
                      software_revision: int = 4809,
                      description: str = "transformed using chunkflow_tpu",
                      file_version: int = 1,
                      body_id: Optional[int] = None) -> str:
        """NeuTu focused-proofreading task JSON (presynapses only, like the
        reference's exporter)."""
        import time as _time

        if not path.endswith(".json"):
            raise ValueError("NeuTu task file must end with .json")
        task = {
            "metadata": {
                "date": _time.strftime("%d-%B-%Y %H:%M"),
                "session path": "",
                "software revision": software_revision,
                "description": description,
                "coordinate system": "dvid",
                "software": "chunkflow_tpu",
                "file version": file_version,
                "username": "chunkflow_tpu",
                "computer": "localhost",
            },
            "data": [
                {
                    "body ID": body_id if body_id is not None else "",
                    "location": [int(v) for v in self.pre[idx, ::-1]],
                }
                for idx in range(self.pre_num)
            ],
        }
        with open(path, "w") as f:
            json.dump(task, f)
        return path
