from chunkflow_tpu.annotations.synapses import Synapses
from chunkflow_tpu.annotations.point_cloud import PointCloud

__all__ = ["Synapses", "PointCloud"]
