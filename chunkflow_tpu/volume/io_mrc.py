"""Native MRC2014 reader (no mrcfile dependency).

Parity target: reference ``plugins/load_mrc.py`` (mrcfile.open). MRC is a
fixed 1024-byte header + optional extended header + raw voxel data; the
subset needed for EM stacks reads directly.
"""
from __future__ import annotations

import struct
from typing import Tuple

import numpy as np

_MODE_TO_DTYPE = {
    0: np.int8,
    1: np.int16,
    2: np.float32,
    3: np.complex64,   # int16 re/im; rarely used
    4: np.complex64,
    6: np.uint16,
    12: np.float16,
}


def load_mrc(path: str) -> Tuple[np.ndarray, dict]:
    """Read an MRC file -> (zyx array, header dict with voxel size in nm)."""
    with open(path, "rb") as f:
        header = f.read(1024)
        nx, ny, nz, mode = struct.unpack("<4i", header[0:16])
        mx, my, mz = struct.unpack("<3i", header[28:40])
        xlen, ylen, zlen = struct.unpack("<3f", header[40:52])
        nsymbt = struct.unpack("<i", header[92:96])[0]
        f.seek(1024 + nsymbt)
        if mode not in _MODE_TO_DTYPE:
            raise ValueError(f"{path}: unsupported MRC mode {mode}")
        dtype = np.dtype(_MODE_TO_DTYPE[mode]).newbyteorder("<")
        data = np.fromfile(f, dtype=dtype, count=nx * ny * nz)
    array = data.reshape(nz, ny, nx)  # MRC stores x fastest -> zyx C order
    # cell dimensions are in angstrom; voxel size nm = len/10/grid
    voxel_size = tuple(
        (length / 10.0 / grid) if grid else 1.0
        for length, grid in ((zlen, mz), (ylen, my), (xlen, mx))
    )
    return array.copy(), {"voxel_size_nm": voxel_size, "mode": mode}


def save_mrc(path: str, array: np.ndarray, voxel_size_nm=(1.0, 1.0, 1.0)) -> str:
    """Write a minimal MRC2014 file (modes: int8/int16/float32/uint16)."""
    arr = np.ascontiguousarray(array)
    mode = {np.dtype(np.int8): 0, np.dtype(np.int16): 1,
            np.dtype(np.float32): 2, np.dtype(np.uint16): 6}.get(arr.dtype)
    if mode is None:
        arr = arr.astype(np.float32)
        mode = 2
    nz, ny, nx = arr.shape
    header = bytearray(1024)
    struct.pack_into("<4i", header, 0, nx, ny, nz, mode)
    struct.pack_into("<3i", header, 28, nx, ny, nz)
    struct.pack_into(
        "<3f", header, 40,
        nx * voxel_size_nm[2] * 10.0,
        ny * voxel_size_nm[1] * 10.0,
        nz * voxel_size_nm[0] * 10.0,
    )
    struct.pack_into("<3i", header, 64, 1, 2, 3)  # axis correspondence
    struct.pack_into(
        "<3f", header, 76,
        float(arr.min()), float(arr.max()), float(arr.mean())
    )
    header[208:212] = b"MAP "
    header[212:216] = bytes([0x44, 0x44, 0x00, 0x00])  # little-endian stamp
    with open(path, "wb") as f:
        f.write(bytes(header))
        f.write(arr.astype(arr.dtype.newbyteorder("<")).tobytes())
    return path
