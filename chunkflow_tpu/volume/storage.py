"""The storage plane: one backend interface, block-granular hot cache,
concurrent block I/O.

The reference pipeline's production claim (18 PB of output images,
PAPER.md) rests on the storage path keeping thousands of workers fed,
yet until this module every byte moved through one blocking
``read().result()`` in volume/precomputed.py. Three facts make that the
wrong shape at fleet scale:

1. **Storage is block-granular.** A precomputed/zarr/n5 volume is a
   key-value store of fixed-size blocks; a cutout is a *set* of block
   GETs that the serial path needlessly serializes behind one future.
2. **Task grids overlap.** Inference chunks carry halos, so neighboring
   tasks re-fetch the same boundary blocks from cold storage — on an
   overlapping grid most block reads are repeats of a neighbor's.
3. **Blocks are immutable in the write-once layout.** Aligned chunks
   never share a block (the write-conflict-avoidance contract,
   volume/precomputed.py), which is exactly what makes a host-side
   block cache safe to share across tasks in a worker.

This module therefore provides, for every array store the repo touches
(neuroglancer precomputed per mip, tensorstore zarr/n5 datasets in the
plugins, in-memory test/bench fixtures):

* :class:`StorageBackend` — the one async array interface
  (:class:`TensorStoreBackend` for real drivers, :class:`MemoryBackend`
  for fixtures) plus the sidecar/existence KV plane
  (:class:`FileKV` / :class:`TensorStoreKV`, :func:`open_kv`);
* :class:`BlockCache` — a bytes-bounded, thread-safe (GL010/locksmith
  clean) LRU of storage blocks, shared process-wide via
  :func:`shared_cache` so halo reads of already-fetched blocks hit host
  memory (the page/block-granularity idiom Ragged Paged Attention uses
  to keep serving occupancy high, PAPERS.md);
* :func:`blockwise_cutout` — a cutout as storage-block-aligned sub-reads
  issued as concurrent backend futures (bounded by
  :func:`read_concurrency`, an adaptive-scheduler knob) and assembled
  host-side;
* :func:`blockwise_save` — the coalescing write path: block-aligned
  writes commit as concurrent per-block futures (no read-modify-write)
  and update the cache write-through; unaligned writes fall back to one
  driver-level RMW write and invalidate the covered blocks, so
  read-after-write through the cache stays correct either way.

Kill switches: ``CHUNKFLOW_STORAGE=serial`` restores the historical
single-read path bit-identically (:func:`storage_mode`);
``CHUNKFLOW_STORAGE_CACHE_MB=0`` disables the cache (every read goes to
storage). Telemetry (docs/storage.md, docs/observability.md): spans
``storage/read`` / ``storage/write``; counters ``storage/hits``,
``storage/misses``, ``storage/block_reads``, ``storage/bytes_read``,
``storage/bytes_written``, ``storage/aligned_writes``,
``storage/unaligned_writes``, ``storage/evictions``; gauge
``storage/cache_bytes``.

Coherence note: the cache is per-worker and trusts the write-once block
layout — blocks observed all-zero (tensorstore's fill_missing rendering
of absent blocks) are deliberately NOT cached, so a halo read that races
a neighbor task's first write re-fetches fresh bytes instead of pinning
stale zeros (docs/storage.md "Invalidation semantics").
"""
from __future__ import annotations

import abc
import itertools
import os
import threading
import time
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from chunkflow_tpu.core import telemetry

__all__ = [
    "storage_mode", "cache_bytes_limit", "read_concurrency",
    "set_read_concurrency", "BlockCache", "shared_cache",
    "reset_shared_cache", "StorageBackend", "TensorStoreBackend",
    "MemoryBackend", "KVBackend", "FileKV", "MemoryKV", "TensorStoreKV",
    "open_kv", "KVArrayBackend",
    "blockwise_cutout", "blockwise_save", "serial_cutout", "GatherFuture",
]

_OFF_VALUES = ("serial", "0", "off", "false", "no")


def storage_mode() -> str:
    """``concurrent`` (default) or ``serial`` (``CHUNKFLOW_STORAGE=serial``
    kill switch: the historical one-blocking-read path, bit-identically).
    Re-read per call so tests and long-lived workers can flip it."""
    value = os.environ.get("CHUNKFLOW_STORAGE", "concurrent").lower()
    return "serial" if value in _OFF_VALUES else "concurrent"


def cache_bytes_limit() -> int:
    """Byte budget of the shared hot-block cache
    (``CHUNKFLOW_STORAGE_CACHE_MB``, default 256 MB; <=0 disables the
    cache entirely). A malformed value falls back to the default."""
    raw = os.environ.get("CHUNKFLOW_STORAGE_CACHE_MB", "")
    try:
        mb = float(raw) if raw else 256.0
    except ValueError:
        mb = 256.0
    return int(mb * (1 << 20))


# ---------------------------------------------------------------------------
# read-concurrency knob (adaptive-scheduler managed)
# ---------------------------------------------------------------------------
_CONC_LOCK = threading.Lock()
_READ_CONCURRENCY: Optional[int] = None


def read_concurrency() -> int:
    """Concurrent block reads issued per cutout: the
    ``CHUNKFLOW_STORAGE_CONCURRENCY`` initial value (default 8), runtime
    adjustable via :func:`set_read_concurrency` — the adaptive
    scheduler's ``storage`` depth knob widens it when ``scheduler/load``
    dominates the stall breakdown (flow/scheduler.py)."""
    with _CONC_LOCK:
        if _READ_CONCURRENCY is not None:
            return _READ_CONCURRENCY
    raw = os.environ.get("CHUNKFLOW_STORAGE_CONCURRENCY", "")
    try:
        return max(1, int(raw)) if raw else 8
    except ValueError:
        return 8


def set_read_concurrency(n: int) -> None:
    """Set the live per-cutout block-read parallelism (DepthController
    ``storage`` knob; tests)."""
    global _READ_CONCURRENCY
    with _CONC_LOCK:
        _READ_CONCURRENCY = max(1, int(n))
    telemetry.gauge("storage/read_concurrency", max(1, int(n)))


def _reset_read_concurrency() -> None:
    """Back to the env-resolved default (tests)."""
    global _READ_CONCURRENCY
    with _CONC_LOCK:
        _READ_CONCURRENCY = None


# ---------------------------------------------------------------------------
# block-granular hot-chunk LRU
# ---------------------------------------------------------------------------
class BlockCache:
    """Bytes-bounded, thread-safe LRU of immutable storage blocks.

    Keys are ``(backend.cache_token, block_lo)`` tuples; values are
    read-only ndarrays holding exactly one storage block (clamped to the
    dataset domain). All mutation sits behind one lock and nothing
    blocking ever runs under it (GL012); hit/miss/eviction totals are
    kept locally and exposed as attributes — the cutout/save paths fold
    them into the telemetry registry outside the lock."""

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, np.ndarray]" = OrderedDict()
        self._nbytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @property
    def nbytes(self) -> int:
        with self._lock:
            return self._nbytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def get(self, key) -> Optional[np.ndarray]:
        """The cached block (read-only view) or None; counts the
        hit/miss and refreshes recency."""
        with self._lock:
            arr = self._entries.get(key)
            if arr is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return arr

    def put(self, key, arr: np.ndarray) -> bool:
        """Insert one block (copied defensively only by callers; the
        cache marks it read-only in place). Oversized blocks are
        refused; inserting evicts LRU entries until the byte budget
        holds."""
        nbytes = int(arr.nbytes)
        if nbytes > self.max_bytes:
            return False
        arr.setflags(write=False)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._nbytes -= old.nbytes
            self._entries[key] = arr
            self._nbytes += nbytes
            while self._nbytes > self.max_bytes and self._entries:
                _, evicted = self._entries.popitem(last=False)
                self._nbytes -= evicted.nbytes
                self.evictions += 1
        return True

    def invalidate(self, key) -> bool:
        """Drop one block (write-path invalidation); True if present."""
        with self._lock:
            arr = self._entries.pop(key, None)
            if arr is None:
                return False
            self._nbytes -= arr.nbytes
            return True

    def invalidate_token(self, token) -> int:
        """Drop every block of one dataset (volume deleted/recreated);
        returns the number of entries removed."""
        with self._lock:
            doomed = [k for k in self._entries if k and k[0] == token]
            for key in doomed:
                self._nbytes -= self._entries.pop(key).nbytes
            return len(doomed)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._nbytes = 0


_CACHE_LOCK = threading.Lock()
_SHARED_CACHE: Optional[BlockCache] = None


def shared_cache() -> Optional[BlockCache]:
    """The process-wide hot-block cache shared across tasks in a worker
    (None when ``CHUNKFLOW_STORAGE_CACHE_MB<=0``). Rebuilt when the
    byte budget changes so tests can resize it via the env knob."""
    global _SHARED_CACHE
    limit = cache_bytes_limit()
    if limit <= 0:
        return None
    with _CACHE_LOCK:
        if _SHARED_CACHE is None or _SHARED_CACHE.max_bytes != limit:
            _SHARED_CACHE = BlockCache(limit)
        return _SHARED_CACHE


def reset_shared_cache() -> None:
    """Drop the shared cache (tests; a fresh one opens on next use)."""
    global _SHARED_CACHE
    with _CACHE_LOCK:
        _SHARED_CACHE = None


# ---------------------------------------------------------------------------
# futures
# ---------------------------------------------------------------------------
class GatherFuture:
    """One future over many: ``result()`` drains every member even when
    one fails (first exception wins — the drain_pending_writes
    contract), and ``.copy`` aggregates the members' copy legs so the
    ``save(wait=False)`` caller-may-reuse-the-buffer protocol holds for
    multi-block writes. Members without a ``.copy`` leg (plain
    concurrent.futures) count as copied once resolved."""

    __slots__ = ("_futures",)

    def __init__(self, futures: Iterable):
        self._futures = list(futures)

    def result(self):
        first: Optional[BaseException] = None
        for future in self._futures:
            try:
                future.result()
            except BaseException as exc:
                if first is None:
                    first = exc
        if first is not None:
            raise first
        return None

    def done(self) -> bool:
        return all(
            f.done() for f in self._futures if hasattr(f, "done")
        )

    @property
    def copy(self) -> "GatherFuture":
        return GatherFuture(
            [getattr(f, "copy", f) for f in self._futures]
        )


# ---------------------------------------------------------------------------
# the backend interface
# ---------------------------------------------------------------------------
class StorageBackend(abc.ABC):
    """Uniform async array-store interface: everything upstream
    (PrecomputedVolume mips, the tensorstore zarr/n5 plugins, test and
    bench fixtures) reads and writes through this, so the concurrent
    cutout/save machinery and the block cache are written once.

    Index space is the backend's native one (xyzc for precomputed,
    dataset order for zarr/n5, plain array axes for fixtures); the
    zyx-czyx facade stays where it always was, in
    volume/precomputed.py."""

    #: stable identity of the backing dataset — the cache key namespace
    cache_token: str

    @property
    @abc.abstractmethod
    def domain(self) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        """(inclusive_min, exclusive_max) index bounds, native order."""

    @property
    @abc.abstractmethod
    def dtype(self) -> np.dtype:
        ...

    @property
    @abc.abstractmethod
    def block_shape(self) -> Tuple[int, ...]:
        """Storage block extent per dimension (native order)."""

    @property
    def grid_offset(self) -> Tuple[int, ...]:
        """Origin the block grid is anchored at (defaults to the domain
        lower bound — true for precomputed and zarr alike)."""
        return self.domain[0]

    @abc.abstractmethod
    def read_async(self, lo: Sequence[int], hi: Sequence[int]):
        """Start reading ``[lo, hi)``; returns a future of an ndarray."""

    @abc.abstractmethod
    def write_async(self, lo: Sequence[int], hi: Sequence[int], arr):
        """Start writing ``arr`` over ``[lo, hi)``; returns a future."""


class TensorStoreBackend(StorageBackend):
    """A :class:`StorageBackend` over one opened tensorstore dataset.

    Block shape defaults to the driver's read-chunk layout (the storage
    block for precomputed/zarr/n5), falling back to the whole domain
    when the driver reports none — a degenerate single-block grid that
    keeps the blockwise paths correct, if cache-coarse."""

    def __init__(self, store, token: Optional[str] = None,
                 block_shape: Optional[Sequence[int]] = None,
                 grid_offset: Optional[Sequence[int]] = None):
        self._store = store
        spec_token = token
        if spec_token is None:
            try:
                spec_token = str(store.spec(minimal_spec=True).to_json())
            except Exception:
                spec_token = f"tensorstore-{id(store)}"
        self.cache_token = spec_token
        lo = tuple(int(v) for v in store.domain.inclusive_min)
        hi = tuple(int(v) for v in store.domain.exclusive_max)
        self._domain = (lo, hi)
        if block_shape is None:
            block_shape = self._layout_block_shape(store, lo, hi)
        self._block_shape = tuple(int(v) for v in block_shape)
        self._grid_offset = (
            tuple(int(v) for v in grid_offset)
            if grid_offset is not None else lo
        )

    @staticmethod
    def _layout_block_shape(store, lo, hi):
        try:
            shape = store.chunk_layout.read_chunk.shape
        except Exception:
            shape = None
        if shape is None or any(not s for s in shape):
            return tuple(h - l for l, h in zip(lo, hi))
        return tuple(int(s) for s in shape)

    @classmethod
    def open(cls, spec: dict, token: Optional[str] = None,
             **kwargs) -> "TensorStoreBackend":
        import tensorstore as ts

        return cls(ts.open(spec).result(), token=token, **kwargs)

    @property
    def store(self):
        return self._store

    @property
    def domain(self):
        return self._domain

    @property
    def dtype(self) -> np.dtype:
        return np.dtype(self._store.dtype.numpy_dtype)

    @property
    def block_shape(self):
        return self._block_shape

    @property
    def grid_offset(self):
        return self._grid_offset

    def _slices(self, lo, hi):
        return tuple(slice(l, h) for l, h in zip(lo, hi))

    def read_async(self, lo, hi):
        return self._store[self._slices(lo, hi)].read()

    def write_async(self, lo, hi, arr):
        return self._store[self._slices(lo, hi)].write(arr)


class MemoryBackend(StorageBackend):
    """An in-memory :class:`StorageBackend` over a numpy array — the
    test fixture and the bench's cold-storage stand-in.

    ``latency_s`` charges a simulated per-BLOCK fetch latency (an object
    GET per storage block, how remote stores actually bill a cutout):
    reading ``[lo, hi)`` sleeps ``latency_s`` times the number of
    storage blocks the range covers, inside a worker thread of the
    backend's pool — so concurrent block reads genuinely overlap their
    latencies and a serial whole-range read genuinely pays them all."""

    _SEQ = itertools.count()

    def __init__(self, array: np.ndarray,
                 block_shape: Optional[Sequence[int]] = None,
                 latency_s: float = 0.0, max_workers: int = 8):
        from concurrent.futures import ThreadPoolExecutor

        self._array = array
        self._lock = threading.Lock()
        self._latency_s = float(latency_s)
        self.cache_token = f"memory-{next(self._SEQ)}"
        self._block_shape = tuple(
            int(v) for v in (block_shape or array.shape)
        )
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers,
            thread_name_prefix="chunkflow-storage",
        )

    @property
    def domain(self):
        return (
            tuple(0 for _ in self._array.shape),
            tuple(int(s) for s in self._array.shape),
        )

    @property
    def dtype(self) -> np.dtype:
        return self._array.dtype

    @property
    def block_shape(self):
        return self._block_shape

    def _covered_blocks(self, lo, hi) -> int:
        n = 1
        for l, h, b in zip(lo, hi, self._block_shape):
            n *= max(1, -((-(h - (l - l % b))) // b))
        return n

    def _slices(self, lo, hi):
        return tuple(slice(l, h) for l, h in zip(lo, hi))

    def _read(self, lo, hi):
        if self._latency_s:
            # sleep OUTSIDE the lock (GL012): the latency is the remote
            # round-trip, not contention on the local buffer
            time.sleep(self._latency_s * self._covered_blocks(lo, hi))
        with self._lock:
            return np.array(self._array[self._slices(lo, hi)], copy=True)

    def _write(self, lo, hi, arr):
        if self._latency_s:
            time.sleep(self._latency_s * self._covered_blocks(lo, hi))
        with self._lock:
            self._array[self._slices(lo, hi)] = arr

    def read_async(self, lo, hi):
        return self._pool.submit(self._read, tuple(lo), tuple(hi))

    def write_async(self, lo, hi, arr):
        return self._pool.submit(self._write, tuple(lo), tuple(hi), arr)

    def close(self) -> None:
        self._pool.shutdown(wait=False)


# ---------------------------------------------------------------------------
# the KV plane (sidecar files + block existence)
# ---------------------------------------------------------------------------
class KVBackend(abc.ABC):
    """Sidecar/object plane of a volume root: ``info`` and JSON
    sidecars, plus batched block-existence checks for resume skip
    logic. One handle per volume, opened once and cached
    (volume/precomputed.py) — not re-opened per call."""

    @abc.abstractmethod
    def read_bytes(self, name: str) -> Optional[bytes]:
        """Value of ``name`` or None when absent."""

    @abc.abstractmethod
    def write_bytes(self, name: str, data: bytes) -> None:
        ...

    @abc.abstractmethod
    def exists_many(self, names: Sequence[str]) -> Dict[str, bool]:
        """Batched stat-style existence of every name — never a full
        value download per key (the resume skip-logic path checks
        whole task grids through this)."""


class FileKV(KVBackend):
    """Local-filesystem KV plane (bare paths and file:// roots)."""

    def __init__(self, root: str):
        self.root = root

    def read_bytes(self, name: str) -> Optional[bytes]:
        path = os.path.join(self.root, name)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def write_bytes(self, name: str, data: bytes) -> None:
        # tmp + rename: a concurrent reader (another worker assembling
        # an interface plane from face sidecars, or a replayed task
        # rewriting the same object) must never observe a torn value
        path = os.path.join(self.root, name)
        os.makedirs(os.path.dirname(path) or self.root, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def exists_many(self, names: Sequence[str]) -> Dict[str, bool]:
        return {
            name: os.path.exists(os.path.join(self.root, name))
            for name in names
        }


class MemoryKV(KVBackend):
    """In-process KV plane (tests, the bench's sidecar stand-in).
    Thread-safe; values are immutable bytes so reads need no copies."""

    def __init__(self):
        self._lock = threading.Lock()
        self._data: Dict[str, bytes] = {}

    def read_bytes(self, name: str) -> Optional[bytes]:
        with self._lock:
            return self._data.get(name)

    def write_bytes(self, name: str, data: bytes) -> None:
        with self._lock:
            self._data[name] = bytes(data)

    def exists_many(self, names: Sequence[str]) -> Dict[str, bool]:
        with self._lock:
            return {name: name in self._data for name in names}


class TensorStoreKV(KVBackend):
    """Remote KV plane over one cached ``ts.KvStore`` handle.

    Existence checks are batched: one ``KvStore.list`` over the tight
    key range spanning the queried names (a single round trip listing
    only keys, no values) — never the historical per-name full-value
    ``read().result()`` download. Falls back to concurrent per-name
    reads if the driver cannot list."""

    def __init__(self, spec: dict):
        self.spec = dict(spec)
        # a kvstore path is a PREFIX to tensorstore: without a trailing
        # slash, "root" + "1_1_1/..." resolves to "root1_1_1/..." and
        # every name lookup silently misses (the array drivers append
        # the slash internally, which is why reads worked while the
        # seed's per-name existence probe never could)
        path = self.spec.get("path")
        if path and not path.endswith("/"):
            self.spec["path"] = path + "/"
        self._lock = threading.Lock()
        self._kv = None

    @property
    def kv(self):
        """The KvStore handle, opened once (satellite: no re-open per
        info/read_json/has_all_blocks call). Double-checked so the
        blocking driver open never runs under the lock; a lost race
        opens one redundant handle and drops it."""
        with self._lock:
            kv = self._kv
        if kv is None:
            import tensorstore as ts

            opened = ts.KvStore.open(self.spec).result()
            with self._lock:
                if self._kv is None:
                    self._kv = opened
                kv = self._kv
        return kv

    def read_bytes(self, name: str) -> Optional[bytes]:
        result = self.kv.read(name).result()
        if result.state == "missing":
            return None
        return bytes(result.value)

    def write_bytes(self, name: str, data: bytes) -> None:
        self.kv.write(name, data).result()

    def exists_many(self, names: Sequence[str]) -> Dict[str, bool]:
        if not names:
            return {}
        import tensorstore as ts

        ordered = sorted(names)
        try:
            keys = self.kv.list(
                ts.KvStore.KeyRange(
                    inclusive_min=ordered[0],
                    exclusive_max=ordered[-1] + "\x00",
                )
            ).result()
            present = {
                k.decode() if isinstance(k, bytes) else str(k)
                for k in keys
            }
            return {name: name in present for name in names}
        except Exception:
            # drivers without list support: concurrent reads (still one
            # wave in flight, not one blocking round trip per block)
            futures = [(name, self.kv.read(name)) for name in names]
            return {
                name: future.result().state != "missing"
                for name, future in futures
            }


def open_kv(spec: dict) -> KVBackend:
    """The right KV plane for a kvstore spec: direct filesystem access
    for the file driver, a cached tensorstore handle otherwise."""
    if spec.get("driver") == "file":
        return FileKV(spec["path"])
    return TensorStoreKV(spec)


class KVArrayBackend(StorageBackend):
    """A :class:`StorageBackend` persisting one npy object per storage
    block through any :class:`KVBackend` — the dependency-free shared
    array store of the segmentation plane (docs/segmentation.md): a
    FileKV root gives multi-process workers a common label volume with
    no tensorstore requirement, a :class:`MemoryKV` gives tests one.

    Blocks are keyed ``<prefix>/<lo..hi bbox string>.npy`` on the grid
    anchored at the domain origin; absent blocks read as ``fill``
    (labels default to background). Writes covering whole (clamped)
    blocks store them directly; partial writes read-modify-write the
    covered blocks — safe under the aligned-chunk contract (parallel
    writers never share a block), and the FileKV tmp+rename write keeps
    concurrent readers untorn either way."""

    _SEQ = itertools.count()

    def __init__(self, kv: KVBackend, domain, dtype,
                 block_shape: Sequence[int], prefix: str = "blocks",
                 fill=0, max_workers: int = 4):
        from concurrent.futures import ThreadPoolExecutor

        self._kv = kv
        lo, hi = domain
        self._domain = (
            tuple(int(v) for v in lo), tuple(int(v) for v in hi)
        )
        self._dtype = np.dtype(dtype)
        self._block_shape = tuple(int(v) for v in block_shape)
        self._prefix = prefix
        self._fill = fill
        root = getattr(kv, "root", None)
        self.cache_token = (
            f"kvarray:{root}:{prefix}" if root is not None
            else f"kvarray:mem{next(self._SEQ)}:{prefix}"
        )
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers,
            thread_name_prefix="chunkflow-kvarray",
        )

    @property
    def domain(self):
        return self._domain

    @property
    def dtype(self) -> np.dtype:
        return self._dtype

    @property
    def block_shape(self):
        return self._block_shape

    def _block_key(self, blo, bhi) -> str:
        span = "_".join(f"{l}-{h}" for l, h in zip(blo, bhi))
        return f"{self._prefix}/{span}.npy"

    def _read_block(self, blo, bhi) -> np.ndarray:
        import io

        data = self._kv.read_bytes(self._block_key(blo, bhi))
        if data is None:
            return np.full(
                tuple(h - l for l, h in zip(blo, bhi)),
                self._fill, dtype=self._dtype,
            )
        return np.load(io.BytesIO(data), allow_pickle=False)

    def _write_block(self, blo, bhi, arr: np.ndarray) -> None:
        import io

        buf = io.BytesIO()
        np.save(buf, np.ascontiguousarray(arr, dtype=self._dtype),
                allow_pickle=False)
        self._kv.write_bytes(self._block_key(blo, bhi), buf.getvalue())

    def _read(self, lo, hi) -> np.ndarray:
        out = np.empty(
            tuple(h - l for l, h in zip(lo, hi)), dtype=self._dtype
        )
        dlo, dhi = self._domain
        for blo, bhi in _covering_blocks(
            lo, hi, self._block_shape, self.grid_offset, dlo, dhi
        ):
            _copy_block(out, lo, hi, self._read_block(blo, bhi), blo, bhi)
        return out

    def _write(self, lo, hi, arr: np.ndarray) -> None:
        arr = np.asarray(arr)
        dlo, dhi = self._domain
        for blo, bhi in _covering_blocks(
            lo, hi, self._block_shape, self.grid_offset, dlo, dhi
        ):
            covers = all(
                l <= bl and bh <= h
                for l, h, bl, bh in zip(lo, hi, blo, bhi)
            )
            sel = tuple(
                slice(max(l, bl) - l, min(h, bh) - l)
                for l, h, bl, bh in zip(lo, hi, blo, bhi)
            )
            if covers:
                self._write_block(blo, bhi, arr[sel])
                continue
            block = self._read_block(blo, bhi)  # partial: RMW
            block[tuple(
                slice(max(l, bl) - bl, min(h, bh) - bl)
                for l, h, bl, bh in zip(lo, hi, blo, bhi)
            )] = arr[sel]
            self._write_block(blo, bhi, block)

    def read_async(self, lo, hi):
        return self._pool.submit(self._read, tuple(lo), tuple(hi))

    def write_async(self, lo, hi, arr):
        return self._pool.submit(self._write, tuple(lo), tuple(hi), arr)

    def close(self) -> None:
        self._pool.shutdown(wait=False)


_BACKEND_LOCK = threading.Lock()
_OPEN_BACKENDS: Dict[str, TensorStoreBackend] = {}


def open_backend_cached(spec: dict) -> TensorStoreBackend:
    """Open (once per process) a :class:`TensorStoreBackend` for a full
    tensorstore spec — the plugin path (load_tensorstore/load_n5) calls
    this per task, and re-opening the driver per call would defeat both
    the driver's own handle reuse and the block cache's token stability.
    The blocking driver open runs outside the lock; a lost race keeps
    the first-registered backend."""
    import json as _json

    key = _json.dumps(spec, sort_keys=True)
    with _BACKEND_LOCK:
        backend = _OPEN_BACKENDS.get(key)
    if backend is None:
        opened = TensorStoreBackend.open(spec, token=key)
        with _BACKEND_LOCK:
            backend = _OPEN_BACKENDS.setdefault(key, opened)
    return backend


def reset_open_backends() -> None:
    """Drop the plugin-path backend handles (tests)."""
    with _BACKEND_LOCK:
        _OPEN_BACKENDS.clear()


# ---------------------------------------------------------------------------
# blockwise concurrent reads
# ---------------------------------------------------------------------------
def _covering_blocks(lo, hi, block, goff, dlo, dhi):
    """Clamped block bounds ``(blo, bhi)`` covering ``[lo, hi)`` on the
    grid anchored at ``goff``, in grid order."""
    ndim = len(lo)
    ranges = []
    for d in range(ndim):
        first = (lo[d] - goff[d]) // block[d]
        last = -((-(hi[d] - goff[d])) // block[d])
        ranges.append(range(first, last))
    blocks = []
    for idx in itertools.product(*ranges):
        blo = tuple(
            max(goff[d] + idx[d] * block[d], dlo[d]) for d in range(ndim)
        )
        bhi = tuple(
            min(goff[d] + (idx[d] + 1) * block[d], dhi[d])
            for d in range(ndim)
        )
        blocks.append((blo, bhi))
    return blocks


def _copy_block(out, lo, hi, arr, blo, bhi) -> None:
    """Copy the ``[lo,hi)``-intersecting part of a block array (covering
    ``[blo,bhi)``) into the output array (origin ``lo``)."""
    sel_out, sel_blk = [], []
    for d in range(len(lo)):
        ilo = max(lo[d], blo[d])
        ihi = min(hi[d], bhi[d])
        sel_out.append(slice(ilo - lo[d], ihi - lo[d]))
        sel_blk.append(slice(ilo - blo[d], ihi - blo[d]))
    out[tuple(sel_out)] = arr[tuple(sel_blk)]


def _check_domain(backend: StorageBackend, lo, hi) -> None:
    dlo, dhi = backend.domain
    for d in range(len(lo)):
        if lo[d] < dlo[d] or hi[d] > dhi[d] or lo[d] >= hi[d]:
            raise ValueError(
                f"request [{tuple(lo)}, {tuple(hi)}) outside storage "
                f"domain [{dlo}, {dhi})"
            )


def serial_cutout(backend: StorageBackend, lo: Sequence[int],
                  hi: Sequence[int]) -> np.ndarray:
    """The historical path: one blocking whole-range read. Kept as the
    bit-identity reference for the concurrent path (tests, bench,
    ``CHUNKFLOW_STORAGE=serial``)."""
    lo, hi = tuple(int(v) for v in lo), tuple(int(v) for v in hi)
    _check_domain(backend, lo, hi)
    with telemetry.span("storage/read", mode="serial"):
        arr = np.asarray(backend.read_async(lo, hi).result())
    telemetry.inc("storage/bytes_read", arr.nbytes)
    return arr


def blockwise_cutout(backend: StorageBackend, lo: Sequence[int],
                     hi: Sequence[int],
                     cache: Optional[BlockCache] = None) -> np.ndarray:
    """Read ``[lo, hi)`` as storage-block-aligned sub-reads: cached
    blocks are served from host memory; misses are issued as concurrent
    backend futures in waves of :func:`read_concurrency` and assembled
    host-side. Reads FULL (clamped) blocks even at the request edges —
    the whole point: a neighbor task's halo read then hits the cache
    instead of cold storage."""
    lo, hi = tuple(int(v) for v in lo), tuple(int(v) for v in hi)
    _check_domain(backend, lo, hi)
    dlo, dhi = backend.domain
    out = np.empty(
        tuple(h - l for l, h in zip(lo, hi)), dtype=backend.dtype
    )
    blocks = _covering_blocks(
        lo, hi, backend.block_shape, backend.grid_offset, dlo, dhi
    )
    hits = 0
    bytes_read = 0
    missing: List[tuple] = []
    with telemetry.span("storage/read", mode="blockwise",
                        blocks=len(blocks)):
        for blo, bhi in blocks:
            cached = (
                cache.get((backend.cache_token, blo))
                if cache is not None else None
            )
            if cached is None:
                missing.append((blo, bhi))
            else:
                hits += 1
                _copy_block(out, lo, hi, cached, blo, bhi)
        wave = max(1, read_concurrency())
        for i in range(0, len(missing), wave):
            batch = missing[i:i + wave]
            futures = [
                backend.read_async(blo, bhi) for blo, bhi in batch
            ]
            for (blo, bhi), future in zip(batch, futures):
                arr = np.asarray(future.result())
                bytes_read += arr.nbytes
                # all-zero blocks may simply not exist yet (fill_missing
                # rendering): never pin them — a later read must see the
                # neighbor's eventual write, not stale cached zeros
                if cache is not None and arr.any():
                    cache.put((backend.cache_token, blo), arr)
                _copy_block(out, lo, hi, arr, blo, bhi)
    if telemetry.enabled():
        if hits:
            telemetry.inc("storage/hits", hits)
        if missing:
            telemetry.inc("storage/misses", len(missing))
            telemetry.inc("storage/block_reads", len(missing))
            telemetry.inc("storage/bytes_read", bytes_read)
        if cache is not None:
            telemetry.gauge("storage/cache_bytes", cache.nbytes)
    return out


# ---------------------------------------------------------------------------
# the coalescing write path
# ---------------------------------------------------------------------------
def _write_is_aligned(lo, hi, block, goff, dlo, dhi) -> bool:
    """True when ``[lo, hi)`` starts on the block grid and ends on it
    (or at the domain edge, where storage clamps trailing blocks): such
    a write owns whole blocks — no read-modify-write, and parallel
    writers cannot conflict (the aligned-chunk contract)."""
    for d in range(len(lo)):
        if (lo[d] - goff[d]) % block[d] != 0:
            return False
        if hi[d] != dhi[d] and (hi[d] - goff[d]) % block[d] != 0:
            return False
    return True


def blockwise_save(backend: StorageBackend, lo: Sequence[int],
                   arr: np.ndarray, cache: Optional[BlockCache] = None,
                   wait: bool = True):
    """Write ``arr`` at ``lo`` through the coalescing path.

    Block-aligned writes decompose into per-block futures issued
    concurrently — each commits its block directly (no driver-level
    read-modify-write) — and update the cache write-through (a copy of
    the written block replaces any cached version, so read-after-write
    through the cache returns the written bytes even before the commit
    is durable). Unaligned writes fall back to one whole-range driver
    write and *invalidate* every covered block instead.

    ``wait=True`` blocks until every block is durable (every future
    drained even when one fails; first exception wins). ``wait=False``
    awaits only the copy legs — the caller may reuse the buffer — and
    returns a :class:`GatherFuture` for the write-behind window; the
    ack-after-durable-write barrier (``runtime.drain_pending_writes``)
    drains it exactly like the single-future path it replaces."""
    lo = tuple(int(v) for v in lo)
    hi = tuple(l + s for l, s in zip(lo, arr.shape))
    _check_domain(backend, lo, hi)
    dlo, dhi = backend.domain
    block, goff = backend.block_shape, backend.grid_offset
    aligned = (
        storage_mode() == "concurrent"
        and _write_is_aligned(lo, hi, block, goff, dlo, dhi)
    )
    futures = []
    with telemetry.span("storage/write",
                        mode="aligned" if aligned else "unaligned"):
        if aligned:
            for blo, bhi in _covering_blocks(lo, hi, block, goff,
                                             dlo, dhi):
                sub = arr[tuple(
                    slice(bl - l, bh - l)
                    for l, bl, bh in zip(lo, blo, bhi)
                )]
                futures.append(backend.write_async(blo, bhi, sub))
                if cache is not None:
                    block_copy = np.array(sub, copy=True)
                    if block_copy.any():
                        cache.put(
                            (backend.cache_token, blo), block_copy
                        )
                    else:
                        # stay consistent with the read path's
                        # zeros-are-never-pinned rule
                        cache.invalidate((backend.cache_token, blo))
            telemetry.inc("storage/aligned_writes")
        else:
            futures.append(backend.write_async(lo, hi, arr))
            if cache is not None:
                for blo, _bhi in _covering_blocks(lo, hi, block, goff,
                                                  dlo, dhi):
                    cache.invalidate((backend.cache_token, blo))
            telemetry.inc("storage/unaligned_writes")
        telemetry.inc("storage/bytes_written", arr.nbytes)
        gathered = GatherFuture(futures)
        if wait:
            gathered.result()
            return None
        # await the COPY legs (the driver reading the source buffer) so
        # callers may freely reuse/mutate the array; only the storage
        # COMMIT stays asynchronous until the drain barrier
        gathered.copy.result()
    return gathered
