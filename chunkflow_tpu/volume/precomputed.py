"""Neuroglancer-precomputed volume storage on tensorstore.

Parity target: reference volume.py PrecomputedVolume (:41-209) — a zyx
C-order facade over xyz F-order precomputed storage, with mip levels,
existence checks for skip logic, and auto dtype conversion. The reference
wraps CloudVolume; here the modern equivalent (tensorstore) provides the
storage driver (the reference itself was moving this way,
plugins/load_tensorstore.py), and the off-by-transpose hazard the reference
acknowledges (SURVEY §7 "zyx C-order vs xyz F-order") is confined to this
one module: everything outside sees czyx Chunks.

Storage layout note: chunks aligned to the storage block size never share a
file, so parallel writers cannot conflict — the write-safety contract that
replaces locking (reference docs "block ... ensures no writing conflict").

All I/O rides the storage plane (volume/storage.py, docs/storage.md):
cutouts decompose into storage-block-aligned concurrent reads served
through the shared hot-block LRU, saves take the coalescing write path
(aligned blocks commit as concurrent per-block futures, cache updated
write-through; unaligned writes invalidate), and the sidecar/existence
KV handle is opened once per volume and cached. ``CHUNKFLOW_STORAGE=
serial`` restores the historical single-read path bit-identically.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from chunkflow_tpu.chunk.base import Chunk, LayerType, as_native_dtype
from chunkflow_tpu.core.bbox import BoundingBox
from chunkflow_tpu.core.cartesian import Cartesian, to_cartesian
from chunkflow_tpu.volume.storage import (
    KVBackend,
    TensorStoreBackend,
    blockwise_cutout,
    blockwise_save,
    open_kv,
    serial_cutout,
    shared_cache,
    storage_mode,
)

_LAYER_TO_PRECOMPUTED = {
    LayerType.IMAGE: "image",
    LayerType.AFFINITY_MAP: "image",
    LayerType.PROBABILITY_MAP: "image",
    LayerType.SEGMENTATION: "segmentation",
    LayerType.UNKNOWN: "image",
}


def _kvstore_spec(path: str) -> dict:
    if path.startswith("file://"):
        return {"driver": "file", "path": path[len("file://"):]}
    if path.startswith("gs://"):
        bucket, _, rest = path[len("gs://"):].partition("/")
        return {"driver": "gcs", "bucket": bucket, "path": rest}
    if path.startswith("s3://"):
        bucket, _, rest = path[len("s3://"):].partition("/")
        return {"driver": "s3", "bucket": bucket, "path": rest}
    # bare filesystem path
    return {"driver": "file", "path": path}


def _local_root(path: str) -> Optional[str]:
    spec = _kvstore_spec(path)
    return spec["path"] if spec["driver"] == "file" else None


class PrecomputedVolume:
    """One precomputed layer (all mips), czyx semantics."""

    def __init__(self, path: str):
        self.path = path
        self.kvstore = _kvstore_spec(path)
        self._stores = {}
        self._backends = {}
        self._kv: Optional[KVBackend] = None
        self._info = None

    # ------------------------------------------------------------------
    @property
    def kv(self) -> KVBackend:
        """The volume root's sidecar/existence plane — ONE handle,
        opened lazily and cached alongside ``_stores`` (never re-opened
        per info/read_json/has_all_blocks call)."""
        if self._kv is None:
            self._kv = open_kv(self.kvstore)
        return self._kv

    @property
    def info(self) -> dict:
        if self._info is None:
            data = self.kv.read_bytes("info")
            if data is None:
                raise FileNotFoundError(f"no info file under {self.path}")
            self._info = json.loads(data)
        return self._info

    def read_json(self, name: str):
        """Read a JSON sidecar file from the volume root (e.g.
        blackout_section_ids.json); None if absent."""
        data = self.kv.read_bytes(name)
        if not data:
            return None
        return json.loads(data)

    @property
    def num_mips(self) -> int:
        return len(self.info["scales"])

    @property
    def num_channels(self) -> int:
        return self.info["num_channels"]

    @property
    def dtype(self) -> np.dtype:
        return np.dtype(self.info["data_type"])

    @property
    def layer_type(self) -> LayerType:
        return (
            LayerType.SEGMENTATION
            if self.info["type"] == "segmentation"
            else LayerType.IMAGE
        )

    def scale(self, mip: int) -> dict:
        return self.info["scales"][mip]

    def voxel_size(self, mip: int = 0) -> Cartesian:
        # precomputed resolution is xyz; we are zyx
        return Cartesian(*reversed(self.scale(mip)["resolution"]))

    def voxel_offset(self, mip: int = 0) -> Cartesian:
        return Cartesian(*reversed(self.scale(mip).get("voxel_offset", (0, 0, 0))))

    def volume_size(self, mip: int = 0) -> Cartesian:
        return Cartesian(*reversed(self.scale(mip)["size"]))

    def block_size(self, mip: int = 0) -> Cartesian:
        return Cartesian(*reversed(self.scale(mip)["chunk_sizes"][0]))

    def bounds(self, mip: int = 0) -> BoundingBox:
        start = self.voxel_offset(mip)
        return BoundingBox(start, start + self.volume_size(mip))

    # ------------------------------------------------------------------
    def _store(self, mip: int):
        if mip not in self._stores:
            import tensorstore as ts

            self._stores[mip] = ts.open(
                {
                    "driver": "neuroglancer_precomputed",
                    "kvstore": self.kvstore,
                    "scale_index": mip,
                }
            ).result()
        return self._stores[mip]

    def _backend(self, mip: int) -> TensorStoreBackend:
        """The storage-plane view of one mip's dataset (xyzc index
        space, block grid anchored at the scale's voxel offset),
        cached alongside ``_stores``."""
        if mip not in self._backends:
            block = self.block_size(mip)
            offset = self.voxel_offset(mip)
            self._backends[mip] = TensorStoreBackend(
                self._store(mip),
                token=f"{self.path}|mip{mip}",
                block_shape=(block.x, block.y, block.z,
                             self.num_channels),
                grid_offset=(offset.x, offset.y, offset.z, 0),
            )
        return self._backends[mip]

    def _xyzc_bounds(self, bbox: BoundingBox) -> Tuple[tuple, tuple]:
        """zyx bbox -> (lo, hi) in the store's xyzc index space."""
        s, e = bbox.start, bbox.stop
        return (s.x, s.y, s.z, 0), (e.x, e.y, e.z, self.num_channels)

    def cutout(
        self,
        bbox: BoundingBox,
        mip: int = 0,
        fill_missing: bool = True,
    ) -> Chunk:
        """Read a czyx chunk in global voxel coordinates at ``mip``.

        tensorstore reads absent storage blocks as zeros (the reference's
        fill_missing=True semantics); pass ``fill_missing=False`` to instead
        raise when any covering block is absent (strict mode).

        The read is block-decomposed: storage-block-aligned sub-reads
        issued as concurrent futures through the shared hot-block LRU
        (volume/storage.py) and assembled host-side — bit-identical to
        the historical single blocking read (``CHUNKFLOW_STORAGE=
        serial`` restores it exactly).
        """
        if not fill_missing and not self.has_all_blocks(bbox, mip=mip):
            raise FileNotFoundError(
                f"missing storage blocks under {self.path} for {bbox} "
                f"at mip {mip} (strict read)"
            )
        backend = self._backend(mip)
        lo, hi = self._xyzc_bounds(bbox)
        if storage_mode() == "serial":
            arr = serial_cutout(backend, lo, hi)
        else:
            arr = blockwise_cutout(backend, lo, hi, cache=shared_cache())
        # xyzc -> czyx
        arr = np.ascontiguousarray(np.transpose(arr, (3, 2, 1, 0)))
        if arr.shape[0] == 1:
            arr = arr[0]
        return Chunk(
            arr,
            voxel_offset=bbox.start,
            voxel_size=self.voxel_size(mip),
            layer_type=self.layer_type,
        )

    def save(self, chunk: Chunk, mip: int = 0, wait: bool = True):
        """Write a chunk at its global offset (czyx -> xyzc).

        Dtype auto-conversion follows the reference
        (save_precomputed.py:84-102): uint8 chunk -> float volume divides
        by 255; float chunk -> uint8 volume multiplies by 255 (truncating
        astype), so [0,1] probability/affinity maps land as full-range
        greyscale instead of silently collapsing to {0, 1}.

        With ``wait=False`` the blocking commit is skipped and the
        write future is returned — the caller OWNS the barrier (the CLI
        drains futures before the task ack so the
        ack-after-durable-write protocol holds; see
        runtime.drain_pending_writes).

        The write rides the coalescing path (volume/storage.py):
        block-aligned saves commit as concurrent per-block futures (no
        read-modify-write) and update the hot-block cache write-through;
        unaligned saves fall back to one driver write and invalidate the
        covered blocks — read-after-write through the cache returns the
        written bytes either way.
        """
        arr = as_native_dtype(np.asarray(chunk.array))
        if arr.ndim == 3:
            arr = arr[None]
        vol_dtype = np.dtype(self.dtype)
        if np.issubdtype(vol_dtype, np.floating) and arr.dtype == np.uint8:
            arr = arr.astype(vol_dtype) / np.array(255, vol_dtype)
        elif vol_dtype == np.uint8 and arr.dtype.kind == "f":
            # clip before scaling: float data outside [0,1] (e.g. raw
            # 0-255 intensities stored as float) would wrap on the
            # truncating astype below. The reference has the same latent
            # bug (its `chunk.max() <= 1.` range check is a no-op
            # expression, save_precomputed.py:88-92); clipping matches
            # normalize_blend's uint8 quantization.
            arr = np.clip(arr, 0.0, 1.0) * 255.0
        arr = arr.astype(self.dtype, copy=False)
        arr_xyzc = np.transpose(arr, (3, 2, 1, 0))  # czyx -> xyzc
        lo, _hi = self._xyzc_bounds(chunk.bbox)
        # blockwise_save awaits the COPY legs itself under wait=False
        # (tensorstore may alias chunk.array when no conversion was
        # needed), so callers may freely reuse/mutate the chunk; only
        # the storage COMMIT stays asynchronous until the drain barrier
        return blockwise_save(
            self._backend(mip), lo, arr_xyzc,
            cache=shared_cache(), wait=wait,
        )

    # ------------------------------------------------------------------
    def block_names(self, bbox: BoundingBox, mip: int = 0) -> List[str]:
        """Storage object names of the blocks covering ``bbox``."""
        scale = self.scale(mip)
        key = scale["key"]
        block = self.block_size(mip)
        offset = self.voxel_offset(mip)
        size = self.volume_size(mip)
        snapped = bbox.snap_to_blocks(block, offset=offset, outward=True)
        names = []
        for blk in snapped.decompose(block):
            # clamp the last blocks to the volume bounds like the storage does
            clamped = blk.clamp(self.bounds(mip))
            if not clamped.is_valid():
                continue
            s, e = clamped.start, clamped.stop
            names.append(f"{key}/{s.x}-{e.x}_{s.y}-{e.y}_{s.z}-{e.z}")
        return names

    def has_all_blocks(self, bbox: BoundingBox, mip: int = 0) -> bool:
        """Existence check for skip logic (resume support).

        True iff every storage block covering ``bbox`` already exists, so a
        re-submitted task can be skipped (reference volume.py:194-209).
        The check is batched stat-style through the volume's cached KV
        handle (one key listing / one concurrent wave — never a
        full-value download per block; volume/storage.py).
        """
        names = self.block_names(bbox, mip)
        return all(self.kv.exists_many(names).values())

    # ------------------------------------------------------------------
    @classmethod
    def create(
        cls,
        path: str,
        volume_size,          # zyx at mip 0
        voxel_size,           # zyx nm at mip 0
        voxel_offset=(0, 0, 0),
        num_channels: int = 1,
        dtype="uint8",
        layer_type: str = "image",
        block_size=(64, 64, 64),   # zyx
        num_mips: int = 1,
        downsample_factor=(1, 2, 2),  # zyx per mip
        encoding: str = "raw",
    ) -> "PrecomputedVolume":
        """Create the info file with a mip pyramid (create_new_info parity)."""
        volume_size = to_cartesian(volume_size)
        voxel_size = to_cartesian(voxel_size)
        voxel_offset = to_cartesian(voxel_offset)
        block = to_cartesian(block_size)
        factor = to_cartesian(downsample_factor)

        scales = []
        size = volume_size
        res = voxel_size
        offset = voxel_offset
        for _ in range(num_mips):
            key = f"{res.x}_{res.y}_{res.z}"
            scales.append(
                {
                    "key": key,
                    "size": [size.x, size.y, size.z],
                    "resolution": [res.x, res.y, res.z],
                    "voxel_offset": [offset.x, offset.y, offset.z],
                    "chunk_sizes": [[block.x, block.y, block.z]],
                    "encoding": encoding,
                }
            )
            size = size.ceildiv(factor)
            offset = offset // factor
            res = res * factor

        info = {
            "type": layer_type,
            "data_type": str(np.dtype(dtype)),
            "num_channels": num_channels,
            "scales": scales,
        }
        local = _local_root(path)
        if local is not None:
            os.makedirs(local, exist_ok=True)
        vol = cls(path)
        vol.kv.write_bytes("info", json.dumps(info).encode())
        vol._info = info
        # a recreated volume must not serve a predecessor's hot blocks
        cache = shared_cache()
        if cache is not None:
            for mip in range(num_mips):
                cache.invalidate_token(f"{path}|mip{mip}")
        return vol

    # ---- reference-spelling compatibility surface ----------------------
    @property
    def bounding_box(self) -> BoundingBox:
        """Reference spelling of bounds() at the default mip."""
        return self.bounds(0)

    @property
    def bbox(self) -> BoundingBox:
        return self.bounding_box

    @property
    def start(self) -> Cartesian:
        return self.voxel_offset(0)

    @property
    def stop(self) -> Cartesian:
        return self.bounds(0).stop

    @property
    def shape(self) -> tuple:
        # reference volume.py:137 includes the channel dim: (c, z, y, x)
        return (self.num_channels,) + tuple(self.volume_size(0))

    @property
    def block_bounding_boxes(self):
        """Non-overlapping storage-block boxes tiling the volume."""
        return self.bounds(0).decompose_to_unaligned_block_bounding_boxes(
            self.block_size(0)
        )

    @property
    def physical_bounding_box(self):
        from chunkflow_tpu.core.bbox import PhysicalBoundingBox

        b = self.bounds(0)
        return PhysicalBoundingBox(b.start, b.stop, self.voxel_size(0))

    @classmethod
    def from_numpy(cls, arr, vol_path: str, **kwargs) -> "PrecomputedVolume":
        """Reference CloudVolume.from_numpy analog (zyx array in, volume
        out)."""
        return cls.from_chunk(Chunk(arr), vol_path, **kwargs)

    @classmethod
    def from_chunk(cls, chunk: Chunk, path: str, **kwargs) -> "PrecomputedVolume":
        """Create a volume sized/typed like ``chunk`` and write it (test
        fixture helper, analog of CloudVolume.from_numpy)."""
        vol = cls.create(
            path,
            volume_size=chunk.shape[-3:],
            voxel_size=chunk.voxel_size,
            voxel_offset=chunk.voxel_offset,
            num_channels=chunk.nchannels,
            dtype=chunk.dtype,
            layer_type=_LAYER_TO_PRECOMPUTED[chunk.layer_type],
            **kwargs,
        )
        vol.save(chunk, mip=0)
        return vol


def load_chunk_or_volume(path: str, mip: int = 0, bbox: Optional[BoundingBox] = None):
    """Open a storage path: h5/tif/npy files load as Chunks, directories as
    PrecomputedVolume (cut out ``bbox`` if given). Reference volume.py:217."""
    if path.endswith(".h5"):
        return Chunk.from_h5(path, bbox=bbox)
    if path.endswith((".tif", ".tiff")):
        return Chunk.from_tif(path)
    if path.endswith(".npy"):
        return Chunk.from_npy(path)
    vol = PrecomputedVolume(path)
    if bbox is not None:
        return vol.cutout(bbox, mip=mip)
    return vol
