"""Multi-page TIFF I/O via Pillow (tifffile is not in the image).

Parity: reference chunk/base.py from_tif/to_tif (:208-264). z-sections map
to TIFF pages.
"""
from __future__ import annotations

import numpy as np
from PIL import Image as PILImage


_COMPRESSION = {
    None: None, "raw": None, "none": None,
    "zlib": "tiff_deflate", "deflate": "tiff_deflate",
    "lzw": "tiff_lzw", "packbits": "packbits",
}


def write_tif(chunk, path: str, compression: str = "zlib") -> str:
    from chunkflow_tpu.chunk.base import as_native_dtype

    arr = as_native_dtype(np.asarray(chunk.array))
    if arr.ndim == 4:
        if arr.shape[0] != 1:
            raise ValueError("TIFF export supports single-channel chunks only")
        arr = arr[0]
    pages = [PILImage.fromarray(section) for section in arr]
    comp = _COMPRESSION.get(compression, compression)
    kwargs = {"compression": comp} if comp else {}
    pages[0].save(path, save_all=True, append_images=pages[1:], **kwargs)
    return path


def read_tif(path: str, voxel_offset=None, voxel_size=None, dtype=None):
    from chunkflow_tpu.chunk.base import Chunk

    img = PILImage.open(path)
    sections = []
    try:
        while True:
            sections.append(np.asarray(img))
            img.seek(img.tell() + 1)
    except EOFError:
        pass
    arr = np.stack(sections, axis=0)
    if dtype is not None:
        arr = arr.astype(dtype)
    return Chunk(arr, voxel_offset=voxel_offset, voxel_size=voxel_size)
