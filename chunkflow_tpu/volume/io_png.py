"""PNG section stack I/O via Pillow (pyspng equivalent surface).

Parity: reference flow/save_pngs.py (z-section export) and
flow/load_pngs.py (stack -> chunk with bbox windowing).

Codec speed (measured 2026-07-29, worst-case random 2048^2 uint8):
decode ~210 MB/s; encode is zlib-bound, so sections are written at
compress_level=1 (fastest; higher levels buy little on EM noise). PNG
export is an offline convenience path, not on the inference hot path.
"""
from __future__ import annotations

import os
import re
from typing import Optional

import numpy as np
from PIL import Image as PILImage

from chunkflow_tpu.core.bbox import BoundingBox


def save_pngs(chunk, output_path: str, name_prefix: str = "") -> None:
    os.makedirs(output_path, exist_ok=True)
    from chunkflow_tpu.chunk.base import as_native_dtype

    arr = as_native_dtype(np.asarray(chunk.array))
    if arr.ndim == 4:
        if getattr(chunk, "is_affinity_map", False) and arr.shape[0] == 3:
            # reference semantics (save_pngs.py:33-38): yx-affinity mean as
            # uint8 greyscale. Float affinities are [0,1] and scale by 255;
            # already-quantized integer affinities average in a wide type
            # (uint8 a+b would wrap) without rescaling.
            if arr.dtype.kind == "f":
                mean = (arr[1] + arr[2]) / 2.0
                arr = (np.clip(mean, 0.0, 1.0) * 255.0).astype(np.uint8)
            elif arr.dtype == np.uint8:
                arr = (
                    (arr[1].astype(np.uint16) + arr[2]) // 2
                ).astype(np.uint8)
            else:
                raise ValueError(
                    f"affinity PNG export supports float or uint8 "
                    f"channels, got {arr.dtype}"
                )
        elif arr.shape[0] != 1:
            raise ValueError("PNG export needs a single-channel chunk")
        else:
            arr = arr[0]
    if arr.dtype.kind == "f":
        # PNG has no float mode; [0,1] float sections (probability /
        # affinity convention) export as greyscale. Out-of-range floats
        # stay fail-loud: silently clipping z-scored or 0-255 data would
        # write saturated images.
        lo, hi = float(arr.min()), float(arr.max())
        if lo < -1e-3 or hi > 1.0 + 1e-3:
            raise ValueError(
                f"float PNG export expects [0,1] data, got [{lo:.3g}, "
                f"{hi:.3g}]; rescale (e.g. normalize-intensity) or cast "
                "to uint8 first"
            )
        arr = (np.clip(arr, 0.0, 1.0) * 255.0).astype(np.uint8)
    z0 = chunk.voxel_offset.z
    for i, section in enumerate(arr):
        PILImage.fromarray(section).save(
            os.path.join(output_path, f"{name_prefix}{z0 + i:05d}.png"),
            compress_level=1,
        )


def load_pngs(
    path: str,
    bbox: Optional[BoundingBox] = None,
    voxel_offset=(0, 0, 0),
    dtype=None,
):
    """Load a directory of z-section PNGs (sorted by the number in the
    filename) into a chunk, optionally windowed by ``bbox``."""
    from chunkflow_tpu.chunk.base import Chunk

    def section_index(name: str) -> int:
        nums = re.findall(r"\d+", name)
        return int(nums[-1]) if nums else 0

    files = sorted(
        (f for f in os.listdir(path) if f.lower().endswith(".png")),
        key=section_index,
    )
    if not files:
        raise FileNotFoundError(f"no .png files in {path}")
    sections = [np.asarray(PILImage.open(os.path.join(path, f))) for f in files]
    arr = np.stack(sections, axis=0)
    if dtype is not None:
        arr = arr.astype(dtype)
    chunk = Chunk(arr, voxel_offset=voxel_offset)
    if bbox is not None:
        chunk = chunk.cutout(bbox)
    return chunk
