"""chunkflow-tpu: TPU-native chunk-wise 3D image processing framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
seung-lab/chunkflow (reference: /root/reference): decompose petascale 3D
volumes into overlapping chunk tasks, distribute them through a queue, and on
each worker run a composable pipeline of operators whose hot path — patch-wise
convnet inference with bump-weighted overlap blending — is a single
jit-compiled XLA program resident in TPU HBM.
"""

__version__ = "0.1.0"

from chunkflow_tpu.core.cartesian import Cartesian
from chunkflow_tpu.core.bbox import BoundingBox, BoundingBoxes
from chunkflow_tpu.chunk.base import Chunk

__all__ = [
    "Cartesian",
    "BoundingBox",
    "BoundingBoxes",
    "Chunk",
    "__version__",
]
