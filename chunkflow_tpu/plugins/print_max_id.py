"""Print the maximum segment id (reference plugins/print_max_id.py)."""
import numpy as np


def execute(chunk):
    print(f"max id: {int(chunk.array.max())}")
