"""Merge per-chunk skeleton fragments into one skeleton per object
(reference plugins/aggregate_skeleton_fragments.py).

Fragments are files named ``<obj_id>:<bbox>`` in precomputed skeleton
format; aggregation concatenates nodes/edges (connecting fragment roots to
the nearest node of the accumulated skeleton) and writes ``<obj_id>``.
"""
import os

import numpy as np

from chunkflow_tpu.annotations.skeleton import Skeleton


def execute(fragment_dir: str, output_dir: str = None, id_prefix: str = None):
    output_dir = output_dir or fragment_dir
    by_id = {}
    for name in os.listdir(fragment_dir):
        if ":" not in name:
            continue
        obj_id = name.split(":")[0]
        if id_prefix and not obj_id.startswith(id_prefix):
            continue
        by_id.setdefault(obj_id, []).append(name)

    os.makedirs(output_dir, exist_ok=True)
    for obj_id, names in by_id.items():
        merged = None
        for name in sorted(names):
            with open(os.path.join(fragment_dir, name), "rb") as f:
                frag = Skeleton.from_precomputed_bytes(f.read())
            if merged is None:
                merged = frag
                continue
            base = len(merged)
            parents = frag.parents.copy()
            remapped = np.where(parents >= 0, parents + base, -1)
            # connect the fragment's root(s) to the nearest merged node
            for root_local in np.nonzero(frag.parents == -1)[0]:
                dists = np.linalg.norm(
                    merged.nodes - frag.nodes[root_local], axis=1
                )
                remapped[root_local] = int(np.argmin(dists))
            merged = Skeleton(
                np.concatenate([merged.nodes, frag.nodes]),
                np.concatenate([merged.parents, remapped]),
                radii=np.concatenate([merged.radii, frag.radii]),
            )
        with open(os.path.join(output_dir, obj_id), "wb") as f:
            f.write(merged.to_precomputed_bytes())
    print(f"aggregated skeletons for {len(by_id)} objects")
    return len(by_id)
