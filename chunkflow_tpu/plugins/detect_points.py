"""Peak detection plugin: probability map -> point list
(reference plugins/detect_points.py)."""
from chunkflow_tpu.chunk import ProbabilityMap


def execute(chunk, min_distance: int = 15, threshold_rel: float = 0.3):
    pm = ProbabilityMap.from_chunk(chunk)
    points, confidences = pm.detect_points(
        min_distance=min_distance, threshold_rel=threshold_rel
    )
    print(f"detected {points.shape[0]} points")
    return points
