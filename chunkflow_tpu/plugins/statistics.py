"""Print chunk statistics plugin (reference plugins/statistics.py)."""
import numpy as np


def execute(chunk):
    arr = np.asarray(chunk.array)
    print(
        f"chunk {chunk.bbox.string}: dtype={arr.dtype} "
        f"min={arr.min()} max={arr.max()} mean={arr.mean():.4f} "
        f"nonzero={np.count_nonzero(arr)}/{arr.size}"
    )
