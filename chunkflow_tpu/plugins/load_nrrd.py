"""Load an NRRD file as a chunk (reference plugins/load_nrrd.py, pynrrd-free)."""
from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.volume.io_nrrd import load_nrrd


def execute(file_name: str, voxel_offset=None, voxel_size=None):
    array, header = load_nrrd(file_name)
    if voxel_offset is None and "chunkflow voxel offset" in header:
        voxel_offset = tuple(
            int(v) for v in header["chunkflow voxel offset"].split()
        )
    return Chunk(array, voxel_offset=voxel_offset, voxel_size=voxel_size)
