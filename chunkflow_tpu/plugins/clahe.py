"""Per-section CLAHE contrast enhancement (reference plugins/clahe.py)."""
import numpy as np


def execute(chunk, clip_limit: float = 2.0, tile_size: int = 8):
    import cv2

    arr = np.asarray(chunk.array)
    if arr.dtype != np.uint8:
        raise ValueError("CLAHE needs a uint8 image chunk")
    clahe = cv2.createCLAHE(
        clipLimit=clip_limit, tileGridSize=(tile_size, tile_size)
    )
    out = np.stack([clahe.apply(section) for section in arr], axis=0)
    return out
