"""Percentile contrast stretch plugin (reference plugins/stretch_intensity.py)."""
import numpy as np


def execute(chunk, low_percentile: float = 1.0, high_percentile: float = 99.0):
    arr = np.asarray(chunk.array).astype(np.float32)
    lo = np.percentile(arr, low_percentile)
    hi = np.percentile(arr, high_percentile)
    dtype = chunk.dtype
    out_max = np.iinfo(dtype).max if np.dtype(dtype).kind in "iu" else 1.0
    out = np.clip((arr - lo) / max(hi - lo, 1e-6) * out_max, 0, out_max)
    return out.astype(dtype)
