"""Fill interior holes of every object (reference
plugins/fill_segmentation_holes.py)."""
import numpy as np
from scipy import ndimage


def execute(seg):
    arr = np.asarray(seg.array)
    out = arr.copy()
    for obj_id in np.unique(arr):
        if obj_id == 0:
            continue
        mask = arr == obj_id
        filled = ndimage.binary_fill_holes(mask)
        out[np.logical_and(filled, ~mask)] = obj_id
    return out
