"""Post-synapse detection around known T-bars: for each pre site, find
peaks of the post-synapse probability map within a search radius
(reference plugins/synapse/detect_post_synapses.py)."""
import numpy as np

from chunkflow_tpu.annotations.synapses import Synapses
from chunkflow_tpu.chunk import ProbabilityMap


def execute(
    synapses,
    post_prob,
    search_radius: int = 50,
    min_distance: int = 5,
    threshold_rel: float = 0.3,
):
    pm = ProbabilityMap.from_chunk(post_prob)
    peaks, confidences = pm.detect_points(
        min_distance=min_distance, threshold_rel=threshold_rel
    )
    if peaks.shape[0] == 0:
        print("no post-synapse candidates found")
        return synapses

    res = np.asarray(tuple(post_prob.voxel_size), dtype=np.float32)
    post_rows = []
    post_conf = []
    for pre_index in range(synapses.pre_num):
        delta = (peaks - synapses.pre[pre_index]) * res
        close = np.nonzero(np.linalg.norm(delta, axis=1) <= search_radius)[0]
        for peak_index in close:
            post_rows.append(
                (pre_index, *peaks[peak_index].tolist())
            )
            post_conf.append(confidences[peak_index])
    post = (
        np.asarray(post_rows, dtype=np.int32)
        if post_rows
        else None
    )
    print(f"attached {len(post_rows)} post-synapses")
    return Synapses(
        synapses.pre,
        post=post,
        pre_confidence=synapses.pre_confidence,
        post_confidence=np.asarray(post_conf) if post_conf else None,
        resolution=synapses.resolution,
    )
