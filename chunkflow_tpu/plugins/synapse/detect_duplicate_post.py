"""Remove duplicate post-synapses: spatial redundancy + same-neuron
duplicates against a segmentation (reference
plugins/synapse/detect_duplicate_post.py)."""
import numpy as np

from chunkflow_tpu.annotations.synapses import Synapses


def execute(synapses, seg=None, distance_threshold: float = 10.0):
    drop = set(synapses.find_redundant_post(distance_threshold).tolist())
    if seg is not None:
        drop |= set(synapses.find_duplicate_post_on_same_neuron(seg).tolist())
    if not drop:
        print("no duplicate post-synapses")
        return synapses
    keep = np.asarray(
        [i for i in range(synapses.post_num) if i not in drop], dtype=np.int64
    )
    print(f"removed {len(drop)} duplicate post-synapses")
    return Synapses(
        synapses.pre,
        post=synapses.post[keep] if keep.size else None,
        pre_confidence=synapses.pre_confidence,
        post_confidence=(
            synapses.post_confidence[keep]
            if synapses.post_confidence is not None and keep.size
            else None
        ),
        resolution=synapses.resolution,
    )
