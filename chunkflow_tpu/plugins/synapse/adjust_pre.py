"""Snap each T-bar to the local probability maximum within a small window
(reference plugins/synapse/adjust_pre.py)."""
import numpy as np

from chunkflow_tpu.annotations.synapses import Synapses


def execute(synapses, prob, window: int = 3):
    arr = np.asarray(prob.array)
    if arr.ndim == 4:
        arr = arr[0]
    offset = prob.voxel_offset.vec
    shape = np.asarray(arr.shape)
    adjusted = synapses.pre.copy()
    for i, point in enumerate(synapses.pre):
        local = point - offset
        lo = np.maximum(local - window, 0)
        hi = np.minimum(local + window + 1, shape)
        if np.any(lo >= hi):
            continue
        sub = arr[lo[0]:hi[0], lo[1]:hi[1], lo[2]:hi[2]]
        best = np.unravel_index(np.argmax(sub), sub.shape)
        adjusted[i] = lo + np.asarray(best) + offset
    return Synapses(
        adjusted,
        post=synapses.post,
        pre_confidence=synapses.pre_confidence,
        post_confidence=synapses.post_confidence,
        resolution=synapses.resolution,
    )
