"""Look up the segment id under each T-bar
(reference plugins/synapse/find_tbar_object.py)."""
import numpy as np


def execute(synapses, seg):
    arr = np.asarray(seg.array)
    if arr.ndim == 4:
        arr = arr[0]
    offset = seg.voxel_offset.vec
    shape = np.asarray(arr.shape)
    ids = np.zeros(synapses.pre_num, dtype=arr.dtype)
    for i, point in enumerate(synapses.pre):
        local = point - offset
        if np.all(local >= 0) and np.all(local < shape):
            ids[i] = arr[tuple(local)]
    print(f"{np.count_nonzero(ids)}/{ids.size} T-bars on labeled objects")
    return ids
