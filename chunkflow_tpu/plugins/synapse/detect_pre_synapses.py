"""T-bar detection: probability map -> Synapses with pre sites
(reference plugins/synapse/detect_pre_synapses.py)."""
from chunkflow_tpu.annotations.synapses import Synapses
from chunkflow_tpu.chunk import ProbabilityMap


def execute(prob, min_distance: int = 15, threshold_rel: float = 0.3):
    pm = ProbabilityMap.from_chunk(prob)
    points, confidences = pm.detect_points(
        min_distance=min_distance, threshold_rel=threshold_rel
    )
    print(f"detected {points.shape[0]} pre-synapses (T-bars)")
    return Synapses(
        points,
        pre_confidence=confidences,
        resolution=tuple(prob.voxel_size),
    )
