"""Cut a label chunk out of a DVID server (reference plugins/cutout_dvid_label.py).

Requires network access to the DVID endpoint; zero-egress environments get
a clear error at call time instead of import time.
"""
import numpy as np

from chunkflow_tpu.chunk.segmentation import Segmentation


def execute(bbox, server: str = None, uuid: str = None,
            instance: str = "segmentation", supervoxels: bool = False):
    if server is None or uuid is None:
        raise ValueError("cutout_dvid_label needs server=... and uuid=...")
    from urllib.request import urlopen

    size = tuple(s for s in bbox.shape)           # zyx
    offset = tuple(int(s) for s in bbox.start)
    # DVID raw API is xyz-ordered
    url = (
        f"{server}/api/node/{uuid}/{instance}/raw/0_1_2/"
        f"{size[2]}_{size[1]}_{size[0]}/"
        f"{offset[2]}_{offset[1]}_{offset[0]}"
        f"?supervoxels={'true' if supervoxels else 'false'}"
    )
    with urlopen(url) as response:
        blob = response.read()
    array = np.frombuffer(blob, dtype=np.uint64).reshape(size)
    return Segmentation(array.copy(), voxel_offset=bbox.start)
