"""Save a chunk as NRRD (reference plugins/save_nrrd.py, pynrrd-free)."""
import numpy as np

from chunkflow_tpu.volume.io_nrrd import save_nrrd


def execute(chunk, file_name: str = "chunk.nrrd"):
    save_nrrd(
        file_name,
        np.asarray(chunk.array),
        voxel_size=tuple(chunk.voxel_size),
        voxel_offset=tuple(chunk.voxel_offset),
    )
    print(f"saved chunk to {file_name}")
