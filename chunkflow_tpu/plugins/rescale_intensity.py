"""Linear intensity rescale into the dtype's full range
(reference plugins/rescale_intensity.py)."""
import numpy as np


def execute(chunk, low: float = None, high: float = None):
    arr = np.asarray(chunk.array).astype(np.float32)
    lo = float(arr.min()) if low is None else low
    hi = float(arr.max()) if high is None else high
    dtype = chunk.dtype
    if np.dtype(dtype).kind in "iu":
        out_max = np.iinfo(dtype).max
    else:
        out_max = 1.0
    scale = out_max / max(hi - lo, 1e-6)
    out = np.clip((arr - lo) * scale, 0, out_max)
    return out.astype(dtype)
