"""Pixel classification via a napari-assistant/apoc classifier file
(reference plugins/napari_pixel_classifier.py). Requires the optional
``apoc`` package; errors clearly when absent."""


def execute(chunk, classifier_path: str = None):
    try:
        import apoc
    except ImportError as e:
        raise ImportError(
            "napari_pixel_classifier needs the 'apoc' package, which is not "
            "installed in this environment"
        ) from e
    import numpy as np

    from chunkflow_tpu.chunk.probability_map import ProbabilityMap

    clf = apoc.PixelClassifier(opencl_filename=classifier_path)
    out = np.asarray(clf.predict(image=np.asarray(chunk.array)))
    return ProbabilityMap(
        out.astype(np.float32),
        voxel_offset=chunk.voxel_offset,
        voxel_size=chunk.voxel_size,
    )
