"""Spatial axis reversal plugin (reference plugins/transpose.py)."""


def execute(chunk):
    return chunk.transpose()  # reverse spatial axes: zyx -> xyz
