"""Map intensity range to [0, 1] float32 (reference plugins/mapto01.py)."""
import numpy as np


def execute(chunk):
    arr = np.asarray(chunk.array).astype(np.float32)
    lo, hi = float(arr.min()), float(arr.max())
    if hi > lo:
        arr = (arr - lo) / (hi - lo)
    else:
        arr = np.zeros_like(arr)
    return arr
