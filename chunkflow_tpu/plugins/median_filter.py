"""Per-section median filter plugin (reference plugins/median_filter.py)."""
import numpy as np
from scipy import ndimage


def execute(chunk, size: int = 3, mode: str = "reflect"):
    arr = np.asarray(chunk.array)
    kernel = (1, size, size) if arr.ndim == 3 else (1, 1, size, size)
    return ndimage.median_filter(arr, size=kernel, mode=mode)
