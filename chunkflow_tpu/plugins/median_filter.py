"""Per-section median filter plugin (reference plugins/median_filter.py)."""
import numpy as np
from scipy import ndimage


def execute(chunk, size=3, mode: str = "reflect"):
    arr = np.asarray(chunk.array)
    if isinstance(size, (tuple, list)):
        kernel = tuple(size)
        # pad (y,x) or (z,y,x) kernels on the left to the array rank
        while len(kernel) < arr.ndim:
            kernel = (1,) + kernel
    else:
        kernel = (1, size, size) if arr.ndim == 3 else (1, 1, size, size)
    return ndimage.median_filter(arr, size=kernel, mode=mode)
