"""Intensity inversion plugin (reference plugins/inverse.py)."""
import numpy as np


def execute(chunk):
    arr = np.asarray(chunk.array)
    if np.dtype(arr.dtype).kind in "iu":
        return (np.iinfo(arr.dtype).max - arr).astype(arr.dtype)
    return (arr.max() - arr).astype(arr.dtype)
