"""2D gaussian blur plugin (reference plugins/gaussian_filter.py)."""


def execute(chunk, sigma: float = 1.0):
    return chunk.gaussian_filter_2d(sigma=sigma)
