"""Affinity -> segmentation via native watershed + hierarchical
agglomeration (reference plugins/agglomerate.py, waterz equivalent).

Signature parity with the reference plugin: ``fragments`` (precomputed
fragment segmentation — only the agglomeration phase runs),
``scoring_function`` (waterz template spellings like
``OneMinus<MeanAffinity<RegionGraphType, ScoreValue>>`` or
``OneMinus<QuantileAffinity<RegionGraphType, ScoreValue, 50, false>>``
are parsed down to their aggregator — Mean/Max/Min/QuantileAffinity;
the short spellings ``mean``/``max``/``min``/``quantileN`` also work),
and ``flip_channel`` (the
reference's affinity channel order is x,y,z, so volumes it produced
need the channel axis reversed to this framework's z,y,x convention —
default False because chunks produced HERE are already zyx, where the
reference defaults True for its own xyz volumes).
"""
import numpy as np

from chunkflow_tpu import native
from chunkflow_tpu.chunk import Segmentation


def _parse_scoring(scoring_function: str) -> str:
    import re

    s = scoring_function.strip().lower()
    if s in ("mean", "max", "min") or re.fullmatch(r"quantile\d{1,3}", s):
        return s
    for agg in ("mean", "max", "min"):
        if f"{agg}affinity" in s:
            return agg
    m = re.search(r"quantileaffinity<[^,]+,[^,]+,\s*(\d{1,3})", s)
    if m:
        return f"quantile{m.group(1)}"
    raise ValueError(
        f"unsupported scoring_function {scoring_function!r}: need "
        "mean/max/min/quantileN or a waterz spelling containing "
        "Mean/Max/Min/QuantileAffinity"
    )


def execute(
    affs,
    fragments=None,
    threshold: float = 0.7,
    aff_threshold_low: float = 0.0001,
    aff_threshold_high: float = 0.9999,
    scoring_function: str = "OneMinus<MeanAffinity<RegionGraphType, ScoreValue>>",
    flip_channel: bool = False,
):
    arr = np.asarray(affs.array, dtype=np.float32)
    if arr.ndim != 4 or arr.shape[0] != 3:
        raise ValueError(f"need [3, z, y, x] affinity chunk, got {arr.shape}")
    if flip_channel:
        # reference-produced volumes store channels x,y,z
        arr = np.ascontiguousarray(arr[::-1])
    frags = None
    if fragments is not None:
        frags = np.asarray(
            fragments.array if hasattr(fragments, "array") else fragments
        )
        if frags.ndim == 4 and frags.shape[0] == 1:
            frags = frags[0]
    seg, count = native.watershed_agglomerate(
        arr,
        t_high=aff_threshold_high,
        t_low=aff_threshold_low,
        merge_threshold=threshold,
        scoring=_parse_scoring(scoring_function),
        fragments=frags,
    )
    print(f"agglomerate: {count} segments")
    return Segmentation(
        seg, voxel_offset=affs.voxel_offset, voxel_size=affs.voxel_size
    )
