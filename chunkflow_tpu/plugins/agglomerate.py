"""Affinity -> segmentation via native watershed + mean-affinity
agglomeration (reference plugins/agglomerate.py, waterz equivalent)."""
import numpy as np

from chunkflow_tpu import native
from chunkflow_tpu.chunk import Segmentation


def execute(
    affs,
    threshold: float = 0.7,
    aff_threshold_low: float = 0.0001,
    aff_threshold_high: float = 0.9999,
):
    arr = np.asarray(affs.array, dtype=np.float32)
    if arr.ndim != 4 or arr.shape[0] != 3:
        raise ValueError(f"need [3, z, y, x] affinity chunk, got {arr.shape}")
    seg, count = native.watershed_agglomerate(
        arr,
        t_high=aff_threshold_high,
        t_low=aff_threshold_low,
        merge_threshold=threshold,
    )
    print(f"agglomerate: {count} segments")
    return Segmentation(
        seg, voxel_offset=affs.voxel_offset, voxel_size=affs.voxel_size
    )
