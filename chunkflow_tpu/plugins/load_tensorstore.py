"""Load a chunk from any tensorstore-supported dataset
(reference plugins/load_tensorstore.py), routed through the storage
plane (volume/storage.py, docs/storage.md): the dataset handle is
opened once per process, the cutout decomposes into storage-block-
aligned concurrent reads, and with ``cache`` truthy the blocks ride the
shared hot-chunk LRU — overlapping/halo reads of already-fetched blocks
hit host memory instead of the driver.

args example:
    driver=zarr;kvstore=file:///tmp/store;voxel_size=(40,4,4);cache=1

``cache`` historically sized a per-open tensorstore ``cache_pool``;
it now opts the read into the process-wide shared block LRU
(``CHUNKFLOW_STORAGE_CACHE_MB`` governs the byte budget). The bbox
indexes the dataset's first three dimensions, as before; extra trailing
dimensions are read whole.
"""
from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.volume.storage import (
    blockwise_cutout,
    open_backend_cached,
    serial_cutout,
    shared_cache,
    storage_mode,
)


def parse_kvstore(kvstore):
    """``scheme://path`` shorthand -> tensorstore kvstore spec."""
    if isinstance(kvstore, str) and "://" in kvstore:
        kv_driver, path = kvstore.split("://", 1)
        kv_driver = "file" if kv_driver == "" else kv_driver
        return {"driver": kv_driver, "path": path}
    return kvstore


def execute(bbox, driver: str = "zarr", kvstore: str = None,
            cache: int = None, voxel_size: tuple = None):
    backend = open_backend_cached(
        {"driver": driver, "kvstore": parse_kvstore(kvstore)}
    )
    dlo, dhi = backend.domain
    lo = tuple(bbox.start) + dlo[3:]
    hi = tuple(bbox.stop) + dhi[3:]
    if storage_mode() == "serial":
        array = serial_cutout(backend, lo, hi)
    else:
        array = blockwise_cutout(
            backend, lo, hi, cache=shared_cache() if cache else None
        )
    return Chunk(
        array,
        voxel_offset=bbox.start,
        voxel_size=voxel_size if voxel_size is not None else (1, 1, 1),
    )
