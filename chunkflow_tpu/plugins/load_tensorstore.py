"""Load a chunk from any tensorstore-supported dataset
(reference plugins/load_tensorstore.py).

args example:
    driver=zarr;kvstore=file:///tmp/store;voxel_size=(40,4,4)
"""
from chunkflow_tpu.chunk.base import Chunk


def execute(bbox, driver: str = "zarr", kvstore: str = None,
            cache: int = None, voxel_size: tuple = None):
    import tensorstore as ts

    if isinstance(kvstore, str) and "://" in kvstore:
        kv_driver, path = kvstore.split("://", 1)
        kv_driver = "file" if kv_driver == "" else kv_driver
        kvstore = {"driver": kv_driver, "path": path}
    spec = {"driver": driver, "kvstore": kvstore}
    if cache:
        spec["context"] = {"cache_pool": {"total_bytes_limit": cache}}
        spec["recheck_cached_data"] = "open"
    dataset = ts.open(spec).result()
    array = dataset[bbox.slices].read().result()
    return Chunk(array, voxel_offset=bbox.start, voxel_size=voxel_size)
