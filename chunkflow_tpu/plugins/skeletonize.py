"""TEASAR-style skeletonization plugin (kimimaro equivalent, basic).

Reference: plugins/skeletonize.py (kimimaro.skeletonize -> precomputed
fragments). This implementation is TEASAR-lite per object:

1. distance transform (DBF) of the object mask;
2. root = voxel with maximum DBF;
3. repeatedly run Dijkstra over the object's 26-connected voxel graph with
   the TEASAR penalty weight ``(1 - dbf/max_dbf)^4`` so paths hug the
   medial axis, extract the path to the furthest unvisited voxel, and
   invalidate voxels within ``invalidation_scale * dbf`` of the path;
4. paths join into one tree rooted at the DBF maximum.

Returns {obj_id: Skeleton} with nodes in physical (nm) coordinates. Pass
``output_path=...`` to also write precomputed skeleton fragments.
"""
import os

import numpy as np
from scipy import ndimage, sparse
from scipy.sparse.csgraph import dijkstra

from chunkflow_tpu.annotations.skeleton import Skeleton


def _object_graph(mask, dbf, voxel_size):
    """Sparse 26-connectivity graph over the object's voxels."""
    coords = np.argwhere(mask)
    index = -np.ones(mask.shape, dtype=np.int64)
    index[tuple(coords.T)] = np.arange(coords.shape[0])
    max_dbf = dbf.max()
    penalty = (1.0 - dbf / (max_dbf + 1e-6)) ** 4

    rows, cols, weights = [], [], []
    offsets = [
        (dz, dy, dx)
        for dz in (-1, 0, 1)
        for dy in (-1, 0, 1)
        for dx in (-1, 0, 1)
        if (dz, dy, dx) > (0, 0, 0)
    ]
    vs = np.asarray(voxel_size, dtype=np.float32)
    for off in offsets:
        shifted = coords + off
        valid = np.all(
            (shifted >= 0) & (shifted < np.asarray(mask.shape)), axis=1
        )
        src = coords[valid]
        dst = shifted[valid]
        dst_idx = index[tuple(dst.T)]
        ok = dst_idx >= 0
        src = src[ok]
        dst_idx = dst_idx[ok]
        src_idx = index[tuple(src.T)]
        step = np.linalg.norm(np.asarray(off) * vs)
        w = step * (
            1.0 + 100.0 * (penalty[tuple(src.T)] + penalty[tuple(dst[ok].T)])
        )
        rows.append(src_idx)
        cols.append(dst_idx)
        weights.append(w)
    rows = np.concatenate(rows)
    cols = np.concatenate(cols)
    weights = np.concatenate(weights)
    n = coords.shape[0]
    graph = sparse.coo_matrix(
        (
            np.concatenate([weights, weights]),
            (np.concatenate([rows, cols]), np.concatenate([cols, rows])),
        ),
        shape=(n, n),
    ).tocsr()
    return coords, index, graph


def _skeletonize_object(mask, voxel_size, invalidation_scale=4.0,
                        max_paths=10000):
    dbf = ndimage.distance_transform_edt(mask, sampling=voxel_size)
    coords, index, graph = _object_graph(mask, dbf, voxel_size)
    n = coords.shape[0]
    if n == 0:
        return None
    root = int(np.argmax(dbf[tuple(coords.T)]))

    dist, predecessors = dijkstra(
        graph, indices=root, return_predecessors=True
    )
    visited = np.zeros(n, dtype=bool)
    vs = np.asarray(voxel_size, dtype=np.float32)
    dbf_per_voxel = dbf[tuple(coords.T)]

    nodes = []          # voxel indices into coords
    parents = []        # parallel: parent position in nodes (-1 root)
    node_of_voxel = {}

    def add_node(voxel_idx, parent_node):
        if voxel_idx in node_of_voxel:
            return node_of_voxel[voxel_idx]
        nodes.append(voxel_idx)
        parents.append(parent_node)
        node_of_voxel[voxel_idx] = len(nodes) - 1
        return len(nodes) - 1

    add_node(root, -1)
    visited[root] = True

    from scipy.spatial import cKDTree

    all_phys = coords * vs
    phys_tree = cKDTree(all_phys)

    for _ in range(max_paths):
        finite = np.isfinite(dist) & ~visited
        if not finite.any():
            break
        target = int(np.argmax(np.where(finite, dist, -np.inf)))
        # walk predecessors back to a voxel already on the skeleton tree
        # (NOT merely invalidated: invalidation marks a tube of off-axis
        # voxels that are not nodes, and joining there would misattach the
        # branch); the root is a tree node, so the walk always terminates
        path = []
        v = target
        while v != -9999 and v not in node_of_voxel:
            path.append(v)
            v = int(predecessors[v])
            if v < 0:
                break
        join = v if v >= 0 and v in node_of_voxel else root
        parent_node = node_of_voxel[join]
        for voxel in reversed(path):
            parent_node = add_node(voxel, parent_node)
        # invalidate voxels near the new path (KD-tree ball queries: the
        # naive full-array distance per path voxel is O(len(path) * n))
        path_coords = coords[path] * vs
        radius = invalidation_scale * dbf_per_voxel[path] + 1e-3
        for pc, r in zip(path_coords, radius):
            visited[phys_tree.query_ball_point(pc, r)] = True
        visited[path] = True

    if (np.isfinite(dist) & ~visited).any():
        print(
            f"warning: skeleton truncated at max_paths={max_paths} with "
            "unvisited voxels remaining; pass a larger max_paths"
        )
    skeleton_nodes = coords[nodes] * vs
    return Skeleton(
        skeleton_nodes,
        np.asarray(parents),
        radii=dbf_per_voxel[nodes],
    )


def execute(
    seg,
    voxel_num_threshold: int = 100,
    invalidation_scale: float = 4.0,
    max_paths: int = 10000,
    output_path: str = None,
):
    arr = np.asarray(seg.array)
    if arr.ndim == 4:
        arr = arr[0]
    voxel_size = tuple(seg.voxel_size)
    skeletons = {}
    ids, counts = np.unique(arr, return_counts=True)
    for obj_id, count in zip(ids, counts):
        if obj_id == 0 or count < voxel_num_threshold:
            continue
        skel = _skeletonize_object(
            arr == obj_id, voxel_size,
            invalidation_scale=invalidation_scale,
            max_paths=max_paths,
        )
        if skel is not None and len(skel) > 1:
            # shift into global physical coordinates
            skel.nodes += seg.voxel_offset.vec * np.asarray(voxel_size)
            skeletons[int(obj_id)] = skel
    print(f"skeletonized {len(skeletons)} objects")
    if output_path:
        os.makedirs(output_path, exist_ok=True)
        bbox_str = seg.bbox.string
        for obj_id, skel in skeletons.items():
            with open(
                os.path.join(output_path, f"{obj_id}:{bbox_str}"), "wb"
            ) as f:
                f.write(skel.to_precomputed_bytes())
    return skeletons
