"""Run inference with a Zeiss .czann model (reference plugins/czann_inference.py).
Requires the optional ``czmodel`` package; errors clearly when absent."""


def execute(chunk, model_file: str = None):
    try:
        from czmodel.pytorch.convert import DefaultConverter  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "czann_inference needs the 'czmodel' package, which is not "
            "installed in this environment"
        ) from e
    raise NotImplementedError(
        "czann support requires the czmodel runtime; load the extracted "
        "model with the 'universal' inference engine instead"
    )
