"""Publish per-operator timings as CloudWatch metrics
(reference plugins/aws/cloud_watch.py:26-67). Requires boto3 + credentials."""


def execute(log: dict, name: str = "chunkflow-tpu"):
    try:
        import boto3
    except ImportError as e:
        raise ImportError(
            "cloud_watch needs the 'boto3' package, which is not installed "
            "in this environment"
        ) from e
    client = boto3.client("cloudwatch")
    metric_data = [
        {
            "MetricName": f"{key}-time",
            "Value": float(value),
            "Unit": "Seconds",
        }
        for key, value in log.get("timer", {}).items()
    ]
    if metric_data:
        client.put_metric_data(Namespace=name, MetricData=metric_data)
