"""Publish the telemetry registry snapshot as CloudWatch metrics.

The reference plugin (plugins/aws/cloud_watch.py:26-67) publishes only
the per-task ``log['timer']`` dict — one Seconds metric per operator.
Since PR 3 the process has a far richer registry
(``core/telemetry.py``): fault-tolerance counters, queue receive
counts, depth/occupancy gauges, per-phase stall histograms. This plugin
now publishes that snapshot — the same signal surface ``/metrics``
serves to a Prometheus scraper, shaped for CloudWatch — so the SQS-fed
fleet (the paper's 3600-worker deployment) gets dashboards and alarms
without any scrape infrastructure. Every datum carries a ``worker``
dimension (``telemetry.worker_id()``) so fleet graphs stay
attributable per worker.

Published, namespace ``chunkflow-tpu``:

* counters (``tasks/committed``, ``tasks/retried``, ``queue/receives``,
  ``compile_cache/*``, ``fleet/spawns``/``fleet/evictions``...) as
  Count;
* gauges (``scheduler/depth/*``, ``device/bytes_in_use``...) as None/
  Bytes — the fleet supervisor's sizing gauges (``fleet/workers``,
  ``fleet/target``, ``fleet/pending``, ``fleet/inflight``) as Count, so
  a CloudWatch alarm on fleet size or queue depth gets a sane unit;
* per-phase span totals as Seconds, plus the derived per-phase stall
  shares and the dominant-stall share (``stall/dominant_share``) — the
  autoscaling signal;
* quantile-histogram p50/p99 estimates (``serving/latency-p50`` /
  ``-p99``) as Milliseconds via ``telemetry.quantile_from_buckets`` —
  the latency-alarm substrate, same estimator as ``/metrics`` and
  ``log-summary``;
* the legacy ``log['timer']`` dict (when a task log is passed) exactly
  as before, so existing dashboards keep working.

Requires boto3 + credentials in production; ``client`` injection keeps
the payload shape testable without either.
"""
from typing import List, Optional

from chunkflow_tpu.core import telemetry

DEFAULT_NAMESPACE = "chunkflow-tpu"

#: CloudWatch PutMetricData caps MetricData at 20 entries per call
_BATCH = 20

#: gauges measured in bytes get the proper CloudWatch unit
_BYTE_GAUGES = ("device/bytes_in_use", "device/peak_bytes")

#: gauges that count discrete things (workers, queued tasks): Count,
#: so fleet-size / queue-depth alarms read naturally
_COUNT_GAUGES = ("fleet/workers", "fleet/target", "fleet/pending",
                 "fleet/inflight")


def snapshot_metric_data(snap: Optional[dict] = None,
                         log: Optional[dict] = None) -> List[dict]:
    """The registry snapshot (plus an optional legacy task log) as a
    CloudWatch MetricData list."""
    from chunkflow_tpu.flow.log_summary import STALL_PHASES

    if snap is None:
        snap = telemetry.snapshot()
    dimensions = [{"Name": "worker", "Value": telemetry.worker_id()}]
    data: List[dict] = []

    def add(name: str, value: float, unit: str) -> None:
        data.append({
            "MetricName": name,
            "Value": float(value),
            "Unit": unit,
            "Dimensions": dimensions,
        })

    for name, value in sorted((snap.get("counters") or {}).items()):
        # time-valued counters (program/compile_seconds, PR 8's device
        # program plane) carry a real unit; everything else is a Count
        unit = "Seconds" if name.endswith("_seconds") else "Count"
        add(name, value, unit)
    for name, value in sorted((snap.get("gauges") or {}).items()):
        if name in _BYTE_GAUGES:
            unit = "Bytes"
        elif name in _COUNT_GAUGES:
            unit = "Count"
        else:
            unit = "None"
        add(name, value, unit)
    hists = snap.get("hists") or {}
    for name, h in sorted(hists.items()):
        add(f"{name}-total", h["total"], "Seconds")
    # quantile histograms (serving/latency, PR 9): publish the p50/p99
    # estimates through the one shared estimator so a CloudWatch latency
    # alarm reads the same number /metrics and log-summary report —
    # Milliseconds, the unit CloudWatch latency dashboards expect
    for name, h in sorted((snap.get("qhists") or {}).items()):
        for q, label in ((0.5, "p50"), (0.99, "p99")):
            value = telemetry.quantile_from_buckets(h, q)
            if value is not None:
                add(f"{name}-{label}", value * 1000.0, "Milliseconds")
    totals = {p: hists[p]["total"] for p in STALL_PHASES if p in hists}
    window = sum(totals.values())
    if window > 0:
        for phase, total in totals.items():
            add(f"stall-share/{phase}", total / window, "None")
        dominant = max(totals, key=totals.get)
        add("stall/dominant_share", totals[dominant] / window, "None")
    for key, value in (log or {}).get("timer", {}).items():
        add(f"{key}-time", value, "Seconds")
    return data


def execute(log: Optional[dict] = None, name: str = DEFAULT_NAMESPACE,
            client=None):
    if client is None:
        try:
            import boto3
        except ImportError as e:
            raise ImportError(
                "cloud_watch needs the 'boto3' package, which is not "
                "installed in this environment"
            ) from e
        client = boto3.client("cloudwatch")
    metric_data = snapshot_metric_data(log=log)
    for i in range(0, len(metric_data), _BATCH):
        client.put_metric_data(
            Namespace=name, MetricData=metric_data[i:i + _BATCH]
        )
