"""Load an MRC file as an image chunk (reference plugins/load_mrc.py,
mrcfile-free: native MRC2014 reader)."""
from chunkflow_tpu.chunk.image import Image
from chunkflow_tpu.volume.io_mrc import load_mrc


def execute(file_name: str, voxel_offset=None):
    array, header = load_mrc(file_name)
    return Image(
        array,
        voxel_offset=voxel_offset,
        voxel_size=tuple(max(1, round(s)) for s in header["voxel_size_nm"]),
    )
