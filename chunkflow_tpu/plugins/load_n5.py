"""Load a chunk from an N5 dataset via tensorstore's n5 driver
(reference plugins/load_n5.py used zarr.N5FSStore; tensorstore subsumes
it). Rides the same storage-plane path as load_tensorstore: one cached
dataset handle per process, block-decomposed concurrent reads, shared
hot-block LRU (volume/storage.py, docs/storage.md)."""
from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.volume.storage import (
    blockwise_cutout,
    open_backend_cached,
    serial_cutout,
    shared_cache,
    storage_mode,
)


def execute(bbox, n5_dir: str = None, group_path: str = None,
            voxel_size: tuple = None, cache: int = None):
    backend = open_backend_cached({
        "driver": "n5",
        "kvstore": {"driver": "file", "path": n5_dir},
        "path": group_path or "",
    })
    dlo, dhi = backend.domain
    lo = tuple(bbox.start) + dlo[3:]
    hi = tuple(bbox.stop) + dhi[3:]
    if storage_mode() == "serial":
        array = serial_cutout(backend, lo, hi)
    else:
        array = blockwise_cutout(
            backend, lo, hi, cache=shared_cache() if cache else None
        )
    return Chunk(
        array,
        voxel_offset=bbox.start,
        voxel_size=voxel_size if voxel_size is not None else (1, 1, 1),
    )
