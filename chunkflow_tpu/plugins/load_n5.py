"""Load a chunk from an N5 dataset via tensorstore's n5 driver
(reference plugins/load_n5.py used zarr.N5FSStore; tensorstore subsumes it)."""
from chunkflow_tpu.chunk.base import Chunk


def execute(bbox, n5_dir: str = None, group_path: str = None,
            voxel_size: tuple = None):
    import tensorstore as ts

    dataset = ts.open({
        "driver": "n5",
        "kvstore": {"driver": "file", "path": n5_dir},
        "path": group_path or "",
    }).result()
    array = dataset[bbox.slices].read().result()
    return Chunk(array, voxel_offset=bbox.start, voxel_size=voxel_size)
