"""Cross-task patch packer: fill fixed device batches from ragged traffic.

The per-chunk fused program (inference/inferencer.py) pads every task's
patch list to a multiple of ``batch_size`` with validity-0 entries, so a
task with 3 patches and batch 8 runs the forward pass at 37% occupancy —
and under many small concurrent requests (the ROADMAP's "millions of
users" scenario) the device spends most of its cycles on padding. This
module drains patches from *all* in-flight tasks into one shared queue
and dispatches fixed ``[B, ci, *pin]`` batches that mix patches across
tasks, keeping occupancy near 1 regardless of request shapes — the
Ragged Paged Attention idiom (PAPERS.md) applied to patch grids, with
PipeFusion's observation that the patch, not the chunk, is the natural
scheduling unit.

Bit-identity contract (tested in tests/serve/test_packer.py): packed
outputs equal the per-chunk fused path's outputs **bitwise**. The fused
program is ``gather -> forward*bump*valid -> per-batch scatter-add ->
normalize``; the packer replays the same math as three steps with the
same grouping:

1. *host prep* — the chunk's int->float32 normalization and edge padding
   are IEEE-exact operations, mirrored on the host (conversion and
   padding are value-copies/roundings with identical results on host
   and device); patches are gathered by host slicing (exact);
2. *shared forward program* (``("serve_forward",)`` in the inferencer's
   ProgramCache — ONE trace for all traffic): computes
   ``forward(params, patches) * bump * valid`` for a mixed batch. A real
   patch's row multiplies by valid=1.0 exactly as in the fused program;
   filler rows are discarded;
3. *per-task scatter program* (``("serve_scatter", run_shape)`` — keyed
   by the PR 2 compile-cache shape bucket, so ragged chunks that bucket
   together share one trace; ``("serve_scatter_fused", run_shape, tag)``
   when the fused Pallas kernel is selected, so a CHUNKFLOW_PALLAS flip
   rebuilds rather than reuses): rebuilds the task's ``[n_pad, ...]``
   weighted stack (missing = padding rows are exact zeros, which is
   bitwise what the fused program scatter-adds for validity-0 entries),
   then replays the *same* scan-over-batches accumulation — same
   ``ops.blend.make_accumulate`` step (the weighted flavor: weight-patch
   contributions computed inside the step, in the fused kernel's VMEM
   pass when selected), same batch grouping, same order — and the same
   ``normalize_blend``.

Provenance: every queued patch carries its request and patch index; the
dispatcher writes each forward row back into its request's stack, so a
mixed batch scatters back to the right task's accumulation buffers.

Kill switch: ``CHUNKFLOW_SERVE=0`` — :meth:`PatchPacker.submit` routes
every request through the untouched per-chunk path (``inferencer(...)``),
bit-identically and without building any serve program. Requests that
the packed path does not cover (legacy ``sharding=`` inferencers, fold
blend, dry-run) take the same fallback automatically, loudly counted as
``serving/fallbacks``. Unified-mesh inferencers stay eligible: the
shared forward dispatches through ``engine.serve_forward_program()``,
which builds the data-sharded batch program for ``data=N``/spatial
meshes and — ``CHUNKFLOW_MESH=pipeline=N`` (ISSUE 19) — the micro-batch
stage ring over the engine's stage protocol, with the micro-batch count
derived from the packed batch's shape at trace time so the kill-switch
slot widening re-traces instead of mis-slicing a stale count.

Telemetry (docs/observability.md "Serving"): ``serving/occupancy`` gauge
+ histogram (real patches per dispatched batch slot), ``serving/
queue_age`` histogram, ``serving/patch_queue`` gauge, ``serving/batches``
/ ``serving/packed_patches`` / ``serving/filler_slots`` /
``serving/fallbacks`` counters, ``serving/forward`` / ``serving/scatter``
spans (host-side only, GL007).
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.core import telemetry
from chunkflow_tpu.inference.patching import enumerate_patches, pad_to_batch

__all__ = [
    "serve_enabled", "RequestExpired", "PackerClosed", "PendingResult",
    "PatchPacker",
]

_OFF_VALUES = ("0", "off", "false", "no")


def serve_enabled() -> bool:
    """The serving kill switch (``CHUNKFLOW_SERVE``, default on).
    Re-read per call so tests and long-lived workers can flip it; off
    means every request takes the per-chunk batching path bit-identically
    and no serve program is ever built."""
    return os.environ.get("CHUNKFLOW_SERVE", "1").lower() not in _OFF_VALUES


class RequestExpired(RuntimeError):
    """The request's deadline passed before its patches completed; its
    remaining queued patches are dropped (``serving/deadline_missed``)."""


class PackerClosed(RuntimeError):
    """The packer was shut down while the request was still queued."""


class PendingResult:
    """One submitted request's completion handle: ``result(timeout)``
    blocks until the output chunk (or the failure) is ready."""

    __slots__ = ("_event", "_result", "_error", "trace_id")

    def __init__(self, trace_id: Optional[str] = None):
        self._event = threading.Event()
        self._result: Optional[Chunk] = None
        self._error: Optional[BaseException] = None
        self.trace_id = trace_id

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def _complete(self, chunk: Chunk) -> None:
        self._result = chunk
        self._event.set()

    def _fail(self, exc: BaseException) -> None:
        if not self._event.is_set():
            self._error = exc
            self._event.set()

    def result(self, timeout: Optional[float] = None) -> Chunk:
        if not self._event.wait(timeout):
            raise TimeoutError("request still in flight")
        if self._error is not None:
            raise self._error
        return self._result


class _Request:
    """Per-request provenance + accumulation state. With the
    device-resident front half (ISSUE 15, the default) the request's
    chunk lives in ``device_chunk`` — uploaded ONCE, raw dtype — and
    ``patches`` stays None; the host front half (``CHUNKFLOW_GATHER=
    off`` or a raw-ineligible dtype) keeps the gathered host ``patches``
    list instead."""

    __slots__ = (
        "chunk", "handle", "deadline", "trace_id", "orig_zyx", "run_zyx",
        "n", "n_pad", "in_starts", "out_starts", "valid", "patches",
        "device_chunk", "weighted", "remaining", "lock", "enqueued_t",
    )

    def __init__(self, chunk, handle, deadline, trace_id):
        self.chunk = chunk
        self.handle = handle
        self.deadline = deadline
        self.trace_id = trace_id
        self.lock = threading.Lock()
        self.enqueued_t = time.time()

    @property
    def expired(self) -> bool:
        return self.deadline is not None and time.time() > self.deadline


def _host_float32(chunk: Chunk) -> np.ndarray:
    """The chunk payload as ``[ci, z, y, x]`` float32 on the host,
    mirroring ``Inferencer._infer``'s on-device normalization bitwise:
    int images scale to [0, 1] by ``1/iinfo.max`` (int->f32 conversion
    is exact, the f32 multiply is the same IEEE operation on host and
    device); float inputs round to f32 with the same IEEE
    round-to-nearest the device conversion applies."""
    arr = np.asarray(chunk.array)
    dt = np.dtype(chunk.dtype)
    if dt.kind in "iu":
        scale = np.float32(1.0 / np.iinfo(dt).max)
        arr = arr.astype(np.float32) * scale
    else:
        arr = np.asarray(arr, dtype=np.float32)
    if arr.ndim == 3:
        arr = arr[None]
    return arr


class PatchPacker:
    """Continuous cross-task patch batching around one
    :class:`~chunkflow_tpu.inference.inferencer.Inferencer`.

    ``submit`` is thread-safe (the serving front-end calls it from HTTP
    handler threads and lifecycle worker threads alike); all device work
    runs on one dispatcher thread, so program build and dispatch never
    race. ``max_wait_ms`` bounds how long a partial batch waits for more
    traffic before dispatching underfull — the latency/occupancy knob.
    """

    def __init__(self, inferencer, max_wait_ms: float = 2.0,
                 max_queue_patches: int = 4096):
        self.inferencer = inferencer
        self.batch_size = int(inferencer.batch_size)
        self.max_wait_s = max(0.0, float(max_wait_ms) / 1e3)
        self.max_queue_patches = int(max_queue_patches)
        self._cv = threading.Condition()
        self._items: deque = deque()  # (request, patch_index, enqueue_t)
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    # -- eligibility ----------------------------------------------------
    def _eligible(self) -> bool:
        """Packed execution covers the scatter path — the serving shape.
        Legacy ``sharding=`` inferencers, fold blend and the kill switch
        fall back to the per-chunk program. A unified mesh
        (``CHUNKFLOW_MESH``, parallel/engine.py) stays eligible: the
        packed forward itself shards across the chips of the slice."""
        inf = self.inferencer
        return (
            serve_enabled()
            and inf.sharding == "none"
            and inf.blend_mode == "scatter"
            and not inf.dry_run
        )

    def _shard_engine(self):
        """The unified mesh engine behind this inferencer, or None for
        single-device serving. Re-resolved per batch so the
        ``CHUNKFLOW_MESH=1`` kill switch drops serving back to one chip
        mid-stream."""
        getter = getattr(self.inferencer, "shard_engine", None)
        return getter() if getter is not None else None

    def _slots(self) -> int:
        """Patch slots per dispatched device batch: the per-chip batch
        times the chips of the mesh — a pod-slice serving plane packs
        ``n_chips`` times more traffic per dispatch at the same per-chip
        occupancy accounting."""
        engine = self._shard_engine()
        chips = engine.spec.n_devices if engine is not None else 1
        return self.batch_size * chips

    # -- submission -----------------------------------------------------
    def submit(self, chunk: Chunk, deadline: Optional[float] = None,
               trace_id: Optional[str] = None) -> PendingResult:
        """Queue one request's patches for packed execution; returns a
        :class:`PendingResult`. ``deadline`` is an absolute ``time.time``
        deadline: patches still queued past it are dropped and the
        request fails with :class:`RequestExpired`. Ineligible requests
        (kill switch, sharded, fold, dry-run) complete synchronously
        through the per-chunk path, bit-identically."""
        handle = PendingResult(trace_id)
        if not self._eligible():
            telemetry.inc("serving/fallbacks")
            try:
                handle._complete(self.inferencer(chunk))
            except BaseException as exc:
                handle._fail(exc)
            return handle
        if chunk.all_zero():
            # same blank fast path the per-chunk program takes
            try:
                handle._complete(self.inferencer._blank_output(chunk))
            except BaseException as exc:
                handle._fail(exc)
            return handle

        req = _Request(chunk, handle, deadline, trace_id)
        try:
            self._prepare(req)
        except BaseException as exc:
            handle._fail(exc)
            return handle
        with self._cv:
            if self._stop:
                handle._fail(PackerClosed("packer is shut down"))
                return handle
            while (len(self._items) + req.n > self.max_queue_patches
                   and self._items and not self._stop):
                # bounded queue: submission backpressure rather than
                # unbounded host memory under a traffic spike. The
                # `self._items` term keeps the predicate satisfiable: a
                # single request larger than the whole bound is admitted
                # once the queue has drained, instead of waiting on a
                # condition that can never become true (a request with
                # n > max_queue_patches used to hang submit forever)
                self._cv.wait(0.05)
            if self._stop:
                handle._fail(PackerClosed("packer is shut down"))
                return handle
            now = time.time()
            for i in range(req.n):
                self._items.append((req, i, now))
            telemetry.gauge("serving/patch_queue", len(self._items))
            self._ensure_thread()
            self._cv.notify_all()
        return handle

    def infer(self, chunk: Chunk, deadline: Optional[float] = None,
              timeout: Optional[float] = None) -> Chunk:
        """Synchronous convenience wrapper around :meth:`submit`."""
        return self.submit(chunk, deadline=deadline).result(timeout)

    def _prepare(self, req: _Request) -> None:
        """Request prep: bucket padding, grid enumeration, provenance
        bookkeeping — and the chunk's ONE trip to the device.

        Device front half (the default): the chunk uploads ONCE in its
        raw dtype (uint8 ships 1/4 the bytes of the old per-patch f32
        re-uploads), is edge-padded to the bucket shape on device, and
        batches later gather patch rows from it by index
        (:meth:`_gather_program`) — per-chunk H2D drops from
        ~(patch/stride)^3 x to 1x chunk size. The ``CHUNKFLOW_GATHER=
        off`` kill switch (or a raw-ineligible dtype) restores the host
        gather bit-identically: conversion, edge-padding and slicing are
        IEEE-exact value copies that commute, so both fronts hand the
        forward program bitwise-equal batches."""
        import jax.numpy as jnp

        from chunkflow_tpu.core import profiling
        from chunkflow_tpu.ops import pallas_gather

        inf = self.inferencer
        chunk = req.chunk
        req.orig_zyx = tuple(chunk.shape[-3:])
        req.run_zyx = inf._run_shape(req.orig_zyx)
        grid = enumerate_patches(
            req.run_zyx,
            inf.input_patch_size,
            inf.output_patch_size,
            inf.output_patch_overlap,
        )
        in_starts, out_starts, valid = pad_to_batch(grid, self.batch_size)
        req.n = grid.num_patches
        req.n_pad = len(valid)
        req.in_starts = in_starts
        req.out_starts = out_starts
        req.valid = valid
        pin = tuple(inf.input_patch_size)
        pout = tuple(inf.output_patch_size)
        co = inf.num_output_channels
        pad = [(0, 0)] + [
            (0, r - s) for r, s in zip(req.run_zyx, req.orig_zyx)
        ]
        device_front = (
            pallas_gather.gather_mode() != "host"
            and pallas_gather.raw_eligible(chunk.dtype)
        )
        if device_front:
            arr = chunk.array
            if not chunk.is_on_device:
                arr = np.asarray(arr)
                profiling.note_h2d(arr.nbytes, key=("serve_gather",))
            arr = jnp.asarray(arr)  # the request's ONE H2D, raw dtype
            if arr.ndim == 3:
                arr = arr[None]
            if req.run_zyx != req.orig_zyx:
                # same edge-replicate the per-chunk path applies for
                # bucketing — on the raw dtype (pad commutes with the
                # conversion exactly)
                arr = jnp.pad(arr, pad, mode="edge")
            prepare, _ = pallas_gather.make_gather(
                inf.num_input_channels, pin)
            # resident form per leg: f32 once for the XLA gather, raw +
            # alignment pad for the Pallas kernel — applied here so
            # batches don't re-run it per dispatch
            req.device_chunk = prepare(arr)
            req.patches = None
        else:
            arr = _host_float32(chunk)
            if req.run_zyx != req.orig_zyx:
                # same edge-replicate the device path applies for bucketing
                arr = np.pad(arr, pad, mode="edge")
            req.device_chunk = None
            req.patches = [
                arr[:, s[0]:s[0] + pin[0], s[1]:s[1] + pin[1],
                    s[2]:s[2] + pin[2]]
                for s in in_starts[:req.n]
            ]
        # padding rows stay exact zeros: bitwise what the fused program's
        # validity-0 entries contribute to the scatter-add. Under the
        # fused pipeline (ops/blend.fused_pipeline_mode, ISSUE 17) a
        # device-front request keeps this stack DEVICE-resident: forward
        # rows overlay it in place (_overlay_program) and the scatter
        # program consumes it directly, so the weighted stack never
        # crosses the PCIe link between forward and blend. The
        # separate-programs leg's D2H+H2D round trip of the same stack
        # is scored as hbm_intermediate bytes (core/profiling.py).
        from chunkflow_tpu.ops import blend as blend_ops

        if device_front and blend_ops.fused_pipeline_mode() != "off":
            req.weighted = jnp.zeros((req.n_pad, co) + pout,
                                     dtype=jnp.float32)
        else:
            req.weighted = np.zeros((req.n_pad, co) + pout,
                                    dtype=np.float32)
        req.remaining = req.n

    # -- dispatcher -----------------------------------------------------
    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="patch-packer",
            )
            self._thread.start()

    def _next_batch(self):
        """Collect up to ``batch_size`` queued patches; a partial batch
        waits ``max_wait_s`` (from its oldest item) for more traffic
        before dispatching underfull."""
        with self._cv:
            while True:
                if self._items:
                    slots = self._slots()
                    oldest_t = self._items[0][2]
                    if (len(self._items) >= slots or self._stop
                            or time.time() - oldest_t >= self.max_wait_s):
                        batch = [
                            self._items.popleft()
                            for _ in range(min(slots, len(self._items)))
                        ]
                        telemetry.gauge("serving/patch_queue",
                                        len(self._items))
                        self._cv.notify_all()
                        return batch
                    self._cv.wait(
                        max(0.0005,
                            self.max_wait_s - (time.time() - oldest_t)))
                    continue
                if self._stop:
                    return None
                self._cv.wait(0.1)

    def _forward_program(self):
        inf = self.inferencer

        def build():
            import jax
            import jax.numpy as jnp

            from chunkflow_tpu.inference.bump import bump_const

            bump = bump_const(tuple(inf.output_patch_size))

            def program(patches, valid, params):
                preds = inf._forward(params, patches)
                # the same weighting expression, in the same order, as
                # the fused program's forward_batch (ops/blend.py)
                return preds * bump[None, None] * \
                    valid[:, None, None, None, None]

            # the packed batch buffer is packer-owned and dead after the
            # call (GL005): donate it into the program
            return jax.jit(program, donate_argnums=(0,))

        from chunkflow_tpu.ops.blend import pipeline_key

        # the forward math itself is pipeline-independent, but the tag
        # joins anyway (the every-key convention): a flip must never
        # leave ANY serving program keyed as if nothing changed
        return inf._programs.get(("serve_forward",) + pipeline_key(),
                                 build)

    def _gather_program(self):
        """The device-front batch assembler: gathers one packed batch's
        rows for ONE request out of its resident chunk and overlays them
        onto the accumulating batch via exact selection (``jnp.where``
        keeps other requests' rows — and signed zeros — untouched).
        Rows this request does not own carry mask 0 and starts (0,0,0).
        Keyed by the gather selection (``CHUNKFLOW_GATHER`` flips
        rebuild); jit handles chunk-shape/slot-count polymorphism."""
        inf = self.inferencer

        def build():
            import jax
            import jax.numpy as jnp

            from chunkflow_tpu.ops import pallas_gather

            _, gather = pallas_gather.make_gather(
                inf.num_input_channels, tuple(inf.input_patch_size))

            def program(chunk_like, starts, rowmask, acc):
                rows = gather(chunk_like, starts)
                mask = rowmask[:, None, None, None, None]
                return jnp.where(mask > 0, rows, acc)

            # acc is packer-owned and dead after the call (GL005); the
            # resident chunk is NOT donated — later batches gather from it
            return jax.jit(program, donate_argnums=(3,))

        from chunkflow_tpu.ops.blend import pipeline_key
        from chunkflow_tpu.ops.pallas_gather import gather_key

        return inf._programs.get(
            ("serve_gather",) + gather_key() + pipeline_key(), build)

    def _overlay_program(self):
        """The fused-pipeline row writeback: scatters one packed batch's
        forward rows into ONE request's DEVICE-resident weighted stack
        (``weighted.at[idx].set(rows)``), so the stack never rides
        D2H+H2D between the forward and the blend. Rows this request
        does not own carry an out-of-bounds index (the ``n_pad``
        sentinel) and are dropped by the scatter's default FILL_OR_DROP
        mode; owned indices are unique and SET (not added), so every
        row keeps its exact bits — including signed zeros — which is
        what keeps packed fused-pipeline output bitwise equal to the
        round-trip leg. Keyed by the pipeline selection so a
        ``CHUNKFLOW_FUSED_PIPELINE`` flip rebuilds; jit handles
        (n_pad, slots) shape polymorphism."""
        inf = self.inferencer

        def build():
            import jax

            def program(weighted, rows, idx):
                return weighted.at[idx].set(rows)

            # the stack is packer-owned and replaced in place across
            # batches (GL005): donate it into each overlay. ``rows`` is
            # NOT donated — one batch may overlay several requests.
            return jax.jit(program, donate_argnums=(0,))

        from chunkflow_tpu.ops.blend import pipeline_key

        return inf._programs.get(("serve_overlay",) + pipeline_key(),
                                 build)

    def _scatter_program(self, run_zyx, n_pad):
        inf = self.inferencer

        def build():
            import jax
            import jax.numpy as jnp
            from jax import lax

            from chunkflow_tpu.inference.bump import bump_const
            from chunkflow_tpu.ops.blend import (
                make_accumulate,
                normalize_blend,
            )

            pout = tuple(inf.output_patch_size)
            co = inf.num_output_channels
            B = self.batch_size
            bump = bump_const(pout)
            # the weighted flavor: the forward program already applied
            # bump*valid to these rows; the weight-buffer contribution
            # (bump * validity, f32) is computed inside the step — in
            # the fused Pallas kernel's VMEM pass when selected
            _, accumulate_weighted, pad_y, pad_x = make_accumulate(
                pout, bump)
            out_dtype = inf.output_dtype
            zyx_buf = (run_zyx[0], run_zyx[1] + pad_y, run_zyx[2] + pad_x)
            num_batches = n_pad // B

            def program(weighted, valid, out_starts):
                out0 = jnp.zeros((co,) + zyx_buf, dtype=jnp.float32)
                w0 = jnp.zeros(zyx_buf, dtype=jnp.float32)

                def step(carry, b):
                    out, weight = carry
                    i0 = b * B
                    w = lax.dynamic_slice(
                        weighted, (i0, 0, 0, 0, 0), (B, co) + pout)
                    v = lax.dynamic_slice(valid, (i0,), (B,))
                    s_out = lax.dynamic_slice(out_starts, (i0, 0), (B, 3))
                    out, weight = accumulate_weighted(
                        out, weight, w, v, s_out)
                    return (out, weight), None

                (out, weight), _ = lax.scan(
                    step, (out0, w0), jnp.arange(num_batches)
                )
                if pad_y or pad_x:
                    out = out[:, :, : run_zyx[1], : run_zyx[2]]
                    weight = weight[:, : run_zyx[1], : run_zyx[2]]
                return normalize_blend(out, weight, out_dtype)

            # the assembled weighted stack is packer-owned and dead
            # after the call (GL005): donate it
            return jax.jit(program, donate_argnums=(0,))

        from chunkflow_tpu.ops.blend import kernel_tag, pipeline_key

        tag = kernel_tag()
        key = (("serve_scatter", tuple(run_zyx)) if tag == "scatter"
               else ("serve_scatter_fused", tuple(run_zyx), tag))
        return inf._programs.get(key + pipeline_key(), build)

    def _loop(self) -> None:
        while True:
            batch = self._next_batch()
            if batch is None:
                return
            try:
                self._run_batch(batch)
            except BaseException as exc:  # noqa: BLE001 — fail, don't die
                for req, _, _ in batch:
                    req.handle._fail(exc)
                # dispatcher-plane failures get their own counter; the
                # front-end owns the per-request outcome counters
                # (serving/errors, serving/deadline_missed) — one count
                # per request no matter who detected the failure first
                telemetry.inc("serving/packer_errors")

    def _run_batch(self, batch) -> None:
        import jax
        import jax.numpy as jnp

        inf = self.inferencer
        now = time.time()
        live = []
        for item in batch:
            req, _, enq_t = item
            if req.handle.done:
                continue  # already failed/expired: drop its patches
            if req.expired:
                req.handle._fail(RequestExpired(
                    f"deadline passed {now - req.deadline:.3f}s ago with "
                    f"patches still queued"))
                continue
            telemetry.observe("serving/queue_age", now - enq_t)
            live.append(item)
        if not live:
            return
        engine = self._shard_engine()
        chips = engine.spec.n_devices if engine is not None else 1
        slots = self.batch_size * chips
        if len(live) > slots:
            # the batch was collected under a wider mesh than the one in
            # effect now (kill-switch race): widen this dispatch to the
            # next shardable multiple instead of dropping rows
            per = self.batch_size * chips
            slots = -(-len(live) // per) * per
        pin = tuple(inf.input_patch_size)
        ci = inf.num_input_channels
        valid_np = np.zeros((slots,), dtype=np.float32)
        host_rows = []  # (row, req, idx): host-front requests
        dev_rows: dict = {}  # id(req) -> (req, [(row, idx), ...])
        for row, (req, idx, _) in enumerate(live):
            valid_np[row] = 1.0
            if req.patches is not None:
                host_rows.append((row, req, idx))
            else:
                dev_rows.setdefault(id(req), (req, []))[1].append(
                    (row, idx))

        from chunkflow_tpu.core import profiling

        # host-front rows (kill switch / raw-ineligible dtypes) assemble
        # on the host and ride H2D gathered, as before
        batch_np = None
        if host_rows or not dev_rows:
            batch_np = np.zeros((slots, ci) + pin, dtype=np.float32)
            for row, req, idx in host_rows:
                batch_np[row] = req.patches[idx]
        # per-chip occupancy: live patches over every chip's slots — the
        # same gauge the single-chip serving plane feeds, now spanning
        # the slice (docs/multichip.md "The three seams")
        occupancy = len(live) / slots
        telemetry.gauge("serving/occupancy", occupancy)
        telemetry.gauge("serving/chips", float(chips))
        telemetry.inc("serving/batches")
        telemetry.inc("serving/packed_patches", len(live))
        telemetry.inc("serving/filler_slots", slots - len(live))

        if inf._device_params is None:
            inf._device_params = jax.device_put(inf.engine.params)

        # assemble the device batch: host-front rows upload gathered (the
        # pre-ISSUE-15 structure, counted at the staging seam); device-
        # front rows gather out of each request's RESIDENT chunk — no
        # patch bytes cross the PCIe link
        if batch_np is not None and (host_rows or not dev_rows):
            if host_rows:
                profiling.note_h2d(batch_np.nbytes, key=("serve_forward",))
            batch_dev = jnp.asarray(batch_np)
        else:
            batch_dev = jnp.zeros((slots, ci) + pin, dtype=jnp.float32)
        for req, rows in dev_rows.values():
            starts = np.zeros((slots, 3), dtype=np.int32)
            mask = np.zeros((slots,), dtype=np.float32)
            for row, idx in rows:
                starts[row] = req.in_starts[idx]
                mask[row] = 1.0
            gather = self._gather_program()
            batch_dev = gather(
                req.device_chunk, jnp.asarray(starts),
                jnp.asarray(mask), batch_dev,
            )

        program = (engine.serve_forward_program() if engine is not None
                   else self._forward_program())
        host_stack_rows = sum(
            isinstance(req.weighted, np.ndarray) for req, _, _ in live
        )
        with telemetry.span("serving/forward", occupancy=round(occupancy, 3)):
            out = program(
                batch_dev, jnp.asarray(valid_np),
                inf._device_params,
            )
            # the separate-programs leg materializes the forward rows on
            # the host (the inter-stage weighted-stack round trip the
            # fused pipeline deletes); fused-pipeline requests keep
            # everything on device and skip the D2H entirely
            out_np = np.asarray(out) if host_stack_rows else None

        if host_stack_rows:
            row_bytes = int(np.prod(out.shape[1:])) * out.dtype.itemsize
            profiling.note_hbm_intermediate(
                host_stack_rows * row_bytes, key=("serve_forward",))

        # fused-pipeline requests: overlay forward rows onto each
        # request's DEVICE-resident weighted stack in place
        dev_stack: dict = {}
        for row, (req, idx, _) in enumerate(live):
            if not isinstance(req.weighted, np.ndarray):
                dev_stack.setdefault(id(req), (req, []))[1].append(
                    (row, idx))
        for req, pairs in dev_stack.values():
            idx_np = np.full((slots,), req.n_pad, dtype=np.int32)
            for row, idx in pairs:
                idx_np[row] = idx
            overlay = self._overlay_program()
            with req.lock:
                req.weighted = overlay(req.weighted, out,
                                       jnp.asarray(idx_np))

        done = []
        for row, (req, idx, _) in enumerate(live):
            with req.lock:
                if isinstance(req.weighted, np.ndarray):
                    req.weighted[idx] = out_np[row]
                if req.patches is not None:
                    req.patches[idx] = None  # free the gathered input early
                req.remaining -= 1
                if req.remaining == 0:
                    req.device_chunk = None  # release the resident chunk
                    done.append(req)
        for req in done:
            try:
                self._finalize(req)
            except BaseException as exc:  # noqa: BLE001
                req.handle._fail(exc)
                telemetry.inc("serving/packer_errors")

    def _finalize(self, req: _Request) -> None:
        """All of the request's patches are forwarded: replay the fused
        program's scan-over-batches accumulation and hand the result
        through the inferencer's shared post-processing."""
        import jax.numpy as jnp

        if req.expired:
            req.handle._fail(RequestExpired("deadline passed at finalize"))
            return
        program = self._scatter_program(req.run_zyx, req.n_pad)
        if isinstance(req.weighted, np.ndarray):
            # the separate-programs leg re-uploads the stack the forward
            # just downloaded — the second half of the inter-stage round
            # trip the fused pipeline deletes (~0 bytes on that leg)
            from chunkflow_tpu.core import profiling

            profiling.note_hbm_intermediate(
                req.weighted.nbytes, key=("serve_scatter",))
        with telemetry.span("serving/scatter"):
            result = program(
                jnp.asarray(req.weighted), jnp.asarray(req.valid),
                jnp.asarray(req.out_starts),
            )
            result.block_until_ready()
        req.weighted = None
        out = self.inferencer._postprocess_result(
            result, req.chunk, req.orig_zyx, req.run_zyx)
        shape = getattr(getattr(out, "array", None), "shape", None)
        if shape:
            voxels = 1
            for length in shape[-3:]:
                voxels *= int(length)
            telemetry.inc("inference/voxels", float(voxels))
        req.handle._complete(out)

    # -- teardown -------------------------------------------------------
    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the dispatcher. ``drain=True`` (default) lets queued
        patches finish first; ``drain=False`` fails still-queued
        requests with :class:`PackerClosed`."""
        with self._cv:
            if not drain:
                while self._items:
                    req, _, _ = self._items.popleft()
                    req.handle._fail(PackerClosed("packer closed"))
            self._stop = True
            self._cv.notify_all()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=timeout)
