"""The serving front-end: ``POST /infer`` with admission, deadlines, leases.

Grown out of ``parallel/restapi.py``'s stdlib HTTP server: a
:class:`ServingService` subclasses the coordination service, so one
listener serves ``/infer`` next to ``/metrics``, ``/healthz`` and
``/profile``. The request path (docs/serving.md):

    POST /infer ──► admission control ──► request = TASK on a queue
      (max in-flight bound +          (PR 5 lifecycle: lease, retry
       scheduler memory watermark)     budget, exactly-once commit)
          │ 429 on reject                    │
          ▼                                  ▼
      deadline clock            worker claims ──► PatchPacker (packed
          │ 504 on miss          cross-task device batches) ──► commit
          ▼                                  │
      response JSON ◄────────────────────────┘

Two execution backends, one wire protocol:

* :class:`LocalBackend` — worker THREADS in this process claim requests
  from a private ``MemoryQueue`` under a ``LifecycleSupervisor``
  (lease heartbeats, transient-error retries with backoff, dead-letter
  for poison requests, a ``MemoryLedger`` for exactly-once commit) and
  execute through one shared :class:`~chunkflow_tpu.serve.packer.
  PatchPacker`, so concurrent requests' patches share device batches.
* :class:`SpoolBackend` — requests spool to ``<dir>/in/<bbox>.h5`` and a
  ``file://`` queue; any number of EXTERNAL worker processes (the
  standard ``fetch-task-from-queue ... delete-task-in-queue`` chain,
  fleet-supervised or not) complete them; the front-end answers when the
  completion ledger marks the request done. A worker SIGKILLed
  mid-request is recovered by lease expiry exactly as in batch mode —
  the request is redelivered and completes exactly once
  (tests/serve/test_serving_chaos.py).

Backpressure is the PR 4 scheduler's memory watermark
(``CHUNKFLOW_SCHED_MEM_GB``): every admitted request reserves its
estimated working set via :func:`flow.scheduler.reserve_host_bytes`;
when serving load holds reservations, the adaptive depth controller
stops widening pipeline depths too — one watermark, every consumer.

Counters/histograms (docs/observability.md "Serving"): ``serving/
requests|admitted|completed|rejected_admission|rejected_memory|
rejected_duplicate|deadline_missed|errors`` counters, ``serving/
inflight`` gauge, the ``serving/latency`` quantile histogram (p50/p99
in ``log-summary`` and ``fleet-status``), one ``serving/request`` span
and a queue-minted ``trace_id`` per request.
"""
from __future__ import annotations

import base64
import binascii
import json
import os
import threading
import time
import uuid
from typing import Dict, Optional

import numpy as np

from chunkflow_tpu.chunk.base import Chunk
from chunkflow_tpu.core import telemetry
from chunkflow_tpu.parallel.restapi import CoordinationService, serve
from chunkflow_tpu.serve.packer import PatchPacker, RequestExpired
from chunkflow_tpu.testing import chaos

__all__ = [
    "AdmissionRejected", "AdmissionController", "ServingRequest",
    "LocalBackend", "SpoolBackend", "ServingService", "start_serving",
]

#: dtypes accepted on the wire; uint8 is the EM-image fast path (4x
#: fewer bytes than float32 per request, normalized on the way in
#: exactly like the batch path)
_WIRE_DTYPES = ("uint8", "uint16", "float32")


class AdmissionRejected(RuntimeError):
    """Request refused at the door; ``reason`` is one of ``inflight``,
    ``memory``, ``duplicate``, ``draining``."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(detail or reason)
        self.reason = reason


class AdmissionController:
    """The door: a hard in-flight bound plus the scheduler's host-memory
    watermark. Rejections are clean 429s with counters
    (``serving/rejected_admission`` / ``serving/rejected_memory``), not
    worker death — shedding is the contract under overload."""

    #: admitted working-set estimate per request byte: the float32 copy
    #: plus gathered patch stacks plus the weighted output stack, all
    #: transiently host-resident (serve/packer.py)
    MEM_FACTOR = 3.0

    def __init__(self, max_inflight: int = 8):
        self.max_inflight = int(max_inflight)
        self._lock = threading.Lock()
        self._inflight = 0
        self._draining = False

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def drain(self) -> None:
        """Stop admitting (graceful shutdown); in-flight requests finish."""
        with self._lock:
            self._draining = True

    def admit(self, nbytes: int) -> int:
        """Admit a request with an ``nbytes`` float32 working set or
        raise :class:`AdmissionRejected`. Returns the reserved byte
        count to pass back to :meth:`release`."""
        from chunkflow_tpu.flow.scheduler import reserve_host_bytes

        reserve = int(nbytes * self.MEM_FACTOR)
        with self._lock:
            if self._draining:
                telemetry.inc("serving/rejected_admission")
                raise AdmissionRejected("draining", "server is draining")
            if self._inflight >= self.max_inflight:
                telemetry.inc("serving/rejected_admission")
                raise AdmissionRejected(
                    "inflight",
                    f"{self._inflight} requests in flight (max "
                    f"{self.max_inflight})",
                )
            if not reserve_host_bytes(reserve):
                telemetry.inc("serving/rejected_memory")
                raise AdmissionRejected(
                    "memory",
                    "admitting this request would cross the scheduler "
                    "memory watermark (CHUNKFLOW_SCHED_MEM_GB)",
                )
            self._inflight += 1
            inflight = self._inflight
        telemetry.inc("serving/admitted")
        telemetry.gauge("serving/inflight", inflight)
        return reserve

    def release(self, reserved: int) -> None:
        from chunkflow_tpu.flow.scheduler import release_host_bytes

        release_host_bytes(reserved)
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            inflight = self._inflight
        telemetry.gauge("serving/inflight", inflight)


class ServingRequest:
    """One admitted request's state, shared between the HTTP handler
    thread and whichever worker (thread or process) completes it.
    Completion/failure is first-wins and counts each outcome exactly
    once no matter how many parties race to report it."""

    def __init__(self, chunk: Chunk, deadline: float,
                 req_id: Optional[str] = None):
        self.chunk = chunk
        self.deadline = deadline
        self.req_id = req_id or uuid.uuid4().hex
        self.trace_id: Optional[str] = None
        self.submitted_t = time.time()
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._result: Optional[Chunk] = None
        self._error: Optional[BaseException] = None

    @property
    def done(self) -> bool:
        return self._event.is_set()

    @property
    def expired(self) -> bool:
        return time.time() > self.deadline

    def complete(self, result: Chunk) -> bool:
        with self._lock:
            if self._event.is_set():
                return False
            self._result = result
            self._event.set()
        telemetry.inc("serving/completed")
        return True

    def fail(self, exc: BaseException) -> bool:
        with self._lock:
            if self._event.is_set():
                return False
            self._error = exc
            self._event.set()
        if isinstance(exc, RequestExpired):
            telemetry.inc("serving/deadline_missed")
        else:
            telemetry.inc("serving/errors")
        return True

    def wait(self, timeout: Optional[float]) -> Chunk:
        """Block for the outcome; a wait that outlives the deadline
        fails the request with :class:`RequestExpired` (first-wins, so
        a worker finishing a hair later changes nothing)."""
        if not self._event.wait(timeout):
            self.fail(RequestExpired(
                f"request {self.req_id} missed its deadline"))
        self._event.wait()
        if self._error is not None:
            raise self._error
        return self._result


# ---------------------------------------------------------------------------
# local backend: worker threads + MemoryQueue lifecycle
# ---------------------------------------------------------------------------
class LocalBackend:
    """In-process execution: every admitted request is a supervised task
    on a private ``MemoryQueue`` — claimed under a lease, retried with
    backoff on transient errors, dead-lettered past the budget,
    committed exactly once through a ``MemoryLedger`` — and computed
    through ONE shared :class:`PatchPacker`, so concurrent requests'
    patches pack into shared device batches."""

    def __init__(self, inferencer, workers: int = 2, max_retries: int = 2,
                 max_wait_ms: float = 2.0, visibility_timeout: float = 30.0,
                 backoff_base: float = 0.05, backoff_cap: float = 1.0):
        from chunkflow_tpu.parallel.lifecycle import (
            LifecycleSupervisor,
            MemoryLedger,
        )
        from chunkflow_tpu.parallel.queues import MemoryQueue

        name = f"serve-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        self.queue = MemoryQueue.open(name, visibility_timeout)
        # idle workers re-enter the claim loop instead of exiting with it
        self.queue.max_empty_retries = 5
        self.queue.retry_sleep = 0.02
        self.ledger = MemoryLedger.open(name)
        self.packer = PatchPacker(inferencer, max_wait_ms=max_wait_ms)
        self._supervisor_factory = lambda: LifecycleSupervisor(
            self.queue, ledger=self.ledger, max_retries=max_retries,
            lease_renew=max(0.5, visibility_timeout / 3.0),
            backoff_base=backoff_base, backoff_cap=backoff_cap,
        )
        self._table: Dict[str, ServingRequest] = {}
        self._table_lock = threading.Lock()
        self._closed = False
        self._threads = [
            threading.Thread(target=self._work, daemon=True,
                             name=f"serve-worker-{i}")
            for i in range(max(1, int(workers)))
        ]
        for t in self._threads:
            t.start()

    # -- front-end side -------------------------------------------------
    def submit(self, record: ServingRequest) -> None:
        with self._table_lock:
            self._table[record.req_id] = record
        self.queue.send_messages([record.req_id])

    def wait(self, record: ServingRequest, timeout: float) -> Chunk:
        try:
            return record.wait(timeout)
        finally:
            with self._table_lock:
                self._table.pop(record.req_id, None)

    # -- worker side ----------------------------------------------------
    def _work(self) -> None:
        supervisor = self._supervisor_factory()
        while not self._closed:
            # the claim loop ends after a short idle streak (bounded
            # empty polls); re-enter until the backend closes, so an
            # idle server keeps serving
            for lc in supervisor.tasks():
                try:
                    self._run_one(lc)
                except BaseException as exc:  # noqa: BLE001 — charge task
                    try:
                        lc.release(exc)
                    except Exception:
                        pass
                if self._closed:
                    break

    def _run_one(self, lc) -> None:
        with self._table_lock:
            record = self._table.get(lc.body)
        if record is None or record.done:
            # answered/expired/stale request (e.g. committed by a prior
            # attempt a hair before this redelivery): ack and move on
            lc.commit()
            return
        record.trace_id = lc.trace_id
        with telemetry.task_context(lc.trace_id):
            try:
                # fault-injection boundary: a seeded chaos kill here is
                # a transient failure; the lifecycle retries the request
                chaos.chaos_point("serving/compute")
                if record.expired:
                    raise RequestExpired(
                        f"request {record.req_id} expired before compute")
                out = self.packer.infer(
                    record.chunk, deadline=record.deadline,
                    timeout=max(0.05, record.deadline - time.time()) + 5.0,
                )
            except RequestExpired as exc:
                # not a compute failure: drop the claim cleanly (ack —
                # retrying an already-late request burns device time)
                record.fail(exc)
                lc.commit()
                return
            except BaseException as exc:
                outcome = lc.release(exc)
                if outcome in ("dead", "preempted"):
                    record.fail(exc)
                return
            record.complete(out)
            lc.commit()

    def close(self, timeout: float = 10.0) -> None:
        self._closed = True
        self.packer.close(drain=False)
        for t in self._threads:
            t.join(timeout=timeout / max(1, len(self._threads)))
        with self._table_lock:
            for record in self._table.values():
                record.fail(AdmissionRejected("draining", "server closed"))
            self._table.clear()


# ---------------------------------------------------------------------------
# spool backend: file queue + h5 spool, external worker processes
# ---------------------------------------------------------------------------
class SpoolBackend:
    """Cross-process execution: requests spool to ``<dir>/in/<bbox>.h5``
    and a ``file://`` queue; external workers run the standard
    supervised chain::

        chunkflow fetch-task-from-queue -q <dir>/queue \\
            --max-retries N --lease-renew S --ledger <dir>/ledger \\
          load-h5 -f <dir>/in/  inference ... --no-crop-output-margin \\
          save-h5 --file-name <dir>/out/  delete-task-in-queue

    The front-end answers when the completion ledger marks the request's
    bbox done and the output file lands. Workers are preemptible by
    construction: a SIGKILL mid-request surfaces as a lease expiry, the
    queue redelivers, and the ledger keeps the effect exactly-once —
    the PR 5/7 story, now request-shaped. Requests must carry unique
    bboxes (the spool's task identity); a duplicate in-flight bbox is
    rejected up front rather than silently merged."""

    def __init__(self, spool_dir: str, visibility_timeout: float = 30.0,
                 poll_s: float = 0.05):
        from chunkflow_tpu.parallel.lifecycle import FileLedger
        from chunkflow_tpu.parallel.queues import open_queue

        self.dir = spool_dir
        self.in_dir = os.path.join(spool_dir, "in")
        self.out_dir = os.path.join(spool_dir, "out")
        self.queue_dir = os.path.join(spool_dir, "queue")
        self.ledger_dir = os.path.join(spool_dir, "ledger")
        for d in (self.in_dir, self.out_dir, self.ledger_dir):
            os.makedirs(d, exist_ok=True)
        self.queue = open_queue(self.queue_dir,
                                visibility_timeout=visibility_timeout)
        self.ledger = FileLedger(self.ledger_dir)
        self.poll_s = max(0.01, float(poll_s))
        self._inflight: Dict[str, ServingRequest] = {}
        self._lock = threading.Lock()

    def submit(self, record: ServingRequest) -> None:
        body = record.chunk.bbox.string
        with self._lock:
            if body in self._inflight:
                telemetry.inc("serving/rejected_duplicate")
                raise AdmissionRejected(
                    "duplicate", f"request bbox {body} already in flight")
            self._inflight[body] = record
        record.req_id = body
        record.chunk.to_h5(self.in_dir + os.sep)
        self.queue.send_messages([body])

    def wait(self, record: ServingRequest, timeout: float) -> Chunk:
        body = record.req_id
        out_path = os.path.join(self.out_dir, f"{body}.h5")
        deadline = time.time() + timeout
        try:
            while time.time() < deadline and not record.done:
                if self.ledger.is_done(body) and os.path.exists(out_path):
                    try:
                        record.complete(Chunk.from_h5(out_path))
                    except OSError:
                        pass  # torn read: the writer is mid-replace
                    else:
                        break
                time.sleep(self.poll_s)
            if not record.done:
                record.fail(RequestExpired(
                    f"request {body} missed its deadline"))
            return record.wait(0.0)
        finally:
            with self._lock:
                self._inflight.pop(body, None)
            # spool hygiene: the input file is consumed; output + ledger
            # marker stay (they ARE the exactly-once record)
            try:
                os.remove(os.path.join(self.in_dir, f"{body}.h5"))
            except OSError:
                pass

    def close(self, timeout: float = 0.0) -> None:
        with self._lock:
            for record in self._inflight.values():
                record.fail(AdmissionRejected("draining", "server closed"))
            self._inflight.clear()


# ---------------------------------------------------------------------------
# HTTP service
# ---------------------------------------------------------------------------
class ServingService(CoordinationService):
    """``POST /infer`` + ``GET /serving`` riding the coordination
    service's handler (so ``/metrics``, ``/healthz`` and ``/profile``
    share the listener). Transport-independent like its parent: tests
    drive :meth:`handle` directly, the CLI serves it over
    ``ThreadingHTTPServer``."""

    def __init__(self, backend, admission: Optional[AdmissionController]
                 = None, default_deadline_s: float = 30.0,
                 max_body_mb: float = 256.0):
        super().__init__()
        self.backend = backend
        self.admission = admission or AdmissionController()
        self.default_deadline_s = float(default_deadline_s)
        self.max_body_bytes = int(max_body_mb * (1 << 20))
        # volume-reference requests: one PrecomputedVolume handle per
        # (path) for the process lifetime — handles carry the cached
        # tensorstore stores + KV sidecar, and their cutouts ride the
        # shared hot-block LRU (volume/storage.py), so repeated serving
        # loads of overlapping regions hit host memory, not the store
        self._volumes: dict = {}
        self._volumes_lock = threading.Lock()

    def handle(self, method: str, path: str, body: Optional[bytes] = None):
        if method == "POST" and path == "/infer":
            return self._handle_infer(body)
        if method == "GET" and path == "/serving":
            return 200, self.serving_stats()
        return super().handle(method, path, body)

    def serving_stats(self) -> dict:
        snap = telemetry.snapshot()
        counters = snap.get("counters", {})
        stats = {
            "inflight": self.admission.inflight,
            "max_inflight": self.admission.max_inflight,
            "requests": counters.get("serving/requests", 0),
            "completed": counters.get("serving/completed", 0),
            "rejected_admission": counters.get(
                "serving/rejected_admission", 0),
            "rejected_memory": counters.get("serving/rejected_memory", 0),
            "deadline_missed": counters.get("serving/deadline_missed", 0),
            "errors": counters.get("serving/errors", 0),
        }
        qhists = snap.get("qhists", {})
        latency = qhists.get("serving/latency")
        if latency:
            stats["latency_p50_s"] = telemetry.quantile_from_buckets(
                latency, 0.5)
            stats["latency_p99_s"] = telemetry.quantile_from_buckets(
                latency, 0.99)
        # the SLO view of the same traffic (core/slo.py): firing alert
        # names ride the /serving payload so a serving dashboard shows
        # "out of spec" next to the raw counters; full burn-rate /
        # budget detail lives on the sibling /alerts route
        from chunkflow_tpu.core import slo

        evaluator = slo.current()
        if evaluator is not None:
            stats["slo_firing"] = evaluator.firing()
        return stats

    # -- the request path ----------------------------------------------
    @staticmethod
    def _parse_request(body: Optional[bytes]) -> dict:
        if not body:
            raise ValueError("empty request body")
        try:
            payload = json.loads(body)
        except ValueError as exc:
            raise ValueError(f"request body is not JSON: {exc}") from None
        if not isinstance(payload, dict):
            raise ValueError("request body must be a JSON object")
        return payload

    def _volume(self, path: str):
        """The cached PrecomputedVolume handle for one dataset path."""
        with self._volumes_lock:
            vol = self._volumes.get(path)
        if vol is not None:
            return vol
        from chunkflow_tpu.volume.precomputed import PrecomputedVolume

        vol = PrecomputedVolume(path)
        with self._volumes_lock:
            # benign race: last writer wins, both handles share the
            # process-wide backend/KV caches anyway
            self._volumes[path] = vol
        return vol

    def _load_volume_chunk(self, payload: dict) -> Chunk:
        """A volume-reference request: instead of inline ``data_b64``
        the body names a precomputed volume and a bbox, and the serving
        plane cuts the chunk out itself — through
        :meth:`PrecomputedVolume.cutout`, i.e. block-decomposed
        concurrent reads riding the shared hot-block LRU
        (docs/storage.md), so overlapping serving loads hit host memory
        instead of re-reading the store."""
        path = payload.get("volume_path")
        if not isinstance(path, str) or not path:
            raise ValueError("volume_path must be a non-empty string")
        if payload.get("data_b64") is not None:
            raise ValueError(
                "volume_path and data_b64 are mutually exclusive")
        start = payload.get("bbox_start")
        size = payload.get("bbox_size")
        if (not isinstance(start, (list, tuple)) or len(start) != 3
                or not all(isinstance(v, int) for v in start)):
            raise ValueError("bbox_start must be three ints (zyx voxels)")
        if (not isinstance(size, (list, tuple)) or len(size) != 3
                or not all(isinstance(v, int) and v > 0 for v in size)):
            raise ValueError(
                "bbox_size must be three positive ints (zyx voxels)")
        mip = payload.get("mip", 0)
        if not isinstance(mip, int) or mip < 0:
            raise ValueError("mip must be a non-negative int")
        try:
            vol = self._volume(path)
            nchan = vol.num_channels
            itemsize = np.dtype(vol.dtype).itemsize
        except ValueError:
            raise
        except Exception as exc:  # noqa: BLE001 — bad dataset = client error
            raise ValueError(
                f"cannot open volume {path!r}: "
                f"{type(exc).__name__}: {exc}") from None
        est = int(np.prod(size)) * nchan * itemsize
        if est > self.max_body_bytes:
            raise ValueError(
                f"bbox implies {est} bytes, over the "
                f"{self.max_body_bytes >> 20} MiB request bound")
        from chunkflow_tpu.core.bbox import BoundingBox

        bbox = BoundingBox.from_delta(tuple(start), tuple(size))
        try:
            return vol.cutout(bbox, mip=mip)
        except ValueError:
            raise
        except Exception as exc:  # noqa: BLE001 — unreadable region
            raise ValueError(
                f"cutout {tuple(start)}+{tuple(size)} failed: "
                f"{type(exc).__name__}: {exc}") from None

    def _decode_chunk(self, payload: dict) -> Chunk:
        if payload.get("volume_path") is not None:
            return self._load_volume_chunk(payload)
        shape = payload.get("shape")
        if (not isinstance(shape, (list, tuple)) or len(shape) not in (3, 4)
                or not all(isinstance(s, int) and s > 0 for s in shape)):
            raise ValueError(
                "shape must be a [z,y,x] or [c,z,y,x] list of positive ints")
        dtype = payload.get("dtype", "uint8")
        if dtype not in _WIRE_DTYPES:
            raise ValueError(
                f"dtype must be one of {_WIRE_DTYPES}, got {dtype!r}")
        data_b64 = payload.get("data_b64")
        if not isinstance(data_b64, str):
            raise ValueError("data_b64 (base64 of C-order raw bytes) "
                             "is required")
        try:
            raw = base64.b64decode(data_b64, validate=True)
        except (binascii.Error, ValueError) as exc:
            raise ValueError(f"data_b64 is not valid base64: {exc}") \
                from None
        expected = int(np.prod(shape)) * np.dtype(dtype).itemsize
        if len(raw) != expected:
            raise ValueError(
                f"payload is {len(raw)} bytes but shape/dtype imply "
                f"{expected}")
        if expected > self.max_body_bytes:
            raise ValueError(
                f"request exceeds max body size "
                f"({self.max_body_bytes >> 20} MiB)")
        arr = np.frombuffer(raw, dtype=dtype).reshape(shape)
        voxel_offset = tuple(payload.get("voxel_offset") or (0, 0, 0))
        if len(voxel_offset) != 3 or not all(
                isinstance(v, int) for v in voxel_offset):
            raise ValueError("voxel_offset must be three ints")
        return Chunk(arr.copy(), voxel_offset=voxel_offset)

    @staticmethod
    def _encode_chunk(chunk: Chunk) -> dict:
        arr = np.asarray(chunk.host().array if chunk.is_on_device
                         else chunk.array)
        # bfloat16 has no portable wire representation: widen to f32
        if arr.dtype.name not in _WIRE_DTYPES:
            arr = arr.astype(np.float32)
        return {
            "shape": list(arr.shape),
            "dtype": arr.dtype.name,
            "data_b64": base64.b64encode(
                np.ascontiguousarray(arr).tobytes()).decode(),
            "voxel_offset": [int(v) for v in chunk.voxel_offset],
        }

    def _handle_infer(self, body: Optional[bytes]):
        telemetry.inc("serving/requests")
        t0 = time.time()
        try:
            payload = self._parse_request(body)
            chunk = self._decode_chunk(payload)
        except ValueError as exc:
            telemetry.inc("serving/errors")
            return 400, {"error": str(exc)}
        deadline_s = payload.get("deadline_s")
        try:
            deadline_s = (self.default_deadline_s if deadline_s is None
                          else max(0.001, float(deadline_s)))
        except (TypeError, ValueError):
            telemetry.inc("serving/errors")
            return 400, {"error": "deadline_s must be a number"}

        # float32 working-set estimate for admission: the request rides
        # the packer as f32 regardless of wire dtype
        f32_bytes = int(np.prod(chunk.shape)) * 4
        try:
            reserved = self.admission.admit(f32_bytes)
        except AdmissionRejected as exc:
            return 429, {"error": str(exc), "reason": exc.reason,
                         "retry_after_s": 0.5}
        record = ServingRequest(chunk, deadline=t0 + deadline_s)
        try:
            with telemetry.span("serving/request"):
                try:
                    self.backend.submit(record)
                except AdmissionRejected as exc:
                    return 429, {"error": str(exc), "reason": exc.reason}
                try:
                    result = self.backend.wait(
                        record, timeout=record.deadline - time.time())
                except RequestExpired as exc:
                    telemetry.observe_quantile(
                        "serving/latency", time.time() - t0)
                    return 504, {"error": str(exc),
                                 "trace_id": record.trace_id}
                except BaseException as exc:  # noqa: BLE001 — clean 500
                    return 500, {"error": f"{type(exc).__name__}: {exc}",
                                 "trace_id": record.trace_id}
            latency = time.time() - t0
            telemetry.observe_quantile("serving/latency", latency)
            response = self._encode_chunk(result)
            response["trace_id"] = record.trace_id
            response["latency_s"] = round(latency, 6)
            return 200, response
        finally:
            self.admission.release(reserved)


def start_serving(service: ServingService, host: str = "0.0.0.0",
                  port: int = 0):
    """Serve a :class:`ServingService` in the background; returns the
    live server — read the ACTUALLY-bound port from
    ``server.server_address`` (port 0 binds ephemeral, the
    multiple-workers-per-host case)."""
    server, _thread = serve(service, host=host, port=int(port),
                            background=True)
    return server
