"""Serving subsystem: cross-task patch batching + the request front-end.

``packer.py`` keeps fixed-shape device batches full from ragged
many-request traffic (the Ragged Paged Attention idiom applied to our
patch grids); ``frontend.py`` turns ``parallel/restapi.py``'s HTTP
server into a real ``POST /infer`` path with admission control,
deadlines and lifecycle-supervised execution. See docs/serving.md.
"""
from chunkflow_tpu.serve.packer import PatchPacker, serve_enabled

__all__ = ["PatchPacker", "serve_enabled"]
