"""Perfetto/Chrome-trace export of the merged fleet telemetry stream.

The fleet already writes a dense per-worker JSONL stream — spans,
gauges, counters, lifecycle events, SLO alerts, all stamped with worker
identity and (in task context) the task's ``trace_id`` — but until now
it could only be read as text tables (``log-summary --fleet``). This
module converts that stream into the Chrome trace-event format that
``chrome://tracing`` and https://ui.perfetto.dev load directly, so one
command turns any run (a chaos acceptance run, a future on-chip tunnel
window) into a loadable timeline:

* each **worker** becomes a trace **process** (``process_name``
  metadata; pid = stable rank of the worker id);
* each telemetry **plane** (the span/event name's ``<plane>/...``
  prefix: ``pipeline``, ``scheduler``, ``op``, ``shard``,
  ``lifecycle``, ...) becomes a **thread track** inside its worker;
* **spans** become complete (``X``) events — the JSONL stamp is the
  span END, so ``ts = t − dur_s``;
* **gauges** and snapshot **counters** become counter (``C``) tracks;
  counter tracks carry ``cat: "cumulative"`` so the validator knows
  which tracks must be monotone;
* **lifecycle / SLO-alert / depth-change / fleet / compile** events
  become instants (``i``);
* a task's cross-worker hops are linked by **flow** events (``s`` at
  its ``queue/submit``, ``t`` steps over intermediate claims, ``f`` at
  the final ``lifecycle/claimed``) sharing one flow id per
  ``trace_id``.

Cross-worker clock skew is normalized before any timestamp is written
(``flow.log_summary.worker_clock_offsets``: the queue send/receive pair
bounds each claimer's offset), and flow chains are additionally clamped
monotone — an exported flow can never end before it starts, which is
the invariant the CI stage asserts.

Usage:
    python tools/trace_export.py <metrics_dir> -o out.json
    chunkflow log-summary --metrics-dir <dir> --export-trace out.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

try:
    from chunkflow_tpu.flow.log_summary import (
        _event_worker,
        load_telemetry_dir,
        worker_clock_offsets,
    )
except ImportError:  # direct script run from anywhere: add the repo root
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from chunkflow_tpu.flow.log_summary import (
        _event_worker,
        load_telemetry_dir,
        worker_clock_offsets,
    )
from chunkflow_tpu.core.telemetry import CHIP_METRIC_RE

#: JSONL event kinds that render as instant markers on their plane track
_INSTANT_KINDS = (
    "task", "task_retry", "alert", "depth_change", "fleet", "compile",
)

#: payload keys that are structural, not event arguments
_STRUCTURAL_KEYS = ("kind", "name", "t", "dur_s", "pid", "worker")


def _plane(name: str) -> str:
    """The track a span/instant renders on: the name's top-level plane
    (``pipeline/stage`` -> ``pipeline``)."""
    return str(name).split("/", 1)[0] or "events"


def _args_of(record: dict) -> dict:
    return {
        k: v for k, v in record.items()
        if k not in _STRUCTURAL_KEYS and v is not None
        and not isinstance(v, (dict, list))
    }


def export_chrome_trace(events: List[dict]) -> dict:
    """The merged JSONL stream as one Chrome trace-event object
    (``{"traceEvents": [...], "displayTimeUnit": "ms"}``). Timestamps
    are microseconds relative to the earliest (skew-normalized) event,
    every emitted event carries ``pid``/``tid``/``ts``, and every flow
    id is paired (one ``s``, a final ``f``)."""
    offsets = worker_clock_offsets(events)

    def t_adj(record: dict) -> float:
        return (float(record.get("t", 0.0))
                + offsets.get(_event_worker(record), 0.0))

    # stable pid per worker, tid per (worker, plane)
    workers = sorted({_event_worker(e) for e in events})
    pids = {worker: i + 1 for i, worker in enumerate(workers)}
    tids: Dict[Tuple[str, str], int] = {}

    def tid_of(worker: str, plane: str) -> int:
        key = (worker, plane)
        if key not in tids:
            tids[key] = 1 + sum(1 for w, _ in tids if w == worker)
        return tids[key]

    # pass 1: the time base (span starts reach earlier than their stamp)
    base: Optional[float] = None
    for record in events:
        if record.get("kind") == "timeseries":
            continue
        start = t_adj(record) - float(record.get("dur_s", 0.0) or 0.0)
        base = start if base is None else min(base, start)
    if base is None:
        base = 0.0

    def ts_us(record: dict) -> float:
        return round((t_adj(record) - base) * 1e6, 3)

    out: List[dict] = []
    # pass 2: spans, counters, instants (+ flow anchors collected)
    flows: Dict[str, List[dict]] = {}  # trace_id -> anchor events
    for record in events:
        kind = record.get("kind")
        worker = _event_worker(record)
        pid = pids[worker]
        name = str(record.get("name", "") or kind)
        if kind == "span":
            dur_s = float(record.get("dur_s", 0.0) or 0.0)
            out.append({
                "ph": "X", "name": name, "cat": "span",
                "pid": pid, "tid": tid_of(worker, _plane(name)),
                "ts": round(ts_us(record) - dur_s * 1e6, 3),
                "dur": round(dur_s * 1e6, 3),
                "args": _args_of(record),
            })
        elif kind == "gauge":
            chip_match = CHIP_METRIC_RE.match(name)
            if chip_match:
                # per-chip gauges (``<plane>/chip/<i>/<metric>``, ISSUE
                # 19) render on a ``chip <i>`` thread track inside their
                # worker, one counter per metric — so a mesh run shows
                # replay-buffer bytes / HBM watermarks side by side per
                # chip instead of interleaved on the global gauge track
                chip = int(chip_match.group("chip"))
                out.append({
                    "ph": "C",
                    "name": (f"{chip_match.group('plane')}/"
                             f"{chip_match.group('metric')}"),
                    "cat": "chip_gauge",
                    "pid": pid,
                    "tid": tid_of(worker, f"chip {chip}"),
                    "ts": ts_us(record),
                    "args": {"value": float(record.get("value", 0.0)),
                             "chip": chip},
                })
            else:
                out.append({
                    "ph": "C", "name": name, "cat": "gauge",
                    "pid": pid, "tid": 0, "ts": ts_us(record),
                    "args": {"value": float(record.get("value", 0.0))},
                })
        elif kind == "snapshot":
            for cname, value in (record.get("counters") or {}).items():
                out.append({
                    "ph": "C", "name": cname, "cat": "cumulative",
                    "pid": pid, "tid": 0, "ts": ts_us(record),
                    "args": {"value": float(value)},
                })
        elif kind in _INSTANT_KINDS:
            anchor = {
                "ph": "i", "name": name, "cat": kind,
                "pid": pid, "tid": tid_of(worker, _plane(name)),
                "ts": ts_us(record), "s": "t",
                "args": _args_of(record),
            }
            out.append(anchor)
            trace_id = record.get("trace_id")
            if trace_id and name in ("queue/submit", "lifecycle/claimed"):
                flows.setdefault(str(trace_id), []).append(
                    {"anchor": anchor, "worker": worker, "name": name})
    # pass 3: flow chains for tasks that hopped between workers
    flow_pairs = 0
    for seq, (trace_id, anchors) in enumerate(sorted(flows.items())):
        if len({a["worker"] for a in anchors}) < 2:
            continue  # a single worker's task needs no arrow
        anchors.sort(key=lambda a: a["anchor"]["ts"])
        submits = [a for a in anchors if a["name"] == "queue/submit"]
        claims = [a for a in anchors if a["name"] == "lifecycle/claimed"]
        if not submits or not claims:
            continue
        chain = [submits[0]] + claims
        flow_pairs += 1
        prev_ts = chain[0]["anchor"]["ts"]
        for i, entry in enumerate(chain):
            anchor = entry["anchor"]
            # belt and braces on top of the offset normalization: a flow
            # step can never precede the step before it
            prev_ts = max(prev_ts, anchor["ts"])
            ph = ("s" if i == 0
                  else "f" if i == len(chain) - 1 else "t")
            flow_event = {
                "ph": ph, "name": "task-hop", "cat": "task_flow",
                "id": seq + 1, "pid": anchor["pid"],
                "tid": anchor["tid"], "ts": prev_ts,
                "args": {"trace_id": trace_id},
            }
            if ph == "f":
                flow_event["bp"] = "e"
            out.append(flow_event)
    # metadata: worker names on processes, plane names on threads
    for worker, pid in pids.items():
        out.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "ts": 0, "args": {"name": f"worker {worker}"},
        })
    for (worker, plane), tid in tids.items():
        out.append({
            "ph": "M", "name": "thread_name", "pid": pids[worker],
            "tid": tid, "ts": 0, "args": {"name": plane},
        })
    out.sort(key=lambda e: (e["ts"], e["pid"], e["tid"]))
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "workers": len(workers),
            "flow_pairs": flow_pairs,
            "source": "chunkflow telemetry JSONL",
        },
    }


def validate_chrome_trace(trace: dict) -> List[str]:
    """Schema checks the CI stage (and tests) assert on an exported
    trace; returns a list of problems (empty = valid):

    * every event carries numeric ``pid``/``tid``/``ts`` (and ``X``
      events a non-negative ``dur``);
    * every flow id is paired — exactly one ``s``, at least one ``f``,
      and no step/finish earlier than its start (monotone chains);
    * ``cumulative`` counter tracks are monotone non-decreasing per
      (pid, name);
    * ``chip_gauge`` counters (per-chip tracks, ISSUE 19) carry a
      non-negative integer ``chip`` arg, and one thread track never
      mixes samples from two different chips."""
    problems: List[str] = []
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    flows: Dict[object, Dict[str, list]] = {}
    counters: Dict[Tuple[object, str], List[Tuple[float, float]]] = {}
    chip_tracks: Dict[Tuple[object, object], int] = {}
    for i, event in enumerate(events):
        for field in ("pid", "tid", "ts"):
            if not isinstance(event.get(field), (int, float)):
                problems.append(f"event {i} ({event.get('ph')}"
                                f" {event.get('name')!r}): bad {field}")
        ph = event.get("ph")
        if ph == "X" and float(event.get("dur", -1.0)) < 0:
            problems.append(f"event {i}: X without non-negative dur")
        elif ph in ("s", "t", "f"):
            entry = flows.setdefault(
                event.get("id"), {"s": [], "t": [], "f": []})
            entry[ph].append(float(event.get("ts", 0.0)))
        elif ph == "C":
            key = (event.get("pid"), str(event.get("name")))
            value = (event.get("args") or {}).get("value")
            if not isinstance(value, (int, float)):
                problems.append(f"event {i}: counter without value")
            elif event.get("cat") == "cumulative":
                counters.setdefault(key, []).append(
                    (float(event.get("ts", 0.0)), float(value)))
            if event.get("cat") == "chip_gauge":
                chip = (event.get("args") or {}).get("chip")
                if not isinstance(chip, int) or chip < 0:
                    problems.append(
                        f"event {i}: chip_gauge counter "
                        f"{event.get('name')!r} without a non-negative "
                        f"integer chip arg")
                    continue
                track = (event.get("pid"), event.get("tid"))
                seen = chip_tracks.setdefault(track, chip)
                if seen != chip:
                    problems.append(
                        f"chip track pid={track[0]} tid={track[1]} "
                        f"mixes chips {seen} and {chip}")
    for flow_id, entry in flows.items():
        if len(entry["s"]) != 1 or not entry["f"]:
            problems.append(
                f"flow {flow_id}: {len(entry['s'])} start(s), "
                f"{len(entry['f'])} finish(es) — must be 1 and >=1")
            continue
        start = entry["s"][0]
        for ts in entry["t"] + entry["f"]:
            if ts < start:
                problems.append(
                    f"flow {flow_id}: step/finish at {ts} before "
                    f"start {start}")
    for (pid, name), samples in counters.items():
        samples.sort(key=lambda s: s[0])
        last = None
        for ts, value in samples:
            if last is not None and value < last:
                problems.append(
                    f"cumulative counter {name!r} (pid {pid}) "
                    f"decreases at ts {ts}: {last} -> {value}")
                break
            last = value
    return problems


def export_metrics_dir(metrics_dir: str, out_path: str) -> dict:
    """Load a metrics dir, export it, validate, write ``out_path``.
    Returns ``{"events", "trace_events", "workers", "flow_pairs",
    "problems"}`` — writing happens even when validation flags
    problems, so a broken trace can be inspected."""
    events = load_telemetry_dir(metrics_dir)
    trace = export_chrome_trace(events)
    problems = validate_chrome_trace(trace)
    with open(out_path, "w") as f:
        json.dump(trace, f)
    return {
        "events": len(events),
        "trace_events": len(trace["traceEvents"]),
        "workers": trace["otherData"]["workers"],
        "flow_pairs": trace["otherData"]["flow_pairs"],
        "problems": problems,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Export merged telemetry JSONL as a Chrome trace")
    parser.add_argument("metrics_dir")
    parser.add_argument("-o", "--output", default="trace.json")
    args = parser.parse_args(argv)
    stats = export_metrics_dir(args.metrics_dir, args.output)
    print(
        f"trace_export: {stats['events']} telemetry event(s) -> "
        f"{stats['trace_events']} trace event(s), "
        f"{stats['workers']} worker process(es), "
        f"{stats['flow_pairs']} cross-worker flow(s) -> {args.output}"
    )
    for problem in stats["problems"]:
        print(f"trace_export: INVALID: {problem}", file=sys.stderr)
    return 1 if stats["problems"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
