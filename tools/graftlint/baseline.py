"""Baseline file: grandfathered findings the CI gate tolerates.

The baseline is a checked-in JSON multiset of finding keys
(path::code::function::line-text — line-number independent, so unrelated
edits don't resurface old findings). The gate fails only on findings whose
key count EXCEEDS the baselined count; fixing a grandfathered finding
just leaves a stale entry, reported as a note so the file gets re-shrunk
with ``--write-baseline``.
"""
from __future__ import annotations

import json
from collections import Counter
from pathlib import Path
from typing import List, Tuple

from tools.graftlint.model import Finding

FORMAT_VERSION = 1


def load_baseline(path: Path) -> Counter:
    if not path.exists():
        return Counter()
    data = json.loads(path.read_text())
    if data.get("version") != FORMAT_VERSION:
        raise ValueError(
            f"baseline {path} has format version {data.get('version')!r}, "
            f"expected {FORMAT_VERSION}; regenerate with --write-baseline"
        )
    return Counter(data.get("findings", {}))


def write_baseline(path: Path, findings: List[Finding]) -> None:
    counts = Counter(f.baseline_key for f in findings)
    data = {
        "version": FORMAT_VERSION,
        "comment": (
            "grandfathered graftlint findings; regenerate with "
            "`python -m tools.graftlint --write-baseline` after fixing "
            "or deliberately adding entries"
        ),
        "findings": {k: counts[k] for k in sorted(counts)},
    }
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(data, indent=2, sort_keys=False) + "\n")


def diff_baseline(
    findings: List[Finding], baseline: Counter
) -> Tuple[List[Finding], int, int]:
    """(new_findings, grandfathered_count, stale_entry_count).

    Findings are matched to baseline slots per key, oldest-line first, so
    the surplus (new) ones are deterministic.
    """
    budget = Counter(baseline)
    new: List[Finding] = []
    grandfathered = 0
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col)):
        if budget[f.baseline_key] > 0:
            budget[f.baseline_key] -= 1
            grandfathered += 1
        else:
            new.append(f)
    stale = sum(budget.values())
    return new, grandfathered, stale
