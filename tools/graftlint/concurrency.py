"""The GL010-series: thread-aware concurrency rules.

PRs 4-9 put ``threading.Thread``/``Lock``/``Condition`` into a dozen
modules (scheduler pumps, lifecycle heartbeats, the fleet supervisor,
the serving front-end, the cross-task packer); these rules catch the
bug shapes that repeatedly slipped past review there — unlocked shared
writes, lock-order inversions, blocking calls under a lock, leaked
threads, and non-looped condition waits. The runtime half of the same
plane is the locksmith sanitizer (chunkflow_tpu/testing/locksmith.py),
which cross-checks lock ordering dynamically over the whole tier-1
suite.

All analysis is module-local and name-based (tools/graftlint/
threads.py); inline ``# graftlint: disable=GL01x`` comments absorb the
deliberate exceptions, each with a justification.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from tools.graftlint.context import FileContext, func_name
from tools.graftlint.model import Finding, make_finding
from tools.graftlint.rules import Rule
from tools.graftlint.threads import (
    LockToken,
    ThreadModel,
    enclosing_class,
    get_model,
    token_display,
)


class SharedWriteWithoutLock(Rule):
    """Shared mutable attribute written from a thread without a lock.

    A ``self.X`` attribute that is written inside a function running on
    a spawned thread (``threading.Thread(target=...)``, ``executor.
    submit``, timers) and is also accessed from other methods of the
    class is shared mutable state: unless the write sits inside a
    ``with <lock>:`` block (any lock of the class or module), two
    threads can interleave on it — torn read-modify-writes, lost
    updates, stale reads. Either guard the write with the class's lock
    or, when the access pattern is provably safe (single writer +
    GIL-atomic read, an ``Event`` doing the signaling), suppress with a
    comment saying why.
    """

    code = "GL010"
    name = "shared-write-without-lock"

    #: attribute writes in these methods precede any thread spawn on the
    #: same object, so they cannot race with it
    SETUP_METHODS = {"__init__", "__new__", "__post_init__"}

    def _global_writes(self, ctx, model) -> Iterator[Finding]:
        """Module-global writes (``global X`` declared) from a
        thread-context function without a lock held — the module-level
        twin of the unguarded ``self.X`` write."""
        for fn in ctx.functions:
            if fn not in model.thread_entries or isinstance(fn, ast.Lambda):
                continue
            declared: Set[str] = set()
            for node, _held in model.iter_held(fn):
                if isinstance(node, ast.Global):
                    declared.update(node.names)
            if not declared:
                continue
            for node, held in model.iter_held(fn):
                if held or not isinstance(node, ast.Name) or \
                        not isinstance(node.ctx, (ast.Store, ast.Del)) or \
                        node.id not in declared:
                    continue
                yield make_finding(
                    ctx, node, self.code,
                    f"module global `{node.id}` is written in "
                    f"thread-context `{func_name(fn)}` without holding "
                    f"a lock — guard the write or suppress with a "
                    f"justification",
                )

    def _attr_accesses(
        self, model: ThreadModel, cls_name: str
    ) -> Dict[str, List[Tuple[ast.AST, str, bool, tuple]]]:
        """attr -> [(node, method name, is_write, held)] over every
        ``self.X`` use in the class's direct methods."""
        out: Dict[str, List[Tuple[ast.AST, str, bool, tuple]]] = {}
        for (cname, mname), fn in model.methods.items():
            if cname != cls_name:
                continue
            for node, held in model.iter_held(fn):
                if not (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self"):
                    continue
                is_write = isinstance(node.ctx, (ast.Store, ast.Del))
                # augmented writes (self.n += 1) parse as Store too
                out.setdefault(node.attr, []).append(
                    (node, mname, is_write, held)
                )
        return out

    def run(self, ctx: FileContext, config) -> Iterator[Finding]:
        model = get_model(ctx)
        if not model.thread_entries:
            return
        yield from self._global_writes(ctx, model)
        classes = {cname for cname, _m in model.methods}
        for cls_name in sorted(classes):
            thread_methods = {
                mname for (cname, mname), fn in model.methods.items()
                if cname == cls_name and fn in model.thread_entries
            }
            if not thread_methods:
                continue
            locks = model.class_locks.get(cls_name, {})
            accesses = self._attr_accesses(model, cls_name)
            for attr, uses in sorted(accesses.items()):
                if attr in locks or attr.startswith("__"):
                    continue
                outside = [u for u in uses if u[1] not in thread_methods]
                if not outside:
                    continue  # thread-private state: no sharing
                for node, mname, is_write, held in uses:
                    if not is_write or mname not in thread_methods \
                            or mname in self.SETUP_METHODS:
                        continue
                    if held:
                        continue  # guarded by some lock
                    yield make_finding(
                        ctx, node, self.code,
                        f"`self.{attr}` is written in thread-context "
                        f"`{mname}` without holding a lock, but is also "
                        f"accessed from "
                        f"`{sorted({u[1] for u in outside})[0]}` — "
                        f"guard the write or suppress with a "
                        f"justification (single-writer, GIL-atomic)",
                    )


class LockOrderInversion(Rule):
    """Lock-acquisition-order inversion across one class/module.

    If one code path acquires lock A then (still holding A) lock B,
    while another path acquires B then A — directly or through a
    module-local call made under the lock — two threads can each take
    their first lock and deadlock waiting for the other. The static
    graph covers the locks visible in one file (``self.X`` attributes,
    module globals, locals); the locksmith runtime sanitizer covers the
    cross-module rest. Fix by picking one global order (document it
    where the locks are created); conditions built over an existing
    lock count as that lock.
    """

    code = "GL011"
    name = "lock-order-inversion"

    def run(self, ctx: FileContext, config) -> Iterator[Finding]:
        model = get_model(ctx)
        edges = model.order_edges()
        if not edges:
            return
        reported: Set[frozenset] = set()
        adjacency: Dict[LockToken, Set[LockToken]] = {}
        for (a, b) in edges:
            adjacency.setdefault(a, set()).add(b)

        def reaches(start: LockToken, goal: LockToken) -> bool:
            seen, stack = set(), [start]
            while stack:
                cur = stack.pop()
                if cur == goal:
                    return True
                if cur in seen:
                    continue
                seen.add(cur)
                stack.extend(adjacency.get(cur, ()))
            return False

        ordered = sorted(
            edges.items(),
            key=lambda kv: (kv[1].lineno, kv[1].col_offset),
        )
        for (a, b), site in ordered:
            pair = frozenset((a, b))
            if pair in reported:
                continue
            if reaches(b, a):
                reported.add(pair)
                other = edges.get((b, a))
                where = (f" (reverse order at line {other.lineno})"
                         if other is not None else
                         " (reverse order via an intermediate lock)")
                yield make_finding(
                    ctx, site, self.code,
                    f"lock-order inversion: `{token_display(b)}` is "
                    f"acquired while holding `{token_display(a)}` here, "
                    f"but the opposite order also exists{where} — "
                    f"two threads taking their first lock each will "
                    f"deadlock; pick one order",
                )


class BlockingCallUnderLock(Rule):
    """Blocking call while holding a lock.

    A ``queue.get()``/``.put()`` without timeout, an unbounded
    ``thread.join()``/``future.result()``, ``block_until_ready`` (a
    device sync can take a full chunk's compute time), a socket/HTTP
    round trip, or a ``time.sleep`` executed inside a ``with <lock>:``
    block stalls every other thread that needs the lock for the whole
    wait — and if the thing being waited on itself needs the lock, the
    program deadlocks. Move the wait outside the critical section, or
    bound it with a timeout. ``Condition.wait`` on a held condition is
    exempt (it releases the lock while waiting — that is the point).
    """

    code = "GL012"
    name = "blocking-call-under-lock"

    BLOCKING_FUNCS = {
        "time.sleep",
        "urllib.request.urlopen",
        "socket.create_connection",
        "requests.get", "requests.post", "requests.put",
        "requests.request",
        "subprocess.run", "subprocess.check_output",
        "subprocess.check_call", "subprocess.call",
        "jax.block_until_ready",
    }

    @staticmethod
    def _has_kwarg(call: ast.Call, *names: str) -> bool:
        return any(kw.arg in names for kw in call.keywords)

    def _blocking_reason(self, ctx, model, call: ast.Call, fn,
                         held) -> str:
        resolved = ctx.imports.resolve(call.func)
        if resolved in self.BLOCKING_FUNCS:
            return f"`{resolved}`"
        if not isinstance(call.func, ast.Attribute) or resolved is not None:
            return ""
        attr = call.func.attr
        if attr == "block_until_ready":
            return "`.block_until_ready()` (device sync)"
        receiver = model.lock_token(call.func.value, fn)
        if attr == "wait":
            if receiver is not None and receiver[1] == "condition":
                return ""  # releases the lock while waiting (GL014's job)
            if receiver is not None and receiver[1] == "event" and \
                    not call.args and not self._has_kwarg(call, "timeout"):
                return "`.wait()` on an Event without timeout"
            return ""
        if attr == "join" and not call.args and not call.keywords:
            return "unbounded `.join()`"
        if attr in ("get", "result") and not call.args and \
                not self._has_kwarg(call, "timeout", "block"):
            return f"blocking `.{attr}()` without timeout"
        if attr == "put" and not self._has_kwarg(call, "timeout", "block"):
            root = call.func.value
            while isinstance(root, ast.Attribute):
                root = root.value
            name = root.id if isinstance(root, ast.Name) else ""
            if "queue" in name.lower() or name == "q":
                return "blocking `.put()` without timeout"
        return ""

    def run(self, ctx: FileContext, config) -> Iterator[Finding]:
        model = get_model(ctx)
        for fn in ctx.functions:
            for node, held in model.iter_held(fn):
                if not held or not isinstance(node, ast.Call):
                    continue
                reason = self._blocking_reason(ctx, model, node, fn, held)
                if not reason:
                    continue
                lock = token_display(held[-1][0])
                yield make_finding(
                    ctx, node, self.code,
                    f"{reason} while holding `{lock}` in "
                    f"`{func_name(fn)}` — every thread needing the lock "
                    f"stalls for the whole wait; move the wait outside "
                    f"the critical section or bound it with a timeout",
                )


class LeakedThread(Rule):
    """``threading.Thread`` that is neither daemonized nor joined.

    A non-daemon thread whose handle is dropped (or never ``join``ed)
    keeps the process alive after main exits and leaks under repeated
    construction; at interpreter shutdown it can race module teardown.
    Every spawned thread needs an owner: pass ``daemon=True`` for
    fire-and-forget helpers, or keep the handle and ``join`` it on the
    shutdown path (the repo's pump/heartbeat/dispatcher threads all do
    one or the other). The check is module-wide: a handle stored on
    ``self`` and joined from another method counts.
    """

    code = "GL013"
    name = "leaked-thread"

    @staticmethod
    def _root_matches(node: ast.AST, key: Tuple[str, str]) -> bool:
        kind, name = key
        if kind == "name":
            return isinstance(node, ast.Name) and node.id == name
        return (isinstance(node, ast.Attribute) and node.attr == name
                and isinstance(node.value, ast.Name)
                and node.value.id == "self")

    def _handled(self, ctx: FileContext, spawn) -> bool:
        key = spawn.target_key
        if key is None:
            return False
        loop_vars: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.For, ast.comprehension)) and \
                    self._root_matches(node.iter, key) and \
                    isinstance(node.target, ast.Name):
                loop_vars.add(node.target.id)
        for node in ast.walk(ctx.tree):
            # X.daemon = True  /  X.setDaemon(True)
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if isinstance(target, ast.Attribute) and \
                            target.attr == "daemon" and \
                            self._root_matches(target.value, key):
                        return True
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            if node.func.attr not in ("join", "setDaemon"):
                continue
            value = node.func.value
            if self._root_matches(value, key):
                return True
            if isinstance(value, ast.Name) and value.id in loop_vars:
                return True  # for t in self._threads: t.join(...)
        return False

    def run(self, ctx: FileContext, config) -> Iterator[Finding]:
        model = get_model(ctx)
        for spawn in model.spawns:
            if spawn.daemon or self._handled(ctx, spawn):
                continue
            yield make_finding(
                ctx, spawn.call, self.code,
                "thread is neither daemonized nor joined anywhere in "
                "this module — pass daemon=True for a fire-and-forget "
                "helper, or keep the handle and join it on the "
                "shutdown path",
            )


class ConditionWaitOutsideLoop(Rule):
    """``Condition.wait`` not inside a loop re-checking its predicate.

    ``wait()`` can return spuriously, and between the notify and the
    wake another thread may have consumed the state change — so the
    predicate must be RE-CHECKED after every wake. A wait that is not
    enclosed in a ``while``/``for`` loop acts on the first wake no
    matter what is actually true, which is a latent lost-wakeup /
    spurious-wakeup bug. Use ``while not pred: cv.wait()`` or
    ``cv.wait_for(pred)`` (which loops internally).
    """

    code = "GL014"
    name = "condition-wait-outside-loop"

    def run(self, ctx: FileContext, config) -> Iterator[Finding]:
        model = get_model(ctx)
        for fn in ctx.functions:
            for node, _held in model.iter_held(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "wait"):
                    continue
                receiver = model.lock_token(node.func.value, fn)
                if receiver is None or receiver[1] != "condition":
                    continue
                cur = getattr(node, "parent", None)
                in_loop = False
                while cur is not None and cur is not fn:
                    if isinstance(cur, (ast.While, ast.For)):
                        in_loop = True
                        break
                    cur = getattr(cur, "parent", None)
                if in_loop:
                    continue
                yield make_finding(
                    ctx, node, self.code,
                    f"`{token_display(receiver[0])}.wait()` outside a "
                    f"predicate loop in `{func_name(fn)}` — spurious "
                    f"wakeups and notify races act on the first wake; "
                    f"use `while not pred: wait()` or `wait_for(pred)`",
                )


CONCURRENCY_RULES: List[Rule] = [
    SharedWriteWithoutLock(),
    LockOrderInversion(),
    BlockingCallUnderLock(),
    LeakedThread(),
    ConditionWaitOutsideLoop(),
]
