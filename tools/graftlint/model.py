"""Finding/suppression primitives shared by the engine, rules and CLI."""
from __future__ import annotations

import io
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Optional, Set

#: ``# graftlint: disable=GL001,GL002`` / ``# graftlint: disable`` /
#: ``# graftlint: disable-file=GL004``
_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(disable(?:-file)?)\s*(?:=\s*([A-Z0-9,\s]+))?"
)

#: sentinel meaning "every rule code"
ALL_CODES = "*"


@dataclass(frozen=True)
class Finding:
    """One lint violation at a specific source location."""

    path: str       # repo-relative posix path
    line: int       # 1-based
    col: int        # 0-based
    code: str       # "GL001"
    message: str
    context: str    # qualname of the enclosing function, or "<module>"
    text: str       # stripped source line (for baseline matching + display)

    @property
    def baseline_key(self) -> str:
        """Line-number-independent identity: survives unrelated edits that
        shift the file, so grandfathered findings don't resurface when a
        docstring above them grows."""
        return f"{self.path}::{self.code}::{self.context}::{self.text}"

    def as_dict(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "code": self.code,
            "message": self.message,
            "context": self.context,
            "text": self.text,
        }


@dataclass
class Suppressions:
    """Per-line and per-file rule suppressions parsed from comments."""

    by_line: Dict[int, Set[str]] = field(default_factory=dict)
    file_wide: Set[str] = field(default_factory=set)

    def is_suppressed(self, line: int, code: str) -> bool:
        for scope in (self.file_wide, self.by_line.get(line, ())):
            if code in scope or ALL_CODES in scope:
                return True
        return False


def extract_comments(source: str) -> Dict[int, str]:
    """{lineno: comment text} via tokenize — immune to '#' inside strings."""
    comments: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                comments[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass  # the AST parse reports the real error; comments best-effort
    return comments


def parse_suppressions(comments: Dict[int, str]) -> Suppressions:
    sup = Suppressions()
    for lineno, text in comments.items():
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        kind, codes_raw = m.group(1), m.group(2)
        codes = (
            {c.strip() for c in codes_raw.split(",") if c.strip()}
            if codes_raw
            else {ALL_CODES}
        )
        if kind == "disable-file":
            sup.file_wide |= codes
        else:
            sup.by_line.setdefault(lineno, set()).update(codes)
    return sup


def comment_matches(
    comments: Dict[int, str], line: int, pattern: re.Pattern,
    lines_back: int = 1,
) -> bool:
    """True if the comment on ``line`` or up to ``lines_back`` lines above
    matches ``pattern`` (GL006's axis-order annotation check)."""
    for ln in range(line, line - lines_back - 1, -1):
        text = comments.get(ln)
        if text is not None and pattern.search(text):
            return True
    return False


def make_finding(
    ctx, node, code: str, message: str, context: Optional[str] = None
) -> Finding:
    """Build a Finding anchored at ``node`` within file context ``ctx``."""
    line = getattr(node, "lineno", 1)
    col = getattr(node, "col_offset", 0)
    text = ""
    if 1 <= line <= len(ctx.lines):
        text = ctx.lines[line - 1].strip()
    return Finding(
        path=ctx.path,
        line=line,
        col=col,
        code=code,
        message=message,
        context=context if context is not None else ctx.qualname_at(node),
        text=text,
    )
