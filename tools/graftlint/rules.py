"""The graftlint rule registry: GL001..GL007 (jit/tracer correctness)
plus the GL010-series concurrency rules (tools/graftlint/concurrency.py).

Each rule is a class with ``code``, ``name`` and ``run(ctx, config)``
yielding Findings. Register new rules by appending to ``RULES`` (see
docs/linting.md for the recipe); codes must be unique and stable — the
baseline file and suppression comments key on them.
"""
from __future__ import annotations

import ast
import re
from typing import Iterator, List

from tools.graftlint.context import FileContext, func_name, walk_local
from tools.graftlint.model import Finding, comment_matches, make_finding

#: parameter names that (heuristically) hold chunk-scale arrays
CHUNK_PARAM_NAMES = {
    "chunk", "chunks", "arr", "array", "vol", "volume", "img", "image",
    "out", "weight", "buf", "buffer", "stack", "patches",
}

#: receiver roots GL006 treats as chunk arrays (superset of the above)
CHUNK_VALUE_NAMES = CHUNK_PARAM_NAMES | {
    "patch", "preds", "pred", "tiles", "dense", "sub", "result", "chunk_arr",
    "weighted", "wstack", "slab",
}

_AXIS_COMMENT_RE = re.compile(r"(?i)\b(zyx|xyz|[bc]?[zyx]{3}|axis|axes|order)\b")
_AXIS_HELPER_RE = re.compile(
    r"(transpose|reorder|reshape|fold|place|axes|axis|to_[zyx]{3}|layout)"
)


class Rule:
    code = "GL000"
    name = "abstract"

    def run(self, ctx: FileContext, config) -> Iterator[Finding]:
        raise NotImplementedError


class HostSyncInJit(Rule):
    """Host-synchronizing call inside a jit-traced function.

    ``.item()``, ``.tolist()``, ``np.asarray``/``np.array``,
    ``jax.device_get`` and ``(jax.)block_until_ready`` force the tracer to
    materialize a concrete value: under ``jax.jit`` that is either a
    ConcretizationTypeError or — worse — a silent device->host round trip
    per call that serializes the TPU pipeline. Keep host syncs at chunk
    boundaries, outside the compiled program.
    """

    code = "GL001"
    name = "host-sync-in-jit"

    SYNC_METHODS = {"item", "tolist", "block_until_ready",
                    "copy_to_host_async"}
    SYNC_FUNCS = {"numpy.asarray", "numpy.array", "jax.device_get",
                  "jax.block_until_ready"}

    def run(self, ctx, config):
        for fn in ctx.traced:
            for node in walk_local(fn):
                if not isinstance(node, ast.Call):
                    continue
                resolved = ctx.imports.resolve(node.func)
                if resolved in self.SYNC_FUNCS:
                    yield make_finding(
                        ctx, node, self.code,
                        f"host sync `{resolved}` inside jit-traced "
                        f"`{func_name(fn)}` — forces a device->host round "
                        f"trip; hoist it out of the compiled program",
                    )
                elif isinstance(node.func, ast.Attribute) and resolved is \
                        None and node.func.attr in self.SYNC_METHODS:
                    yield make_finding(
                        ctx, node, self.code,
                        f"host sync `.{node.func.attr}()` inside jit-traced "
                        f"`{func_name(fn)}` — keep host syncs at chunk "
                        f"boundaries, outside jit",
                    )


class NumpyOnTracer(Rule):
    """numpy op inside a jit-traced function (np/jnp namespace mixing).

    ``np.*`` array ops applied to traced values either crash
    (ConcretizationTypeError) or silently fall back to host execution,
    breaking the fused XLA program. Inside traced code use ``jnp.*`` /
    ``jax.lax``; numpy belongs to host-side geometry (patch grids, bump
    tables) computed before the program is staged.
    """

    code = "GL002"
    name = "numpy-on-tracer"

    #: numpy attributes that are trace-safe: dtype metadata, scalar type
    #: constructors, and static shape arithmetic on Python ints
    SAFE = {
        "dtype", "iinfo", "finfo", "errstate", "promote_types",
        "result_type", "can_cast", "isscalar", "ndim", "prod",
        "issubdtype", "broadcast_shapes", "index_exp", "s_", "newaxis",
        "pi", "e", "inf", "nan",
        "float16", "float32", "float64", "int8", "int16", "int32", "int64",
        "uint8", "uint16", "uint32", "uint64", "bool_", "intp",
    }

    def run(self, ctx, config):
        for fn in ctx.traced:
            for node in walk_local(fn):
                if not isinstance(node, ast.Call):
                    continue
                resolved = ctx.imports.resolve(node.func)
                if resolved is None or not resolved.startswith("numpy."):
                    continue
                attr = resolved.split(".")[1]
                if attr in self.SAFE or resolved in HostSyncInJit.SYNC_FUNCS:
                    continue  # GL001 owns asarray/array
                yield make_finding(
                    ctx, node, self.code,
                    f"numpy op `{resolved}` inside jit-traced "
                    f"`{func_name(fn)}` — use jnp/lax so the op stays in "
                    f"the compiled program",
                )


class TracerControlFlow(Rule):
    """Python control flow on a tracer-derived value.

    ``if``/``while``/``bool()``/``assert`` on a traced value concretizes
    the tracer: at best a ConcretizationTypeError, at worst a silent
    per-value recompilation every time the branch flips. Use ``lax.cond``
    / ``lax.while_loop`` / ``jnp.where``, or branch on static facts
    (``x.shape``, ``x.ndim``, ``len(...)``) which this rule ignores.
    """

    code = "GL003"
    name = "tracer-control-flow"

    def run(self, ctx, config):
        for fn in ctx.traced:
            tainted = ctx.tainted_names(fn)
            for node in walk_local(fn):
                test = None
                kind = None
                if isinstance(node, (ast.If, ast.While)):
                    test, kind = node.test, type(node).__name__.lower()
                elif isinstance(node, ast.IfExp):
                    test, kind = node.test, "conditional expression"
                elif isinstance(node, ast.Assert):
                    test, kind = node.test, "assert"
                elif isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Name) and \
                        node.func.id == "bool" and node.args:
                    test, kind = node.args[0], "bool()"
                if test is not None and ctx.expr_is_tainted(test, tainted):
                    yield make_finding(
                        ctx, node, self.code,
                        f"Python `{kind}` on a tracer-derived value inside "
                        f"jit-traced `{func_name(fn)}` — recompilation/"
                        f"concretization hazard; use lax.cond/jnp.where or "
                        f"branch on static shape facts",
                    )


class ImplicitFloat64(Rule):
    """Implicit float64 literal or dtype promotion in blending-critical code.

    numpy defaults to float64: a dtype-less ``np.zeros``/``np.linspace``,
    a ``.mean()``/``.sum()`` accumulator without ``dtype=``, or an
    explicit ``np.float64`` doubles memory traffic and silently promotes
    downstream math. Blending accumulators in ``ops/`` and ``inference/``
    must be explicit float32 (scoped via ``float64_paths`` in
    ``[tool.graftlint]``). Deliberate float64 (e.g. the host-side bump
    table) gets an inline ``# graftlint: disable=GL004``.
    """

    code = "GL004"
    name = "implicit-float64"

    #: constructor -> positional index at which dtype may be passed
    #: (None: keyword-only in practice)
    CONSTRUCTORS = {"zeros": 1, "ones": 1, "empty": 1, "full": 2,
                    "identity": 1, "linspace": None, "arange": None,
                    "eye": None}
    ACCUMULATORS = {"mean", "sum", "cumsum", "var", "std"}
    F64_REFS = {"numpy.float64", "numpy.double", "jax.numpy.float64"}

    def _in_scope(self, ctx, config) -> bool:
        return any(ctx.path.startswith(p) for p in config.float64_paths)

    def run(self, ctx, config):
        if not self._in_scope(ctx, config):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                yield from self._check_call(ctx, node)
            elif isinstance(node, (ast.Name, ast.Attribute)):
                resolved = ctx.imports.resolve(node)
                parent = getattr(node, "parent", None)
                # report the ref itself once (not again as parent pieces)
                if resolved in self.F64_REFS and not (
                    isinstance(parent, ast.Attribute)
                    and ctx.imports.resolve(parent) in self.F64_REFS
                ):
                    yield make_finding(
                        ctx, node, self.code,
                        f"explicit float64 (`{resolved}`) — blending "
                        f"accumulators are float32; if this float64 is "
                        f"deliberate, add `# graftlint: disable=GL004`",
                    )

    def _has_dtype_kwarg(self, call: ast.Call) -> bool:
        return any(kw.arg == "dtype" for kw in call.keywords)

    def _check_call(self, ctx, node: ast.Call):
        resolved = ctx.imports.resolve(node.func)
        if resolved and resolved.startswith("numpy."):
            attr = resolved.split(".")[1]
            dtype_pos = self.CONSTRUCTORS.get(attr)
            has_positional_dtype = (
                dtype_pos is not None and len(node.args) > dtype_pos
            )
            if attr in self.CONSTRUCTORS and not has_positional_dtype \
                    and not self._has_dtype_kwarg(node):
                yield make_finding(
                    ctx, node, self.code,
                    f"`{resolved}` without dtype= defaults to float64 "
                    f"(or int64) — pass dtype=np.float32/int32 explicitly",
                )
        elif isinstance(node.func, ast.Attribute) and resolved is None:
            attr = node.func.attr
            if attr in self.ACCUMULATORS and not self._has_dtype_kwarg(node):
                yield make_finding(
                    ctx, node, self.code,
                    f"`.{attr}()` accumulator without dtype= — promotes "
                    f"integer inputs to float64; pass dtype=np.float32",
                )
            elif attr == "astype" and node.args:
                arg = node.args[0]
                target = ctx.imports.resolve(arg)
                if target in self.F64_REFS or (
                    isinstance(arg, ast.Name) and arg.id == "float"
                ) or (
                    isinstance(arg, ast.Constant)
                    and arg.value in ("float64", "double")
                ):
                    yield make_finding(
                        ctx, node, self.code,
                        "`.astype(float64)` — blending data stays float32",
                    )


class JitWithoutDonation(Rule):
    """Chunk-sized array passed to jax.jit without donate_argnums.

    A jitted program whose parameters include a chunk-scale buffer
    (``chunk``, ``arr``, ``out``, ``weight``, ...) copies that buffer on
    every call unless it is donated; at production chunk sizes that is
    hundreds of MB of HBM traffic per task. Either donate
    (``donate_argnums``/``donate_argnames``) or suppress with a comment
    explaining why the caller still needs the buffer.
    """

    code = "GL005"
    name = "jit-without-donation"

    DONATE_KWARGS = {"donate_argnums", "donate_argnames"}

    def _chunk_params(self, fn) -> List[str]:
        args = fn.args
        names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
        return [n for n in names if n in CHUNK_PARAM_NAMES]

    def _has_donation(self, call_like) -> bool:
        if not isinstance(call_like, ast.Call):
            return False  # bare @jax.jit: no kwargs at all
        return any(
            kw.arg in self.DONATE_KWARGS for kw in call_like.keywords
        )

    def run(self, ctx, config):
        seen = set()
        for fn in ctx.functions:
            if isinstance(fn, ast.Lambda):
                continue
            for dec in fn.decorator_list:
                info = ctx.jit_decorator_info(dec)
                if info is None or self._has_donation(info):
                    continue
                chunky = self._chunk_params(fn)
                if chunky:
                    seen.add(id(fn))
                    yield make_finding(
                        ctx, dec, self.code,
                        f"`@jit` on `{fn.name}` takes chunk-sized "
                        f"`{chunky[0]}` but no donate_argnums — the buffer "
                        f"is copied every call", context=ctx.qualname_at(fn),
                    )
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and ctx.is_jit_ref(node.func)
                    and node.args):
                continue
            callee = ctx._callee_func(node.args[0], node)
            if callee is None or isinstance(callee, ast.Lambda) or \
                    id(callee) in seen:
                continue
            if self._has_donation(node):
                continue
            chunky = self._chunk_params(callee)
            if chunky:
                yield make_finding(
                    ctx, node, self.code,
                    f"`jax.jit({func_name(callee)})` takes chunk-sized "
                    f"`{chunky[0]}` but no donate_argnums — the buffer is "
                    f"copied every call",
                )


class AxisOrderHazard(Rule):
    """Axis shuffle on a chunk array without an axis-order annotation.

    Chunkflow is zyx everywhere (channel-leading czyx on device); a bare
    ``transpose``/``swapaxes``/``moveaxis``/``reshape`` on a chunk array
    is where xyz/zyx bugs are born. Annotate the line (or the one above)
    with a comment naming the order (``# czyx -> cxyz``, ``# axis 0=z``),
    or do the shuffle inside a helper whose NAME declares it
    (``transpose_*``, ``fold_*``, ``place``...).
    """

    code = "GL006"
    name = "axis-order-hazard"

    SHUFFLES = {"transpose", "swapaxes", "moveaxis", "reshape"}

    @staticmethod
    def _root_name(node: ast.AST):
        while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
            node = node.func if isinstance(node, ast.Call) else node.value
        return node.id if isinstance(node, ast.Name) else None

    def run(self, ctx, config):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            target = None
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in self.SHUFFLES:
                resolved = ctx.imports.resolve(node.func)
                if resolved is None:  # method on an array value
                    target = node.func.value
                elif resolved.split(".")[-1] in self.SHUFFLES and (
                    resolved.startswith("numpy.")
                    or resolved.startswith("jax.numpy.")
                ):
                    target = node.args[0] if node.args else None
            if target is None:
                continue
            root = self._root_name(target)
            if root not in CHUNK_VALUE_NAMES:
                continue
            if comment_matches(ctx.comments, node.lineno, _AXIS_COMMENT_RE):
                continue
            qual = ctx.qualname_at(node)
            if _AXIS_HELPER_RE.search(qual.split(".")[-1]):
                continue
            yield make_finding(
                ctx, node, self.code,
                f"`{node.func.attr}` on chunk array `{root}` without an "
                f"axis-order comment — annotate the zyx/xyz order on this "
                f"line or move it into a named axis helper",
            )


class TelemetryInJit(Rule):
    """Telemetry or wall-clock timing call inside a jit-traced function.

    ``time.time()`` / ``perf_counter()`` and the telemetry API
    (``span``, ``inc``, ``gauge``, ``observe``, ...) are host-side
    bookkeeping. Inside a traced function they measure TRACE time, not
    run time — executed once at compile, never per call — so the numbers
    are silently wrong; at worst the call concretizes a tracer. The
    telemetry layer's design rule #1 (core/telemetry.py) is that no
    instrumentation ever executes inside jitted code: time spans around
    the program (dispatch, block_until_ready, host copy), never in it.
    """

    code = "GL007"
    name = "telemetry-in-jit"

    TIMING_FUNCS = {
        "time.time", "time.time_ns", "time.perf_counter",
        "time.perf_counter_ns", "time.monotonic", "time.monotonic_ns",
        "time.process_time", "time.process_time_ns",
        "timeit.default_timer", "datetime.datetime.now",
    }
    TELEMETRY_MODULE = "chunkflow_tpu.core.telemetry"

    def run(self, ctx, config):
        for fn in ctx.traced:
            for node in walk_local(fn):
                if not isinstance(node, ast.Call):
                    continue
                resolved = ctx.imports.resolve(node.func)
                if resolved in self.TIMING_FUNCS:
                    yield make_finding(
                        ctx, node, self.code,
                        f"wall-clock call `{resolved}` inside jit-traced "
                        f"`{func_name(fn)}` — measures trace time, not run "
                        f"time; time the dispatch/wait from the host side",
                    )
                elif resolved is not None and resolved.startswith(
                        self.TELEMETRY_MODULE + "."):
                    api = resolved[len(self.TELEMETRY_MODULE) + 1:]
                    yield make_finding(
                        ctx, node, self.code,
                        f"telemetry call `{api}` inside jit-traced "
                        f"`{func_name(fn)}` — instrumentation never "
                        f"executes in compiled code (it would record "
                        f"trace-time only); hoist it to the call site",
                    )


RULES: List[Rule] = [
    HostSyncInJit(),
    NumpyOnTracer(),
    TracerControlFlow(),
    ImplicitFloat64(),
    JitWithoutDonation(),
    AxisOrderHazard(),
    TelemetryInJit(),
]

# The GL010-series concurrency rules live in their own module (they rest
# on the thread/lock model, not the jit-trace analysis); the import is
# deferred to the bottom because concurrency.py subclasses Rule.
from tools.graftlint.concurrency import CONCURRENCY_RULES  # noqa: E402

RULES.extend(CONCURRENCY_RULES)

# The GL020-series Pallas/Mosaic kernel soundness rules likewise live in
# their own module, resting on the pallas_call site model.
from tools.graftlint.pallas import PALLAS_RULES  # noqa: E402

RULES.extend(PALLAS_RULES)

RULES_BY_CODE = {r.code: r for r in RULES}
