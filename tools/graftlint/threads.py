"""Thread/lock model backing the GL010-series concurrency rules.

The concurrency half of graftlint needs to know three things about a
file that the jit-trace analysis (context.py) does not track:

1. **Which functions run on spawned threads** — the *thread context*.
   Seeds: ``threading.Thread(target=...)`` / ``threading.Timer``
   callbacks, ``executor.submit(fn, ...)``. Propagated to a fixpoint
   over the module-local call graph (``self.method()`` calls resolve
   within the enclosing class, bare names lexically), mirroring how
   traced-function membership propagates.
2. **Which objects are locks** — ``threading.Lock/RLock/Condition/
   Semaphore/Event`` constructions bound to module globals, ``self.X``
   attributes, or function locals. A ``Condition(existing_lock)`` is
   aliased to its underlying lock for ordering purposes (two conditions
   over one lock are ONE mutex).
3. **What is held where** — for every AST node, the stack of lock
   guards whose ``with`` block encloses it (:meth:`ThreadModel.
   iter_held`), plus lock-acquisition order edges across the functions
   of one class/module (:meth:`ThreadModel.order_edges`).

Like the traced analysis this is module-local and name-based on
purpose: cross-module lock graphs are the runtime sanitizer's job
(chunkflow_tpu/testing/locksmith.py), and inline suppressions absorb
the residual blind spots.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.graftlint.context import (
    FUNC_TYPES,
    FileContext,
    FuncNode,
    enclosing_function,
)

#: constructor -> synchronization-object kind
LOCK_CTORS = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
    "threading.Event": "event",
    "threading.Semaphore": "semaphore",
    "threading.BoundedSemaphore": "semaphore",
    "multiprocessing.Lock": "lock",
    "multiprocessing.RLock": "rlock",
    "multiprocessing.Condition": "condition",
    "multiprocessing.Event": "event",
}

#: kinds whose ``with X:`` block is a critical section (an Event is a
#: flag, not a guard; a Barrier cannot be held)
GUARD_KINDS = ("lock", "rlock", "condition", "semaphore")

#: a lock's identity within one file: ("mod", name) for module globals,
#: ("cls", ClassName, attr) for self attributes, ("loc", func_id, name)
#: for function locals
LockToken = Tuple[str, ...]


def enclosing_class(node: ast.AST) -> Optional[ast.ClassDef]:
    cur = getattr(node, "parent", None)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur
        cur = getattr(cur, "parent", None)
    return None


def token_display(token: LockToken) -> str:
    """Human-readable lock name for findings: ``self._lock`` /
    ``_STATE_LOCK``."""
    if token[0] == "cls":
        return f"self.{token[2]}"
    return str(token[-1])


def get_model(ctx: FileContext) -> "ThreadModel":
    """The (cached) thread/lock model for one file context."""
    model = getattr(ctx, "_thread_model", None)
    if model is None:
        model = ThreadModel(ctx)
        ctx._thread_model = model  # type: ignore[attr-defined]
    return model


class ThreadSpawn:
    """One ``threading.Thread(...)`` / ``Timer(...)`` construction site
    (GL013's unit of analysis)."""

    __slots__ = ("call", "daemon", "target_key", "in_collection")

    def __init__(self, call: ast.Call):
        self.call = call
        self.daemon = any(
            kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
            and kw.value.value is True
            for kw in call.keywords
        )
        #: ("name", n) / ("attr", a) — where the handle lands, if bound
        self.target_key: Optional[Tuple[str, str]] = None
        #: handle stored inside a list/dict/comprehension (joined via a
        #: loop over the container, not directly)
        self.in_collection = False


class ThreadModel:
    """Everything the GL01x rules need to know about one file."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.module_locks: Dict[str, str] = {}
        self.class_locks: Dict[str, Dict[str, str]] = {}
        self.local_locks: Dict[Tuple[int, str], str] = {}
        #: condition token -> the lock token it wraps (Condition(lock))
        self.cond_alias: Dict[LockToken, LockToken] = {}
        self.thread_entries: Set[FuncNode] = set()
        self.spawns: List[ThreadSpawn] = []
        #: (class name, method name) -> def node (direct class body only)
        self.methods: Dict[Tuple[str, str], FuncNode] = {}
        self._acquires_closure: Dict[int, Set[LockToken]] = {}
        self._collect_methods()
        self._collect_locks()
        self._collect_entries()

    # -- structure ----------------------------------------------------
    def _collect_methods(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.methods[(node.name, item.name)] = item

    def _lock_ctor_kind(self, value: ast.AST) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        return LOCK_CTORS.get(self.ctx.imports.resolve(value.func))

    def _collect_locks(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            kind = self._lock_ctor_kind(value)
            if kind is None:
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            fn = enclosing_function(node)
            for target in targets:
                token = self._bind_target(target, fn, kind)
                if token is None or kind != "condition":
                    continue
                # Condition(existing_lock): same mutex for ordering
                if isinstance(value, ast.Call) and value.args:
                    wrapped = self.lock_token(value.args[0], fn)
                    if wrapped is not None:
                        self.cond_alias[token] = wrapped[0]

    def _bind_target(self, target: ast.AST, fn: Optional[FuncNode],
                     kind: str) -> Optional[LockToken]:
        if isinstance(target, ast.Name):
            if fn is None:
                self.module_locks[target.id] = kind
                return ("mod", target.id)
            self.local_locks[(id(fn), target.id)] = kind
            return ("loc", str(id(fn)), target.id)
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self" and fn is not None:
            cls = enclosing_class(fn)
            if cls is not None:
                self.class_locks.setdefault(cls.name, {})[target.attr] = kind
                return ("cls", cls.name, target.attr)
        return None

    # -- lock tokens ---------------------------------------------------
    def lock_token(
        self, expr: ast.AST, fn: Optional[FuncNode]
    ) -> Optional[Tuple[LockToken, str]]:
        """(token, kind) when ``expr`` names a known synchronization
        object from ``fn``'s point of view; None otherwise."""
        if isinstance(expr, ast.Name):
            scope = fn
            while scope is not None:
                kind = self.local_locks.get((id(scope), expr.id))
                if kind is not None:
                    return ("loc", str(id(scope)), expr.id), kind
                scope = enclosing_function(scope)
            kind = self.module_locks.get(expr.id)
            if kind is not None:
                return ("mod", expr.id), kind
            return None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                and fn is not None:
            cls = enclosing_class(fn)
            if cls is not None:
                kind = self.class_locks.get(cls.name, {}).get(expr.attr)
                if kind is not None:
                    return ("cls", cls.name, expr.attr), kind
        return None

    def order_token(self, token: LockToken) -> LockToken:
        """The token used for lock-ORDER identity: a condition built
        over an existing lock is that lock."""
        return self.cond_alias.get(token, token)

    # -- thread-context analysis ---------------------------------------
    def _callee(self, expr: ast.AST, site: ast.AST) -> Optional[FuncNode]:
        """Resolve a callable reference: a lambda, a lexically visible
        function name, or a ``self.method`` of the enclosing class."""
        if isinstance(expr, ast.Lambda):
            return expr
        if isinstance(expr, ast.Name):
            return self.ctx.resolve_local(expr.id, site)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            cls = enclosing_class(site)
            if cls is not None:
                return self.methods.get((cls.name, expr.attr))
        return None

    def _collect_entries(self) -> None:
        seeds: Set[FuncNode] = set()
        for node in ast.walk(self.ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = self.ctx.imports.resolve(node.func)
            if resolved in ("threading.Thread", "threading.Timer"):
                spawn = ThreadSpawn(node)
                self._bind_spawn(spawn)
                self.spawns.append(spawn)
                target = None
                if resolved == "threading.Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target = kw.value
                elif len(node.args) >= 2:  # Timer(interval, function)
                    target = node.args[1]
                for kw in node.keywords:
                    if kw.arg == "function":
                        target = kw.value
                if target is not None:
                    callee = self._callee(target, node)
                    if callee is not None:
                        seeds.add(callee)
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("submit", "call_soon_threadsafe") \
                    and node.args:
                callee = self._callee(node.args[0], node)
                if callee is not None:
                    seeds.add(callee)
        # fixpoint over the module-local call graph: a function called
        # from a thread entry runs on that thread too
        worklist = list(seeds)
        entries = set(seeds)
        while worklist:
            fn = worklist.pop()
            for node, _held in self.iter_held(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = self._callee(node.func, node)
                if callee is not None and callee not in entries:
                    entries.add(callee)
                    worklist.append(callee)
        self.thread_entries = entries

    def _bind_spawn(self, spawn: ThreadSpawn) -> None:
        """Find where a Thread construction's handle is stored (walking
        out through list/dict/comprehension wrappers)."""
        node: ast.AST = spawn.call
        parent = getattr(node, "parent", None)
        while isinstance(parent, (ast.List, ast.Tuple, ast.Dict,
                                  ast.ListComp, ast.comprehension,
                                  ast.IfExp)):
            spawn.in_collection = True
            node = parent
            parent = getattr(parent, "parent", None)
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            target = parent.targets[0]
        elif isinstance(parent, (ast.AnnAssign, ast.AugAssign)):
            target = parent.target
        else:
            return
        if isinstance(target, ast.Name):
            spawn.target_key = ("name", target.id)
        elif isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self":
            spawn.target_key = ("attr", target.attr)

    # -- held-lock traversal -------------------------------------------
    def iter_held(
        self, fn: FuncNode
    ) -> Iterator[Tuple[ast.AST, Tuple[Tuple[LockToken, str], ...]]]:
        """Yield every node in ``fn``'s own body (not nested functions)
        with the tuple of (token, kind) guards held at that point —
        guards being ``with <lock>`` blocks over known lock objects."""
        body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
        for stmt in body:
            yield from self._iter(stmt, (), fn)

    def _iter(self, node, held, fn):
        yield node, held
        if isinstance(node, FUNC_TYPES):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = list(held)
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    yield sub, held
                tok = self.lock_token(item.context_expr, fn)
                if tok is not None and tok[1] in GUARD_KINDS:
                    inner.append(tok)
            for stmt in node.body:
                yield from self._iter(stmt, tuple(inner), fn)
            return
        for child in ast.iter_child_nodes(node):
            yield from self._iter(child, held, fn)

    # -- lock-order edges ----------------------------------------------
    def _direct_acquires(self, fn: FuncNode) -> Set[LockToken]:
        out: Set[LockToken] = set()
        for node, _held in self.iter_held(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    tok = self.lock_token(item.context_expr, fn)
                    if tok is not None and tok[1] in GUARD_KINDS:
                        out.add(self.order_token(tok[0]))
        return out

    def acquires_closure(self, fn: FuncNode) -> Set[LockToken]:
        """Every lock ``fn`` may acquire, directly or through
        module-local callees (fixpoint, cycle-safe)."""
        cached = self._acquires_closure.get(id(fn))
        if cached is not None:
            return cached
        self._acquires_closure[id(fn)] = set()  # cycle guard
        out = set(self._direct_acquires(fn))
        for node, _held in self.iter_held(fn):
            if isinstance(node, ast.Call):
                callee = self._callee(node.func, node)
                if callee is not None and callee is not fn:
                    out |= self.acquires_closure(callee)
        self._acquires_closure[id(fn)] = out
        return out

    def order_edges(
        self,
    ) -> Dict[Tuple[LockToken, LockToken], ast.AST]:
        """Directed lock-order edges over the whole file:
        ``(held, acquired) -> first AST node establishing the edge``.
        Includes edges through one level of module-local calls (holding
        A while calling a function whose closure acquires B)."""
        edges: Dict[Tuple[LockToken, LockToken], ast.AST] = {}

        def add(a: LockToken, b: LockToken, site: ast.AST) -> None:
            if a != b and (a, b) not in edges:
                edges[(a, b)] = site

        for fn in self.ctx.functions:
            for node, held in self.iter_held(fn):
                if not held:
                    continue
                held_tokens = [self.order_token(t) for t, _k in held]
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        tok = self.lock_token(item.context_expr, fn)
                        if tok is None or tok[1] not in GUARD_KINDS:
                            continue
                        acquired = self.order_token(tok[0])
                        for h in held_tokens:
                            add(h, acquired, node)
                elif isinstance(node, ast.Call):
                    callee = self._callee(node.func, node)
                    if callee is None:
                        continue
                    for acquired in self.acquires_closure(callee):
                        for h in held_tokens:
                            add(h, acquired, node)
        return edges
