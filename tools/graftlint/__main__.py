import sys

from tools.graftlint.cli import main

if __name__ == "__main__":
    sys.exit(main())
