"""The GL020-series: Pallas/Mosaic kernel soundness rules (ISSUE 16).

The only defect class that has ever broken this repo ON HARDWARE —
Mosaic's "failed to prove that a tile index ... is divisible by the
tiling (8)" alignment proof (ops/pallas_blend.py round-1 failure) —
plus VMEM overspill, scratch read-before-write and async-copy protocol
bugs are all invisible on the CPU box: they surface only at Mosaic
compile/run time inside a scarce tunnel window. These rules move the
statically-provable share of that class to lint time; the runtime half
is the kernelcheck interpret-mode sanitizer
(chunkflow_tpu/testing/kernelcheck.py).

The rules rest on a per-file Pallas kernel model (:class:`PallasModel`):
every ``pl.pallas_call`` site with its kernel function, grid spec
(``PrefetchScalarGridSpec``/``GridSpec``), BlockSpecs (memory space,
block shape, index-map constancy), scratch shapes, scalar-prefetch
count, ``input_output_aliases`` and ``interpret`` kwarg — plus the
positional mapping from kernel parameters to those roles (scalar
prefetch args, then inputs, then outputs, then scratch: the Pallas
calling convention).

Like every graftlint analysis this is module-local, name-based and
fold-what-you-can: symbolic shapes (the shipping kernels' ``py``/``px``
arguments) make a quantity unfoldable and the affected check SKIPS
rather than guesses — a lint that cries wolf on the kernels it exists
to protect would be deleted within a week. Deliberate exceptions get
``# graftlint: disable=GL02x`` with a justification.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.graftlint.context import (
    FileContext,
    FuncNode,
    enclosing_function,
    walk_local,
)
from tools.graftlint.model import Finding, make_finding
from tools.graftlint.rules import Rule

#: Mosaic sublane tilings of the second-minor dim by dtype width
#: (f32 8, 16-bit 16, 8-bit 32); the minor dim is always 128 lanes
SUBLANE_TILINGS = (8, 16, 32)
LANE_TILING = 128

#: analytic VMEM budgets by device kind, bytes. ~16 MiB/core holds for
#: every generation this repo targets; the table exists so a future
#: part with a different budget is one entry, and CHUNKFLOW_VMEM_BUDGET
#: overrides outright (CI boxes lint for a specific target).
VMEM_BUDGETS: Dict[str, int] = {
    "tpu v3": 16 * 2**20,
    "tpu v4": 16 * 2**20,
    "tpu v5e": 16 * 2**20,
    "tpu v5p": 16 * 2**20,
    "tpu v6": 32 * 2**20,
    "default": 16 * 2**20,
}

#: jnp/np dtype name -> itemsize, for scratch-shape byte accounting
DTYPE_SIZES = {
    "float64": 8, "int64": 8, "uint64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool_": 1, "bool": 1,
    "float8_e4m3fn": 1, "float8_e5m2": 1,
}


def vmem_budget_bytes() -> int:
    """The device VMEM budget GL021 lints against:
    ``CHUNKFLOW_VMEM_BUDGET`` (bytes) wins outright; otherwise
    ``CHUNKFLOW_VMEM_DEVICE`` picks a :data:`VMEM_BUDGETS` row by
    substring (default row when unset/unmatched)."""
    raw = os.environ.get("CHUNKFLOW_VMEM_BUDGET", "").strip()
    if raw:
        try:
            return max(1, int(float(raw)))
        except ValueError:
            pass
    kind = os.environ.get("CHUNKFLOW_VMEM_DEVICE", "").lower()
    for needle, budget in VMEM_BUDGETS.items():
        if needle != "default" and needle in kind:
            return budget
    return VMEM_BUDGETS["default"]


# ---------------------------------------------------------------------------
# constant folding over module + function-local int bindings
# ---------------------------------------------------------------------------
def _const_env(ctx: FileContext, func: Optional[FuncNode]) -> Dict[str, int]:
    """Name -> int for simple constant assignments visible at ``func``:
    module-level ``_SUBLANE = 8`` style bindings plus the function's own
    locals. Reassigned names are dropped (ambiguous)."""
    env: Dict[str, int] = {}
    ambiguous: Set[str] = set()

    def note(target: ast.AST, value: ast.AST) -> None:
        if not isinstance(target, ast.Name):
            return
        folded = _fold_int(value, env)
        if folded is None or target.id in ambiguous:
            env.pop(target.id, None)
            ambiguous.add(target.id)
        elif target.id in env and env[target.id] != folded:
            env.pop(target.id)
            ambiguous.add(target.id)
        else:
            env[target.id] = folded

    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            note(node.targets[0], node.value)
    scope = func
    while scope is not None:
        if not isinstance(scope, ast.Lambda):
            for node in walk_local(scope):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    note(node.targets[0], node.value)
        scope = enclosing_function(scope)
    return env


def _fold_int(node: Optional[ast.AST],
              env: Dict[str, int]) -> Optional[int]:
    """Fold an expression to an int using ``env``; None when symbolic."""
    if node is None:
        return None
    if isinstance(node, ast.Constant):
        return node.value if isinstance(node.value, int) \
            and not isinstance(node.value, bool) else None
    if isinstance(node, ast.Name):
        return env.get(node.id)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _fold_int(node.operand, env)
        return -inner if inner is not None else None
    if isinstance(node, ast.BinOp):
        left = _fold_int(node.left, env)
        right = _fold_int(node.right, env)
        if left is None or right is None:
            return None
        if isinstance(node.op, ast.Add):
            return left + right
        if isinstance(node.op, ast.Sub):
            return left - right
        if isinstance(node.op, ast.Mult):
            return left * right
        if isinstance(node.op, ast.FloorDiv) and right != 0:
            return left // right
        if isinstance(node.op, ast.Mod) and right != 0:
            return left % right
        if isinstance(node.op, ast.Pow) and right >= 0:
            return left ** right
    return None


def _fold_shape(node: Optional[ast.AST],
                env: Dict[str, int]) -> Optional[Tuple[int, ...]]:
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    dims = [_fold_int(elt, env) for elt in node.elts]
    if any(d is None for d in dims):
        return None
    return tuple(dims)  # type: ignore[arg-type]


def _dtype_size(ctx: FileContext, node: Optional[ast.AST]) -> Optional[int]:
    """Itemsize of a dtype reference like ``jnp.float32``; None when the
    dtype is a runtime value (``chunk.dtype``)."""
    if node is None:
        return None
    resolved = ctx.imports.resolve(node)
    name = resolved.rsplit(".", 1)[-1] if resolved else (
        node.attr if isinstance(node, ast.Attribute) else (
            node.id if isinstance(node, ast.Name) else None))
    return DTYPE_SIZES.get(name) if name else None


def _resolve_tail(ctx: FileContext, node: ast.AST) -> str:
    """The resolved dotted path of a call target, or its syntactic tail
    when the root is not an import alias ('' when neither applies)."""
    resolved = ctx.imports.resolve(node)
    if resolved:
        return resolved
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    return ".".join(reversed(parts))


def _is_call_to(ctx: FileContext, node: ast.AST, suffix: str) -> bool:
    return isinstance(node, ast.Call) and \
        _resolve_tail(ctx, node.func).endswith(suffix)


def _local_value(ctx: FileContext, name: str,
                 at: ast.AST) -> Optional[ast.AST]:
    """The value last assigned to ``name`` in the scope chain of ``at``
    (lexical, source order — good enough for the build-then-call shape
    every pallas_call site in this repo has)."""
    scope = enclosing_function(at)
    while True:
        body = walk_local(scope) if scope is not None else \
            ast.walk(ctx.tree)
        hit: Optional[ast.AST] = None
        for node in body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == name:
                if hit is None or node.lineno <= getattr(at, "lineno", 1):
                    hit = node.value
        if hit is not None:
            return hit
        if scope is None:
            return None
        scope = enclosing_function(scope)


# ---------------------------------------------------------------------------
# the model
# ---------------------------------------------------------------------------
@dataclass
class BlockSpecInfo:
    """One parsed ``pl.BlockSpec`` (or an unparseable stand-in)."""

    node: Optional[ast.AST] = None
    any_space: bool = False        # memory_space=pl.ANY / pltpu.HBM
    shape: Optional[Tuple[int, ...]] = None  # folded block shape
    has_block_shape: bool = False
    constant_index: bool = False   # index_map returns only constants


@dataclass
class ScratchInfo:
    """One parsed scratch_shapes entry."""

    node: Optional[ast.AST] = None
    kind: str = "other"            # 'vmem' | 'smem' | 'sem' | 'other'
    nbytes: Optional[int] = None   # folded shape x dtype size


@dataclass
class PallasCallSite:
    """One ``pl.pallas_call`` site with everything the rules inspect."""

    call: ast.Call
    builder: Optional[FuncNode]            # enclosing function
    kernel: Optional[FuncNode] = None
    num_scalar_prefetch: int = 0
    grid: Optional[ast.AST] = None
    in_specs: List[BlockSpecInfo] = field(default_factory=list)
    out_specs: List[BlockSpecInfo] = field(default_factory=list)
    scratch: List[ScratchInfo] = field(default_factory=list)
    #: folded input_output_aliases; None = kwarg absent;
    #: "unknown" = present but not a literal dict
    aliases: object = None
    interpret: Optional[ast.AST] = None    # the kwarg's value node
    #: kernel param name -> (kind, index within kind); kinds:
    #: 'scalar' | 'in' | 'out' | 'scratch'. Empty when the param count
    #: does not reconcile with the spec counts (model incomplete).
    params: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    env: Dict[str, int] = field(default_factory=dict)


class PallasModel:
    """Every pallas_call site in one file, parsed once per file."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.sites: List[PallasCallSite] = []
        #: module defines/imports a ``*_mode`` selector (GL024)
        self.has_mode_selector = self._find_mode_selector(ctx)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    _resolve_tail(ctx, node.func).endswith("pallas_call"):
                self.sites.append(self._parse_site(node))

    @staticmethod
    def _find_mode_selector(ctx: FileContext) -> bool:
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name.endswith("_mode"):
                return True
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if (alias.asname or alias.name).endswith("_mode"):
                        return True
        return False

    # -- parsing -------------------------------------------------------
    def _parse_site(self, call: ast.Call) -> PallasCallSite:
        ctx = self.ctx
        builder = enclosing_function(call)
        site = PallasCallSite(call=call, builder=builder)
        site.env = _const_env(ctx, builder)

        # the kernel function: first positional arg
        if call.args:
            arg = call.args[0]
            if isinstance(arg, ast.Lambda):
                site.kernel = arg
            elif isinstance(arg, ast.Name):
                site.kernel = ctx.resolve_local(arg.id, call)

        kwargs = {kw.arg: kw.value for kw in call.keywords if kw.arg}
        site.interpret = kwargs.get("interpret")

        # grid spec: inline kwargs or a grid_spec object
        spec_kwargs = dict(kwargs)
        grid_spec = kwargs.get("grid_spec")
        if isinstance(grid_spec, ast.Name):
            grid_spec = _local_value(ctx, grid_spec.id, call)
        if isinstance(grid_spec, ast.Call):
            for kw in grid_spec.keywords:
                if kw.arg:
                    spec_kwargs.setdefault(kw.arg, kw.value)

        nsp = _fold_int(spec_kwargs.get("num_scalar_prefetch"), site.env)
        site.num_scalar_prefetch = nsp or 0
        site.grid = spec_kwargs.get("grid")
        site.in_specs = self._parse_spec_list(
            spec_kwargs.get("in_specs"), call)
        site.out_specs = self._parse_spec_list(
            spec_kwargs.get("out_specs"), call)
        site.scratch = self._parse_scratch(
            spec_kwargs.get("scratch_shapes"), call, site.env)
        site.aliases = self._parse_aliases(
            kwargs.get("input_output_aliases"), call, site.env)

        # out_specs may be implicit: one output per out_shape entry
        if not site.out_specs:
            out_shape = kwargs.get("out_shape")
            n_out = len(out_shape.elts) if isinstance(
                out_shape, (ast.List, ast.Tuple)) else 1
            site.out_specs = [BlockSpecInfo() for _ in range(n_out)]

        self._map_params(site)
        return site

    def _parse_spec_list(self, node: Optional[ast.AST],
                         at: ast.AST) -> List[BlockSpecInfo]:
        if isinstance(node, ast.Name):
            node = _local_value(self.ctx, node.id, at)
        if node is None:
            return []
        if isinstance(node, (ast.List, ast.Tuple)):
            return [self._parse_spec(elt, at) for elt in node.elts]
        return [self._parse_spec(node, at)]

    def _parse_spec(self, node: ast.AST, at: ast.AST) -> BlockSpecInfo:
        ctx = self.ctx
        if isinstance(node, ast.Name):
            resolved = _local_value(ctx, node.id, at)
            if resolved is not None:
                node = resolved
        info = BlockSpecInfo(node=node)
        if not _is_call_to(ctx, node, "BlockSpec"):
            return info
        assert isinstance(node, ast.Call)
        kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
        space = kwargs.get("memory_space")
        if space is not None:
            tail = _resolve_tail(ctx, space)
            info.any_space = tail.endswith(".ANY") or tail.endswith(".HBM")
        shape_node = node.args[0] if node.args else kwargs.get(
            "block_shape")
        if isinstance(shape_node, (ast.Tuple, ast.List)):
            info.has_block_shape = True
            env = _const_env(ctx, enclosing_function(at))
            info.shape = _fold_shape(shape_node, env)
        index_map = (node.args[1] if len(node.args) > 1
                     else kwargs.get("index_map"))
        if isinstance(index_map, ast.Lambda):
            body = index_map.body
            elts = body.elts if isinstance(body, ast.Tuple) else [body]
            info.constant_index = all(
                isinstance(e, ast.Constant) for e in elts)
        return info

    def _parse_scratch(self, node: Optional[ast.AST], at: ast.AST,
                       env: Dict[str, int]) -> List[ScratchInfo]:
        if isinstance(node, ast.Name):
            node = _local_value(self.ctx, node.id, at)
        if not isinstance(node, (ast.List, ast.Tuple)):
            return []
        out: List[ScratchInfo] = []
        for elt in node.elts:
            info = ScratchInfo(node=elt)
            tail = _resolve_tail(self.ctx, elt.func) if isinstance(
                elt, ast.Call) else ""
            if "SemaphoreType" in tail:
                info.kind = "sem"
            elif tail.endswith(".VMEM") or tail.endswith(".SMEM"):
                info.kind = "vmem" if tail.endswith(".VMEM") else "smem"
                assert isinstance(elt, ast.Call)
                shape = _fold_shape(
                    elt.args[0] if elt.args else None, env)
                size = _dtype_size(
                    self.ctx, elt.args[1] if len(elt.args) > 1 else None)
                if shape is not None and size is not None:
                    nbytes = size
                    for d in shape:
                        nbytes *= d
                    info.nbytes = nbytes
            out.append(info)
        return out

    @staticmethod
    def _parse_aliases(node: Optional[ast.AST], at: ast.AST,
                       env: Dict[str, int]) -> object:
        if node is None:
            return None
        if isinstance(node, ast.Dict):
            folded: Dict[int, int] = {}
            for k, v in zip(node.keys, node.values):
                ki, vi = _fold_int(k, env), _fold_int(v, env)
                if ki is None or vi is None:
                    return "unknown"
                folded[ki] = vi
            return folded
        return "unknown"

    @staticmethod
    def _map_params(site: PallasCallSite) -> None:
        if site.kernel is None:
            return
        args = site.kernel.args
        names = [a.arg for a in args.posonlyargs + args.args]
        counts = (site.num_scalar_prefetch, len(site.in_specs),
                  len(site.out_specs), len(site.scratch))
        if len(names) != sum(counts):
            return  # model incomplete: rules needing the mapping skip
        kinds = ("scalar", "in", "out", "scratch")
        i = 0
        for kind, count in zip(kinds, counts):
            for j in range(count):
                site.params[names[i]] = (kind, j)
                i += 1


def get_pallas_model(ctx: FileContext) -> PallasModel:
    model = getattr(ctx, "_pallas_model", None)
    if model is None:
        model = PallasModel(ctx)
        ctx._pallas_model = model  # type: ignore[attr-defined]
    return model


# ---------------------------------------------------------------------------
# kernel-body helpers shared by the rules
# ---------------------------------------------------------------------------
def _ref_of_subscript(node: ast.Subscript) -> Optional[str]:
    """The base ref name of ``ref[...]`` / ``ref.at[...]``."""
    value = node.value
    if isinstance(value, ast.Attribute) and value.attr == "at":
        value = value.value
    if isinstance(value, ast.Name):
        return value.id
    return None


def _index_elts(node: ast.Subscript) -> List[ast.AST]:
    idx = node.slice
    return list(idx.elts) if isinstance(idx, ast.Tuple) else [idx]


def _multiple_of_hints(ctx: FileContext,
                       kernel: FuncNode) -> Dict[str, ast.AST]:
    """name -> divisor expression for ``x = pl.multiple_of(expr, N)``
    bindings in the kernel body."""
    hints: Dict[str, ast.AST] = {}
    for node in walk_local(kernel):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and _is_call_to(ctx, node.value, ".multiple_of") \
                and len(node.value.args) > 1:
            hints[node.targets[0].id] = node.value.args[1]
    return hints


def _start_aligned(ctx: FileContext, expr: ast.AST, required: int,
                   hints: Dict[str, ast.AST],
                   env: Dict[str, int]) -> bool:
    """Whether a slice-start expression is provably aligned to the
    tiling: a divisible constant, a ``pl.multiple_of`` hint (inline or
    via a hinted local) whose divisor is a multiple of ``required`` (an
    unfoldable divisor gets the benefit of the doubt — the hint's
    PRESENCE is what this rule enforces; a wrong divisor still fails at
    Mosaic compile), or arithmetic that preserves alignment."""
    if isinstance(expr, ast.Constant):
        return isinstance(expr.value, int) and expr.value % required == 0
    if isinstance(expr, ast.Name):
        folded = _fold_int(expr, env)
        if folded is not None:
            return folded % required == 0
        divisor = hints.get(expr.id)
        if divisor is None:
            return False
        return _divisor_ok(divisor, required, env)
    if _is_call_to(ctx, expr, ".multiple_of") and len(expr.args) > 1:
        return _divisor_ok(expr.args[1], required, env)
    if isinstance(expr, ast.BinOp):
        if isinstance(expr.op, ast.Mult):
            for side in (expr.left, expr.right):
                folded = _fold_int(side, env)
                if folded is not None and folded % required == 0:
                    return True
            return False
        if isinstance(expr.op, (ast.Add, ast.Sub)):
            return all(
                _start_aligned(ctx, side, required, hints, env)
                for side in (expr.left, expr.right)
            )
    return False


def _divisor_ok(divisor: ast.AST, required: int,
                env: Dict[str, int]) -> bool:
    folded = _fold_int(divisor, env)
    if folded is None:
        return True  # hint present, divisor symbolic: benefit of doubt
    return folded % required == 0


# ---------------------------------------------------------------------------
# GL020: unaligned DMA slice corner
# ---------------------------------------------------------------------------
class UnalignedDmaSlice(Rule):
    """Dynamic slice corner into the minor dims of an ANY-space ref
    without a ``pl.multiple_of`` tiling hint.

    Mosaic requires DMA slice offsets into the two minor dims of a
    tiled HBM/ANY memref *provably* divisible by the dtype tiling —
    (sublane, 128) with sublane 8 for f32, 16 for 16-bit, 32 for 8-bit
    dtypes. A runtime index (a prefetched starts-table entry) carries no
    such proof, and the kernel dies at Mosaic compile time with
    "failed to prove that a tile index ... is divisible by the tiling"
    — the round-1 hardware failure of ops/pallas_blend.py, visible only
    inside a scarce TPU tunnel window. Round the corner down to the
    tiling host-side and hint it (``pl.multiple_of(start, 8)`` /
    ``(start, 128)``), then address the patch at its (dy, dx) offset
    inside the aligned VMEM window (the shipping kernels' pattern).
    """

    code = "GL020"
    name = "unaligned-dma-slice"

    def run(self, ctx: FileContext, config) -> Iterator[Finding]:
        model = get_pallas_model(ctx)
        for site in model.sites:
            if site.kernel is None or not site.params:
                continue
            any_refs = {
                name for name, (kind, j) in site.params.items()
                if kind == "in" and site.in_specs[j].any_space
                or kind == "out" and site.out_specs[j].any_space
            }
            if not any_refs:
                continue
            hints = _multiple_of_hints(ctx, site.kernel)
            for node in walk_local(site.kernel):
                if not isinstance(node, ast.Subscript):
                    continue
                ref = _ref_of_subscript(node)
                if ref not in any_refs:
                    continue
                elts = _index_elts(node)
                if len(elts) < 2:
                    continue
                checks = (
                    (elts[-2], "second-minor", min(SUBLANE_TILINGS),
                     "8/16/32"),
                    (elts[-1], "minor", LANE_TILING, "128"),
                )
                for elt, dim, required, tiling in checks:
                    start = elt.args[0] if _is_call_to(ctx, elt, ".ds") \
                        and elt.args else elt
                    if isinstance(start, ast.Slice):
                        start = start.lower or ast.Constant(value=0)
                    if not _start_aligned(ctx, start, required,
                                          hints, site.env):
                        yield make_finding(
                            ctx, node, self.code,
                            f"dynamic {dim}-dim slice corner into "
                            f"ANY-space ref `{ref}` without a "
                            f"`pl.multiple_of` hint matching the dtype "
                            f"tiling ({tiling}) — Mosaic cannot prove "
                            f"divisibility and fails at compile time "
                            f"on hardware; round the corner down and "
                            f"add the hint",
                        )


# ---------------------------------------------------------------------------
# GL021: analytic VMEM budget overflow
# ---------------------------------------------------------------------------
class VmemBudgetOverflow(Rule):
    """Analytic VMEM footprint exceeds the device budget.

    Per grid step a pallas_call holds: every blocked (non-ANY) in/out
    window — DOUBLED for non-constant-index blocks, which the pipeline
    double-buffers — plus every VMEM/SMEM scratch allocation. When that
    sum (folding what is constant-foldable; symbolic dims make a block
    unaccountable and it contributes nothing — this rule under-counts
    rather than guesses) exceeds the device VMEM budget
    (:func:`vmem_budget_bytes`; ``CHUNKFLOW_VMEM_BUDGET`` overrides,
    ``CHUNKFLOW_VMEM_DEVICE`` picks the table row), the kernel cannot
    compile on hardware — another failure class invisible on the CPU
    box. Block dtypes are unknown statically and assumed float32
    (4 bytes); scratch entries carry their dtype and are counted
    exactly. ``tools/kernel_report.py`` prints the same arithmetic with
    runtime shapes filled in.
    """

    code = "GL021"
    name = "vmem-budget-overflow"

    def run(self, ctx: FileContext, config) -> Iterator[Finding]:
        model = get_pallas_model(ctx)
        budget = vmem_budget_bytes()
        for site in model.sites:
            total = 0
            accounted = []
            for spec in site.in_specs + site.out_specs:
                if spec.any_space or spec.shape is None:
                    continue
                elems = 1
                for d in spec.shape:
                    elems *= d
                nbytes = elems * 4  # dtype unknown statically: assume f32
                if not spec.constant_index:
                    nbytes *= 2  # double-buffered by the pipeline
                total += nbytes
                accounted.append(nbytes)
            for scratch in site.scratch:
                if scratch.nbytes:
                    total += scratch.nbytes
                    accounted.append(scratch.nbytes)
            if total > budget:
                yield make_finding(
                    ctx, site.call, self.code,
                    f"analytic VMEM footprint {total} bytes "
                    f"({len(accounted)} accounted windows/scratch, "
                    f"double-buffered blocks x2) exceeds the device "
                    f"budget {budget} — the kernel cannot compile on "
                    f"hardware; shrink the block windows or override "
                    f"CHUNKFLOW_VMEM_BUDGET if the target differs",
                )


# ---------------------------------------------------------------------------
# GL022: in-place RMW output not aliased
# ---------------------------------------------------------------------------
class RmwOutputNotAliased(Rule):
    """A kernel output that is READ in the kernel body without an
    ``input_output_aliases`` entry.

    Reading an output ref (as an async-copy source or a subscript load)
    makes the kernel a read-modify-write over that buffer — its initial
    contents matter. Without ``input_output_aliases`` tying an input to
    that output, XLA materializes the output as a FRESH buffer: on the
    CPU interpreter the read sees zeros and the accumulate silently
    drops prior contributions; under donation the behavior differs
    between backends. Pass the buffer as an input and alias it
    (``input_output_aliases={in_idx: out_idx}`` — the fused blend
    kernel's pattern), or don't read the output.
    """

    code = "GL022"
    name = "rmw-output-not-aliased"

    def run(self, ctx: FileContext, config) -> Iterator[Finding]:
        model = get_pallas_model(ctx)
        for site in model.sites:
            if site.kernel is None or not site.params:
                continue
            if site.aliases == "unknown":
                continue  # present but unfoldable: benefit of the doubt
            aliased_outputs = set(
                site.aliases.values()) if isinstance(
                site.aliases, dict) else set()
            out_refs = {
                name: j for name, (kind, j) in site.params.items()
                if kind == "out"
            }
            read = self._read_outputs(ctx, site, out_refs)
            for name, node in read.items():
                j = out_refs[name]
                if j not in aliased_outputs:
                    yield make_finding(
                        ctx, node, self.code,
                        f"output ref `{name}` (output {j}) is read in "
                        f"the kernel body but no input_output_aliases "
                        f"entry aliases an input to it — the RMW reads "
                        f"an undefined fresh buffer; alias the operand "
                        f"(input_output_aliases={{in_idx: {j}}})",
                    )

    @staticmethod
    def _read_outputs(ctx: FileContext, site: PallasCallSite,
                      out_refs: Dict[str, int]) -> Dict[str, ast.AST]:
        """output param name -> first node where it is READ. A read is a
        Load-context subscript on the ref, or the ref (directly or via a
        ``x = ref.at[...]`` binding) used as an async-copy SOURCE."""
        reads: Dict[str, ast.AST] = {}
        at_bindings: Dict[str, str] = {}
        for node in walk_local(site.kernel):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Subscript):
                base = _ref_of_subscript(node.value)
                if base in out_refs:
                    at_bindings[node.targets[0].id] = base
        for node in walk_local(site.kernel):
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, ast.Load) and \
                    not (isinstance(node.value, ast.Attribute)
                         and node.value.attr == "at"):
                base = _ref_of_subscript(node)
                if base in out_refs:
                    reads.setdefault(base, node)
            if _is_call_to(ctx, node, "make_async_copy") and node.args:
                src = node.args[0]
                base = None
                if isinstance(src, ast.Name):
                    base = at_bindings.get(src.id)
                    if src.id in out_refs:
                        base = src.id
                elif isinstance(src, ast.Subscript):
                    base = _ref_of_subscript(src)
                if base in out_refs:
                    reads.setdefault(base, node)
        return reads


# ---------------------------------------------------------------------------
# GL023: async-copy protocol
# ---------------------------------------------------------------------------
class AsyncCopyProtocol(Rule):
    """Started-but-unwaited ``make_async_copy``, or a DMA semaphore
    reused by overlapping copies.

    A DMA that is ``.start()``ed but never ``.wait()``ed races the
    compute that reads its destination (or the next grid step reusing
    the scratch); a second copy started on the SAME semaphore while the
    first is still in flight makes the waits ambiguous — either copy's
    completion satisfies either wait, including across ``pl.when`` arms
    where only one copy actually ran. Every started copy needs its wait
    on every path, and concurrent copies need distinct semaphores.
    Statements are scanned in source order with ``@pl.when`` arms
    inlined at their definition point (that is their execution point).
    """

    code = "GL023"
    name = "async-copy-protocol"

    def run(self, ctx: FileContext, config) -> Iterator[Finding]:
        model = get_pallas_model(ctx)
        for site in model.sites:
            if site.kernel is None or isinstance(site.kernel, ast.Lambda):
                continue
            yield from self._scan(ctx, site.kernel)

    def _scan(self, ctx: FileContext,
              kernel: FuncNode) -> Iterator[Finding]:
        copies: Dict[str, dict] = {}     # name -> {sem, started, waited}
        outstanding: Dict[str, dict] = {}  # sem name -> copy rec
        findings: List[Finding] = []

        def sem_of(call: ast.Call) -> Optional[str]:
            kwargs = {kw.arg: kw.value for kw in call.keywords if kw.arg}
            sem = call.args[2] if len(call.args) > 2 else \
                kwargs.get("sem")
            return sem.id if isinstance(sem, ast.Name) else None

        def start(rec: dict, node: ast.AST) -> None:
            rec["started"] = node
            sem = rec.get("sem")
            if sem is None:
                return
            other = outstanding.get(sem)
            if other is not None and other is not rec:
                findings.append(make_finding(
                    ctx, node, self.code,
                    f"DMA semaphore `{sem}` is reused by overlapping "
                    f"copies: a copy started on it has not been waited "
                    f"— either wait first or use a distinct semaphore",
                ))
            outstanding[sem] = rec

        def wait(rec: dict) -> None:
            rec["waited"] = True
            sem = rec.get("sem")
            if sem is not None and outstanding.get(sem) is rec:
                del outstanding[sem]

        def visit(stmts: List[ast.stmt]) -> None:
            for stmt in stmts:
                if isinstance(stmt, ast.FunctionDef):
                    # @pl.when arms execute where they are defined
                    visit(stmt.body)
                    continue
                if isinstance(stmt, ast.Assign) and \
                        len(stmt.targets) == 1 and \
                        isinstance(stmt.targets[0], ast.Name) and \
                        _is_call_to(ctx, stmt.value, "make_async_copy"):
                    copies[stmt.targets[0].id] = {
                        "sem": sem_of(stmt.value), "node": stmt.value,
                        "started": None, "waited": False,
                    }
                    continue
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call) or \
                            not isinstance(node.func, ast.Attribute):
                        continue
                    owner = node.func.value
                    if node.func.attr in ("start", "wait") and \
                            isinstance(owner, ast.Name) and \
                            owner.id in copies:
                        rec = copies[owner.id]
                        if node.func.attr == "start":
                            start(rec, node)
                        else:
                            wait(rec)
                    elif node.func.attr == "start" and \
                            _is_call_to(ctx, owner, "make_async_copy"):
                        # inline chain: can never be waited
                        rec = {"sem": sem_of(owner), "node": node,
                               "started": node, "waited": False}
                        copies[f"<inline:{node.lineno}>"] = rec
                        start(rec, node)
                if isinstance(stmt, (ast.If, ast.For, ast.While,
                                     ast.With)):
                    visit(stmt.body)
                    visit(getattr(stmt, "orelse", []))

        visit(kernel.body)
        for name, rec in copies.items():
            if rec["started"] is not None and not rec["waited"]:
                findings.append(make_finding(
                    ctx, rec["started"], self.code,
                    f"async copy `{name}` is started but never waited "
                    f"— the DMA races every read of its destination; "
                    f"call .wait() before the data is used",
                ))
        yield from findings


# ---------------------------------------------------------------------------
# GL024: unguarded pallas_call site
# ---------------------------------------------------------------------------
class UnguardedPallasCall(Rule):
    """A ``pl.pallas_call`` site with no mode selector and no dynamic
    ``interpret=`` seam.

    A compiled Mosaic kernel hard-fails on a CPU box (and on any box
    whose platform string the code did not anticipate). Every kernel in
    this repo sits behind a ``pallas_mode()``/``gather_mode()``-style
    env selector (core/envmode.py) so the XLA fallback runs by default
    and CPU tests run the kernel in interpret mode. A bare pallas_call
    — module defines/imports no ``*_mode`` selector AND the call's
    ``interpret`` kwarg is absent or a literal — has no off-ramp. Add a
    selector (and fold it into the program cache key so env flips
    rebuild), or thread ``interpret=`` through from one.
    """

    code = "GL024"
    name = "unguarded-pallas-call"

    def run(self, ctx: FileContext, config) -> Iterator[Finding]:
        model = get_pallas_model(ctx)
        if model.has_mode_selector:
            return
        for site in model.sites:
            if site.interpret is not None and \
                    not isinstance(site.interpret, ast.Constant):
                continue  # interpret= threaded from a caller: guarded
            yield make_finding(
                ctx, site.call, self.code,
                "pallas_call has no selection seam: the module defines/"
                "imports no `*_mode` selector and `interpret=` is not "
                "threaded from a caller — a CPU box hard-fails instead "
                "of falling back; guard it behind an env-mode selector "
                "(core/envmode.py) like pallas_mode/gather_mode",
            )


PALLAS_RULES = [
    UnalignedDmaSlice(),
    VmemBudgetOverflow(),
    RmwOutputNotAliased(),
    AsyncCopyProtocol(),
    UnguardedPallasCall(),
]
