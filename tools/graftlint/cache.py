"""Per-file graftlint result cache keyed by content hash.

The run_tests.sh gate and the pre-commit hook re-lint the whole tree on
every invocation; the AST analysis is pure per (path, source, config,
linter version), so results are memoized under ``.graftlint_cache/``.
A cache entry's key folds in:

- the file's repo-relative path (GL004/GL010 scope by path, and the
  path is part of every Finding),
- the file's content (sha256),
- the effective config (select, float64_paths — anything that changes
  rule behavior),
- the linter's own source (sha256 over ``tools/graftlint/*.py``), so
  editing a rule invalidates every entry at once.

Entries are one small JSON file each, written atomically; a torn or
unreadable entry is treated as a miss, never an error — the cache must
never be the thing that breaks CI. ``--no-cache`` (or
``Config(cache_dir=None)``) bypasses it entirely.
"""
from __future__ import annotations

import hashlib
import json
import os
from pathlib import Path
from typing import List, Optional, Tuple

from tools.graftlint.model import Finding

#: bumped when the entry layout itself changes
_SCHEMA = 1

_TOOL_HASH: Optional[str] = None


def tool_hash() -> str:
    """sha256 over the linter's own sources: any rule/engine edit
    invalidates the whole cache."""
    global _TOOL_HASH
    if _TOOL_HASH is None:
        h = hashlib.sha256()
        pkg = Path(__file__).resolve().parent
        for src in sorted(pkg.glob("*.py")):
            h.update(src.name.encode())
            h.update(src.read_bytes())
        _TOOL_HASH = h.hexdigest()
    return _TOOL_HASH


def config_fingerprint(config) -> str:
    payload = {
        "select": sorted(config.select) if config.select else None,
        "float64_paths": sorted(config.float64_paths),
        "schema": _SCHEMA,
    }
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode()
    ).hexdigest()


def entry_key(path: str, source: str, config) -> str:
    h = hashlib.sha256()
    h.update(path.encode())
    h.update(b"\x00")
    h.update(source.encode())
    h.update(b"\x00")
    h.update(config_fingerprint(config).encode())
    h.update(b"\x00")
    h.update(tool_hash().encode())
    return h.hexdigest()


class ResultCache:
    """Content-addressed (findings, suppressed) store for one run."""

    def __init__(self, cache_dir: str, repo_root: Optional[Path] = None):
        root = Path(cache_dir)
        if not root.is_absolute() and repo_root is not None:
            root = repo_root / root
        self.dir = root
        self.hits = 0
        self.misses = 0

    def _entry_path(self, key: str) -> Path:
        return self.dir / key[:2] / f"{key}.json"

    def get(
        self, path: str, source: str, config
    ) -> Optional[Tuple[List[Finding], int]]:
        entry = self._entry_path(entry_key(path, source, config))
        try:
            data = json.loads(entry.read_text())
            findings = [Finding(**f) for f in data["findings"]]
            suppressed = int(data["suppressed"])
        except (OSError, ValueError, TypeError, KeyError):
            self.misses += 1
            return None
        self.hits += 1
        return findings, suppressed

    def put(self, path: str, source: str, config,
            findings: List[Finding], suppressed: int) -> None:
        entry = self._entry_path(entry_key(path, source, config))
        payload = json.dumps({
            "findings": [f.as_dict() for f in findings],
            "suppressed": suppressed,
        })
        try:
            entry.parent.mkdir(parents=True, exist_ok=True)
            tmp = entry.with_suffix(f".tmp-{os.getpid()}")
            tmp.write_text(payload)
            os.replace(tmp, entry)
        except OSError:
            pass  # a read-only checkout just runs uncached
