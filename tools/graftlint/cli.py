"""graftlint command line: human/JSON/SARIF output, baseline gate,
result cache, --explain, --stats.

Exit codes: 0 clean (all findings grandfathered), 1 new findings (or a
parse failure), 2 usage/config error.
"""
from __future__ import annotations

import argparse
import inspect
import json
import sys
from collections import Counter
from pathlib import Path
from typing import List

from tools.graftlint.baseline import (
    diff_baseline,
    load_baseline,
    write_baseline,
)
from tools.graftlint.config import load_config
from tools.graftlint.engine import lint_paths
from tools.graftlint.model import Finding
from tools.graftlint.rules import RULES_BY_CODE


def _print_human(new: List[Finding], grandfathered: int, stale: int,
                 suppressed: int, gate: bool) -> None:
    for f in new:
        print(f"{f.path}:{f.line}:{f.col}: {f.code} [{f.context}] "
              f"{f.message}")
        if f.text:
            print(f"    {f.text}")
    bits = [f"{len(new)} new finding{'s' if len(new) != 1 else ''}"]
    if gate:
        bits.append(f"{grandfathered} grandfathered")
        if stale:
            bits.append(
                f"{stale} stale baseline entr"
                f"{'ies' if stale != 1 else 'y'} (run --write-baseline)"
            )
    if suppressed:
        bits.append(f"{suppressed} suppressed inline")
    print("graftlint: " + ", ".join(bits))


#: rule code -> family label for --stats (GL001-GL007 are the jit/tracer
#: correctness rules, GL010-GL014 the concurrency soundness plane,
#: GL020+ the Pallas/Mosaic kernel soundness plane)
def rule_family(code: str) -> str:
    try:
        number = int(code[2:])
    except ValueError:
        return "other"
    if number == 0:
        return "parse"
    if number >= 20:
        return "pallas"
    return "concurrency" if number >= 10 else "jit"


def _print_stats(all_findings: List[Finding], new: List[Finding],
                 suppressed: int) -> None:
    """Per-rule and per-family hit counts (run_tests.sh prints this so
    the CI log shows which rule families carry weight)."""
    per_rule = Counter(f.code for f in all_findings)
    families = Counter(rule_family(f.code) for f in all_findings)
    print("graftlint stats:")
    for family in ("parse", "jit", "concurrency", "pallas", "other"):
        if family not in families and family not in (
                "concurrency", "jit", "pallas"):
            continue
        rules = ", ".join(
            f"{code}={per_rule[code]}"
            for code in sorted(per_rule)
            if rule_family(code) == family
        ) or "clean"
        print(f"  {family:<12} {families.get(family, 0):>3} "
              f"finding(s)  [{rules}]")
    print(f"  new={len(new)} suppressed_inline={suppressed}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="JAX/TPU correctness + concurrency + Pallas kernel "
                    "linter for chunkflow-tpu (rules GL001..GL024; see "
                    "docs/linting.md)",
    )
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to lint (default: config include)")
    parser.add_argument("--output", choices=("human", "json", "sarif"),
                        default=None,
                        help="output format (default: human)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="alias for --output json")
    parser.add_argument("--select", metavar="GL001,GL002",
                        help="comma-separated rule codes to run")
    parser.add_argument("--baseline", metavar="FILE",
                        help="baseline file (default from [tool.graftlint])")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline: report every finding")
    parser.add_argument("--write-baseline", action="store_true",
                        help="grandfather all current findings and exit 0")
    parser.add_argument("--no-cache", action="store_true",
                        help="bypass the per-file result cache "
                             "(.graftlint_cache/)")
    parser.add_argument("--stats", action="store_true",
                        help="print per-rule-family hit counts")
    parser.add_argument("--config", metavar="PYPROJECT",
                        help="pyproject.toml to read [tool.graftlint] from")
    parser.add_argument("--explain", metavar="GLXXX",
                        help="print a rule's documentation and exit")
    args = parser.parse_args(argv)

    if args.explain:
        rule = RULES_BY_CODE.get(args.explain.upper())
        if rule is None:
            print(f"unknown rule {args.explain!r}; known: "
                  f"{', '.join(sorted(RULES_BY_CODE))}", file=sys.stderr)
            return 2
        print(f"{rule.code} ({rule.name})\n")
        print(inspect.cleandoc(rule.__doc__ or "(no documentation)"))
        return 0

    output = args.output or ("json" if args.as_json else "human")
    try:
        config = load_config(Path(args.config) if args.config else None)
        if args.select:
            config.select = [c.strip().upper()
                             for c in args.select.split(",") if c.strip()]
        if args.baseline:
            config.baseline = args.baseline
        roots = args.paths or config.include
        findings, suppressed = lint_paths(
            roots, config, use_cache=not args.no_cache
        )
    except (ValueError, OSError) as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    baseline_path = Path(config.baseline)
    if args.write_baseline:
        write_baseline(baseline_path, findings)
        print(f"graftlint: wrote {len(findings)} finding"
              f"{'s' if len(findings) != 1 else ''} to {baseline_path}")
        return 0

    gate = not args.no_baseline
    if gate:
        try:
            baseline = load_baseline(baseline_path)
        except ValueError as e:
            print(f"graftlint: {e}", file=sys.stderr)
            return 2
        new, grandfathered, stale = diff_baseline(findings, baseline)
    else:
        new, grandfathered, stale = findings, 0, 0

    if output == "json":
        print(json.dumps({
            "new": [f.as_dict() for f in new],
            "grandfathered": grandfathered,
            "stale_baseline_entries": stale,
            "suppressed": suppressed,
        }, indent=2))
    elif output == "sarif":
        from tools.graftlint import __version__
        from tools.graftlint.sarif import render_sarif

        print(json.dumps(render_sarif(new, __version__), indent=2))
    else:
        _print_human(new, grandfathered, stale, suppressed, gate)
    if args.stats:
        _print_stats(findings, new, suppressed)
    return 1 if new else 0
