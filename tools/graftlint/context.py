"""Per-file static analysis context: imports, scopes, traced functions, taint.

The heart of graftlint is knowing which functions execute UNDER A JAX TRACE
— that is where a host sync or a numpy op silently wrecks the compiled
program. A function is considered traced when any of these hold:

1. it is decorated with ``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)``;
2. it is passed to ``jax.jit(...)`` / a traced-callback wrapper
   (``lax.scan``, ``lax.map``, ``lax.cond``, ``jax.vmap``, ``shard_map``,
   ...) anywhere in the module;
3. it is returned by a ``build_*`` program-builder function (this repo's
   idiom: ``build_fold_program`` et al. return a closure that the caller
   jits or embeds in a jitted program);
4. it is defined inside, or called by name from, a traced function
   (propagated to a fixpoint over the module-local call graph).

This is module-local and name-based on purpose: cross-module dataflow is
out of scope for a purpose-built linter, and the baseline absorbs the
residual blind spots.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple, Union

from tools.graftlint.model import extract_comments, parse_suppressions

FuncNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]
FUNC_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

#: callables whose function argument runs under trace
JIT_CALLABLES = {
    "jax.jit",
    "jax.pjit",
    "jax.experimental.pjit.pjit",
}
TRACED_WRAPPERS = {
    "jax.lax.scan",
    "jax.lax.map",
    "jax.lax.cond",
    "jax.lax.switch",
    "jax.lax.while_loop",
    "jax.lax.fori_loop",
    "jax.lax.associative_scan",
    "jax.lax.custom_root",
    "jax.vmap",
    "jax.pmap",
    "jax.grad",
    "jax.value_and_grad",
    "jax.checkpoint",
    "jax.remat",
    "jax.experimental.shard_map.shard_map",
}

#: attribute reads that are static under trace (no tracer value involved)
STATIC_ATTRS = {
    "shape", "ndim", "dtype", "size", "sharding", "aval", "weak_type",
    "itemsize",
}


def attach_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.parent = node  # type: ignore[attr-defined]


class ImportMap:
    """Alias -> dotted module path, collected over the WHOLE file.

    This codebase imports jax inside functions (deferred imports keep CLI
    startup fast), so alias collection ignores scope; a per-file alias
    colliding across scopes with different targets would be its own smell.
    """

    def __init__(self, tree: ast.AST):
        self.aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        self.aliases[alias.asname] = alias.name
                    else:
                        # ``import jax.numpy`` binds the TOP name
                        top = alias.name.split(".")[0]
                        self.aliases.setdefault(top, top)
            elif isinstance(node, ast.ImportFrom) and node.module:
                if node.level:
                    continue  # relative imports: not stdlib/jax/numpy
                for alias in node.names:
                    bound = alias.asname or alias.name
                    self.aliases[bound] = f"{node.module}.{alias.name}"

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted path of a Name/Attribute chain, e.g. ``np.asarray`` ->
        ``numpy.asarray``; None when the root is not an import alias."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        base = self.aliases.get(node.id)
        if base is None:
            return None
        parts.append(base)
        return ".".join(reversed(parts))


def enclosing_function(node: ast.AST) -> Optional[FuncNode]:
    cur = getattr(node, "parent", None)
    while cur is not None:
        if isinstance(cur, FUNC_TYPES):
            return cur
        cur = getattr(cur, "parent", None)
    return None


def func_name(node: FuncNode) -> str:
    return node.name if not isinstance(node, ast.Lambda) else "<lambda>"


def walk_local(func: FuncNode) -> Iterator[ast.AST]:
    """Every node in ``func``'s own body, NOT descending into nested
    functions (each traced function is analyzed exactly once)."""
    body = func.body if not isinstance(func, ast.Lambda) else [func.body]
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, FUNC_TYPES):
                continue
            stack.append(child)


class FileContext:
    """Everything the rules need to know about one source file."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        attach_parents(self.tree)
        self.comments = extract_comments(source)
        self.suppressions = parse_suppressions(self.comments)
        self.imports = ImportMap(self.tree)
        self.functions: List[FuncNode] = [
            n for n in ast.walk(self.tree) if isinstance(n, FUNC_TYPES)
        ]
        # (enclosing scope node, name) -> def node; module scope key None
        self._defs: Dict[Tuple[Optional[ast.AST], str], FuncNode] = {}
        for fn in self.functions:
            if not isinstance(fn, ast.Lambda):
                self._defs[(enclosing_function(fn), fn.name)] = fn
        self.traced: Set[FuncNode] = set()
        self._compute_traced()

    # -- traced-function analysis ------------------------------------
    def resolve_local(
        self, name: str, from_node: ast.AST
    ) -> Optional[FuncNode]:
        """A function def visible from ``from_node`` via lexical scoping."""
        scope: Optional[ast.AST] = enclosing_function(from_node)
        while True:
            hit = self._defs.get((scope, name))
            if hit is not None:
                return hit
            if scope is None:
                return None
            scope = enclosing_function(scope)

    def _callee_func(self, arg: ast.AST, site: ast.AST) -> Optional[FuncNode]:
        if isinstance(arg, ast.Lambda):
            return arg
        if isinstance(arg, ast.Name):
            return self.resolve_local(arg.id, site)
        return None

    def is_jit_ref(self, node: ast.AST) -> bool:
        return self.imports.resolve(node) in JIT_CALLABLES

    def jit_decorator_info(self, dec: ast.AST) -> Optional[ast.AST]:
        """The decorator expression if ``dec`` applies jit (plain ref,
        ``jax.jit(...)`` factory, or ``partial(jax.jit, ...)``), else
        None. The returned node is where GL005 inspects kwargs."""
        if self.is_jit_ref(dec):
            return dec
        if isinstance(dec, ast.Call):
            if self.is_jit_ref(dec.func):
                return dec
            if self.imports.resolve(dec.func) == "functools.partial" and \
                    dec.args and self.is_jit_ref(dec.args[0]):
                return dec
        return None

    def _compute_traced(self) -> None:
        seeds: Set[FuncNode] = set()
        for fn in self.functions:
            if isinstance(fn, ast.Lambda):
                continue
            if any(self.jit_decorator_info(d) for d in fn.decorator_list):
                seeds.add(fn)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            target = self.imports.resolve(node.func)
            if target in JIT_CALLABLES or target in TRACED_WRAPPERS:
                for arg in node.args:
                    callee = self._callee_func(arg, node)
                    if callee is not None:
                        seeds.add(callee)
        # build_* builders: the closure they return ends up jitted (or
        # embedded in a jitted program) by the caller
        for fn in self.functions:
            if isinstance(fn, ast.Lambda) or not fn.name.startswith("build_"):
                continue
            for node in walk_local(fn):
                if isinstance(node, ast.Return) and node.value is not None:
                    callee = self._callee_func(node.value, node)
                    if callee is not None:
                        seeds.add(callee)

        # fixpoint: nested defs of traced funcs + module-local callees
        worklist = list(seeds)
        traced = set(seeds)
        children: Dict[FuncNode, List[FuncNode]] = {}
        for fn in self.functions:
            parent = enclosing_function(fn)
            if parent is not None:
                children.setdefault(parent, []).append(fn)
        while worklist:
            fn = worklist.pop()
            for nested in children.get(fn, ()):  # defined under trace
                if nested not in traced:
                    traced.add(nested)
                    worklist.append(nested)
            for node in walk_local(fn):  # called under trace
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Name):
                    callee = self.resolve_local(node.func.id, node)
                    if callee is not None and callee not in traced:
                        traced.add(callee)
                        worklist.append(callee)
        self.traced = traced

    # -- taint: does an expression carry a tracer value? ---------------
    @staticmethod
    def _is_static_use(name_node: ast.Name) -> bool:
        """x.shape / x.ndim / len(x) / isinstance(x, T) / ``x is None``
        read only static trace-time facts, never a tracer value."""
        parent = getattr(name_node, "parent", None)
        if isinstance(parent, ast.Attribute) and parent.attr in STATIC_ATTRS:
            return True
        if isinstance(parent, ast.Call) and isinstance(parent.func, ast.Name)\
                and parent.func.id in ("len", "isinstance", "type", "id") \
                and name_node in parent.args:
            return True
        if isinstance(parent, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in parent.ops
        ):
            return True
        return False

    def expr_is_tainted(self, expr: ast.AST, tainted: Set[str]) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and node.id in tainted \
                    and not self._is_static_use(node):
                return True
        return False

    def tainted_names(self, func: FuncNode) -> Set[str]:
        """Names carrying tracer values inside a traced function: the
        parameters, plus anything assigned from a tainted expression
        (propagated to a fixpoint; static-fact reads don't propagate)."""
        args = func.args
        tainted: Set[str] = {
            a.arg
            for a in (
                args.posonlyargs + args.args + args.kwonlyargs
                + ([args.vararg] if args.vararg else [])
                + ([args.kwarg] if args.kwarg else [])
            )
        }
        changed = True
        while changed:
            changed = False
            for node in walk_local(func):
                targets: List[ast.AST] = []
                value: Optional[ast.AST] = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.For):
                    targets, value = [node.target], node.iter
                if value is None or not self.expr_is_tainted(value, tainted):
                    continue
                for target in targets:
                    for leaf in ast.walk(target):
                        if isinstance(leaf, ast.Name) and \
                                leaf.id not in tainted:
                            tainted.add(leaf.id)
                            changed = True
        return tainted

    # -- reporting helpers --------------------------------------------
    def qualname_at(self, node: ast.AST) -> str:
        parts: List[str] = []
        fn = node if isinstance(node, FUNC_TYPES) else None
        if fn is None:
            fn = enclosing_function(node)
        while fn is not None:
            parts.append(func_name(fn))
            fn = enclosing_function(fn)
        return ".".join(reversed(parts)) if parts else "<module>"
