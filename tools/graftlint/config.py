"""[tool.graftlint] configuration from pyproject.toml.

Recognized keys (all optional)::

    [tool.graftlint]
    include = ["chunkflow_tpu"]            # default lint roots
    exclude = ["chunkflow_tpu/native/*"]   # fnmatch globs, repo-relative
    select = ["GL001", "GL002", ...]       # enabled rules (default: all)
    baseline = "tools/graftlint/baseline.json"
    float64_paths = ["chunkflow_tpu/ops", "chunkflow_tpu/inference"]
    cache_dir = ".graftlint_cache"         # per-file result cache

CLI flags override file config. Python 3.10 has no tomllib, so parsing
uses the already-vendored ``tomli`` when present and degrades to defaults
(with a warning) when neither is importable — graftlint must never be the
thing that breaks CI bootstrap.
"""
from __future__ import annotations

import sys
from dataclasses import dataclass, field
from fnmatch import fnmatch
from pathlib import Path
from typing import List, Optional


@dataclass
class Config:
    include: List[str] = field(default_factory=lambda: ["chunkflow_tpu"])
    exclude: List[str] = field(default_factory=list)
    select: Optional[List[str]] = None  # None -> all rules
    baseline: str = "tools/graftlint/baseline.json"
    float64_paths: List[str] = field(
        default_factory=lambda: [
            "chunkflow_tpu/ops", "chunkflow_tpu/inference",
        ]
    )
    #: per-file result cache directory (tools/graftlint/cache.py);
    #: None disables caching entirely (the --no-cache escape hatch)
    cache_dir: Optional[str] = ".graftlint_cache"

    def is_excluded(self, relpath: str) -> bool:
        return any(fnmatch(relpath, pat) for pat in self.exclude)


def _load_toml(path: Path) -> dict:
    try:
        import tomllib  # Python >= 3.11
    except ModuleNotFoundError:
        try:
            import tomli as tomllib  # type: ignore[no-redef]
        except ModuleNotFoundError:
            print(
                f"graftlint: no tomllib/tomli available; ignoring {path} "
                f"and using built-in defaults",
                file=sys.stderr,
            )
            return {}
    with open(path, "rb") as f:
        return tomllib.load(f)


def load_config(pyproject: Optional[Path] = None) -> Config:
    cfg = Config()
    path = pyproject if pyproject is not None else Path("pyproject.toml")
    if not path.exists():
        return cfg
    section = _load_toml(path).get("tool", {}).get("graftlint", {})
    for key in ("include", "exclude", "select", "float64_paths"):
        if key in section:
            setattr(cfg, key, list(section[key]))
    if "baseline" in section:
        cfg.baseline = str(section["baseline"])
    if "cache_dir" in section:
        raw = section["cache_dir"]
        cfg.cache_dir = str(raw) if raw else None
    return cfg
