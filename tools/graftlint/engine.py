"""File/tree runners: parse, run rules, apply suppressions."""
from __future__ import annotations

from pathlib import Path
from typing import Iterable, List, Optional, Tuple

from tools.graftlint.config import Config
from tools.graftlint.context import FileContext
from tools.graftlint.model import Finding
from tools.graftlint.rules import RULES, RULES_BY_CODE


def _selected_rules(config: Config):
    if config.select is None:
        return RULES
    unknown = [c for c in config.select if c not in RULES_BY_CODE]
    if unknown:
        raise ValueError(
            f"unknown rule code(s) {unknown}; known: "
            f"{sorted(RULES_BY_CODE)}"
        )
    return [RULES_BY_CODE[c] for c in config.select]


def lint_file(
    path: str, source: str, config: Optional[Config] = None
) -> Tuple[List[Finding], int]:
    """(findings, suppressed_count) for one file's source text.

    ``path`` should be repo-relative posix (it becomes the Finding path
    and feeds baseline keys + GL004 path scoping). Syntax errors surface
    as a single GL000 finding rather than crashing the whole run.
    """
    config = config or Config()
    try:
        ctx = FileContext(path, source)
    except SyntaxError as e:
        return [
            Finding(
                path=path, line=e.lineno or 1, col=e.offset or 0,
                code="GL000", message=f"file does not parse: {e.msg}",
                context="<module>", text=(e.text or "").strip(),
            )
        ], 0
    findings: List[Finding] = []
    suppressed = 0
    for rule in _selected_rules(config):
        for f in rule.run(ctx, config):
            if ctx.suppressions.is_suppressed(f.line, f.code):
                suppressed += 1
            else:
                findings.append(f)
    findings.sort(key=lambda f: (f.line, f.col, f.code))
    return findings, suppressed


def iter_python_files(
    roots: Iterable[str], config: Config, repo_root: Path
) -> Iterable[Path]:
    for root in roots:
        p = (repo_root / root).resolve()
        candidates = [p] if p.is_file() else sorted(p.rglob("*.py"))
        for f in candidates:
            rel = f.relative_to(repo_root).as_posix()
            if not config.is_excluded(rel):
                yield f


def lint_paths(
    roots: Iterable[str],
    config: Optional[Config] = None,
    repo_root: Optional[Path] = None,
    use_cache: bool = True,
) -> Tuple[List[Finding], int]:
    """Lint every .py under the given roots; (findings, suppressed).

    With ``use_cache`` (and ``config.cache_dir`` set), per-file results
    are memoized by content hash under the cache dir, so reruns only
    re-analyze changed files (tools/graftlint/cache.py).
    """
    config = config or Config()
    repo_root = (repo_root or Path.cwd()).resolve()
    cache = None
    if use_cache and config.cache_dir:
        from tools.graftlint.cache import ResultCache

        cache = ResultCache(config.cache_dir, repo_root)
    all_findings: List[Finding] = []
    suppressed = 0
    for f in iter_python_files(roots, config, repo_root):
        rel = f.relative_to(repo_root).as_posix()
        source = f.read_text()
        cached = cache.get(rel, source, config) if cache else None
        if cached is not None:
            found, sup = cached
        else:
            found, sup = lint_file(rel, source, config)
            if cache is not None:
                cache.put(rel, source, config, found, sup)
        all_findings.extend(found)
        suppressed += sup
    all_findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return all_findings, suppressed
