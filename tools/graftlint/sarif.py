"""SARIF 2.1.0 rendering for graftlint findings.

SARIF (Static Analysis Results Interchange Format) is what code-review
UIs (GitHub code scanning, VS Code SARIF viewer) ingest; ``--output
sarif`` makes the gate's findings reviewable inline instead of as CI
log text. One run, one tool (``graftlint``), rule metadata from the
registry docstrings, one result per NEW finding (grandfathered and
suppressed findings are by definition not actionable and are omitted,
matching the human/JSON outputs' exit semantics).
"""
from __future__ import annotations

import inspect
from typing import List

from tools.graftlint.model import Finding
from tools.graftlint.rules import RULES

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def _rule_descriptor(rule) -> dict:
    doc = inspect.cleandoc(rule.__doc__ or "")
    short = doc.splitlines()[0] if doc else rule.name
    return {
        "id": rule.code,
        "name": rule.name,
        "shortDescription": {"text": short},
        "fullDescription": {"text": doc},
        "defaultConfiguration": {"level": "error"},
    }


def render_sarif(findings: List[Finding], version: str) -> dict:
    """The findings as a SARIF 2.1.0 log (a plain dict, ready for
    ``json.dumps``)."""
    results = []
    for f in findings:
        results.append({
            "ruleId": f.code,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path,
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {
                        "startLine": f.line,
                        "startColumn": f.col + 1,  # SARIF is 1-based
                        "snippet": {"text": f.text},
                    },
                },
                "logicalLocations": [{
                    "fullyQualifiedName": f.context,
                    "kind": "function",
                }],
            }],
        })
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "graftlint",
                    "informationUri":
                        "https://github.com/seung-lab/chunkflow",
                    "version": version,
                    "rules": [_rule_descriptor(r) for r in RULES],
                }
            },
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }
