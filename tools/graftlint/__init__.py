"""graftlint: a JAX/TPU correctness linter purpose-built for chunkflow-tpu.

Chunkflow's throughput rests on invariants the compiler cannot see: jitted
hot paths must stay free of host syncs, numpy ops must not touch traced
values, Python control flow must not branch on tracers, accumulators must
stay float32, big chunk buffers should be donated, and every axis shuffle
on a zyx chunk needs its order spelled out. graftlint checks those
statically, with a per-rule baseline so CI only fails on NEW violations.

Rules
-----
GL001  host-sync call inside a jit-traced function
GL002  numpy op applied inside a jit-traced function (np/jnp mixing)
GL003  Python control flow on a tracer-derived value (recompile/leak)
GL004  implicit float64 literal or dtype promotion in ops/ and inference/
GL005  chunk-sized array passed to jax.jit without donate_argnums
GL006  axis shuffle on a chunk array without an axis-order comment/helper

Usage
-----
    python -m tools.graftlint chunkflow_tpu/            # human output
    python -m tools.graftlint --json chunkflow_tpu/     # machine output
    python -m tools.graftlint --write-baseline          # grandfather all
    python -m tools.graftlint --explain GL003           # rule docs

Suppress a single line with ``# graftlint: disable=GL001`` (comma-separate
several codes; bare ``disable`` silences every rule on that line) or a
whole file with ``# graftlint: disable-file=GL004``.
"""
from tools.graftlint.model import Finding  # noqa: F401
from tools.graftlint.engine import lint_file, lint_paths  # noqa: F401

__version__ = "0.1.0"
