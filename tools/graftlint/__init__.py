"""graftlint: a JAX/TPU correctness + concurrency linter for chunkflow-tpu.

Chunkflow's throughput rests on invariants the compiler cannot see: jitted
hot paths must stay free of host syncs, numpy ops must not touch traced
values, Python control flow must not branch on tracers, accumulators must
stay float32, big chunk buffers should be donated, and every axis shuffle
on a zyx chunk needs its order spelled out. Its host side is seriously
concurrent, so the same goes for thread/lock discipline. graftlint checks
both statically, with a per-rule baseline so CI only fails on NEW
violations, and a content-hash result cache so reruns only re-analyze
changed files.

Rules
-----
GL001  host-sync call inside a jit-traced function
GL002  numpy op applied inside a jit-traced function (np/jnp mixing)
GL003  Python control flow on a tracer-derived value (recompile/leak)
GL004  implicit float64 literal or dtype promotion in ops/ and inference/
GL005  chunk-sized array passed to jax.jit without donate_argnums
GL006  axis shuffle on a chunk array without an axis-order comment/helper
GL007  telemetry/wall-clock call inside a jit-traced function
GL010  shared mutable attribute written from a thread without a lock
GL011  lock-acquisition-order inversion within one class/module
GL012  blocking call (queue get/put, join, device sync, HTTP) under a lock
GL013  threading.Thread neither daemonized nor joined
GL014  Condition.wait outside a predicate loop

The GL010-series' runtime twin is the locksmith lock-order sanitizer
(chunkflow_tpu/testing/locksmith.py), default-on under the tier-1 suite.

Usage
-----
    python -m tools.graftlint chunkflow_tpu/            # human output
    python -m tools.graftlint --output json             # machine output
    python -m tools.graftlint --output sarif            # SARIF 2.1.0
    python -m tools.graftlint --write-baseline          # grandfather all
    python -m tools.graftlint --explain GL011           # rule docs
    python -m tools.graftlint --stats                   # per-family counts

Suppress a single line with ``# graftlint: disable=GL001`` (comma-separate
several codes; bare ``disable`` silences every rule on that line) or a
whole file with ``# graftlint: disable-file=GL004``.
"""
from tools.graftlint.model import Finding  # noqa: F401
from tools.graftlint.engine import lint_file, lint_paths  # noqa: F401

__version__ = "0.1.0"
