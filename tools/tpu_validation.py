"""One-process TPU validation + measurement battery.

The TPU tunnel in this environment serves a single client at a time and
wedges if probed concurrently or killed mid-compile, so every hardware
question is answered in ONE process, in priority order, with results
appended to ``tools/tpu_validation.json`` as they arrive (a crash keeps
earlier answers).

Run:  python tools/tpu_validation.py
"""
from __future__ import annotations

import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESULTS_PATH = os.path.join(os.path.dirname(__file__), "tpu_validation.json")
RESULTS: dict = {}


def record(name, value):
    RESULTS[name] = value
    with open(RESULTS_PATH, "w") as f:
        json.dump(RESULTS, f, indent=2)
    print(f"[{name}] {value}", flush=True)


def step(name):
    def deco(fn):
        def run():
            t0 = time.perf_counter()
            try:
                value = fn()
                record(name, {"ok": True, "value": value,
                              "seconds": round(time.perf_counter() - t0, 1)})
                return True
            except Exception:
                record(name, {"ok": False,
                              "error": traceback.format_exc()[-2000:],
                              "seconds": round(time.perf_counter() - t0, 1)})
                return False
        return run
    return deco


@step("tunnel")
def check_tunnel():
    import jax
    import jax.numpy as jnp

    d = jax.devices()
    y = (jnp.ones((512, 512)) @ jnp.ones((512, 512))).block_until_ready()
    return str(d)


@step("pallas_oracle")
def check_pallas_oracle():
    import numpy as np

    os.environ["CHUNKFLOW_PALLAS"] = "1"
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.inferencer import Inferencer

    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=3,
        framework="identity",
        batch_size=2,
        crop_output_margin=False,
    )
    rng = np.random.default_rng(1)
    chunk = rng.random((8, 32, 32)).astype(np.float32)
    out = np.asarray(inferencer(Chunk(chunk)).array)
    mse = float(((out - chunk[None]) ** 2).mean())
    assert mse < 1e-8, f"pallas oracle MSE={mse}"
    return {"mse": mse}


def _fwd_time(model, params, x, n=3):
    import jax

    f = jax.jit(lambda p, v: model.apply({"params": p}, v))
    f(params, x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(n):
        f(params, x).block_until_ready()
    return (time.perf_counter() - t0) / n


@step("fwd_parity_f32")
def fwd_parity():
    import jax.numpy as jnp

    from chunkflow_tpu.models import unet3d

    model = unet3d.UNet3D(in_channels=1, out_channels=3)
    params = unet3d.init_params(model, (20, 256, 256), 1)
    x = jnp.zeros((2, 20, 256, 256, 1), jnp.float32)
    dt = _fwd_time(model, params, x)
    return {"ms": round(dt * 1e3, 1),
            "mvox_s": round(2 * 20 * 256 * 256 / dt / 1e6, 2)}


@step("fwd_tpu_bf16")
def fwd_tpu_variant():
    import jax.numpy as jnp

    from chunkflow_tpu.models import unet3d

    model = unet3d.create_tpu_optimized_model()
    params = unet3d.init_params(model, (20, 256, 256), 1)
    x = jnp.zeros((4, 20, 256, 256, 1), jnp.float32)
    dt = _fwd_time(model, params, x)
    return {"ms": round(dt * 1e3, 1),
            "mvox_s": round(4 * 20 * 256 * 256 / dt / 1e6, 2)}


def _bench(pallas: str, variant: str, dtype: str, batch: int):
    import importlib

    os.environ["CHUNKFLOW_PALLAS"] = pallas
    os.environ["CHUNKFLOW_BENCH_VARIANT"] = variant
    os.environ["CHUNKFLOW_BENCH_DTYPE"] = dtype
    os.environ["CHUNKFLOW_BENCH_BATCH"] = str(batch)
    import bench

    importlib.reload(bench)
    return {"mvox_s": round(bench.run_config({
        "model_variant": variant, "dtype": dtype,
        "batch_size": batch, "pallas": pallas,
    }), 2)}


@step("bench_tpu_bf16_xla")
def bench_flagship_xla():
    return _bench("0", "tpu", "bfloat16", 4)


@step("bench_tpu_bf16_pallas")
def bench_flagship_pallas():
    return _bench("1", "tpu", "bfloat16", 4)


@step("bench_parity_f32")
def bench_parity():
    return _bench("0", "parity", "float32", 2)


@step("entry_compile")
def entry_compile():
    import jax

    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    out.block_until_ready()
    return {"shape": list(out.shape)}


def main():
    steps = [check_tunnel, check_pallas_oracle, fwd_parity, fwd_tpu_variant,
             bench_flagship_xla, bench_flagship_pallas, bench_parity,
             entry_compile]
    if not steps[0]():
        print("tunnel unavailable; aborting", file=sys.stderr)
        return 1
    for s in steps[1:]:
        s()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
