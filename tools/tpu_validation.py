"""One-process TPU validation + measurement battery.

The TPU tunnel in this environment serves a single client at a time, takes
minutes to acquire a device, and wedges if probed concurrently or killed
mid-compile, so every hardware question is answered in ONE process, in
importance order (headline-class bench steps first, A/B diagnostics
after — tunnel windows run ~25 min), with results appended to
``tools/tpu_validation.json`` as they arrive (a crash keeps earlier
answers).  The persistent XLA compilation cache is enabled, so a completed
run also warms the cache for the driver's later ``bench.py`` invocation.

Run:  nohup python tools/tpu_validation.py > tools/tpu_validation.log 2>&1 &
"""
from __future__ import annotations

import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CHUNKFLOW_VALIDATION_RESULTS: redirect for CPU rehearsals so a smoke
# run can never clobber the live battery's resume cache
RESULTS_PATH = os.environ.get(
    "CHUNKFLOW_VALIDATION_RESULTS",
    os.path.join(os.path.dirname(__file__), "tpu_validation.json"),
)
RESULTS: dict = {}

# The tunnel can drop mid-battery (observed: 26 min hang, then connection
# refused). Reruns keep prior successes and only redo failed/missing steps,
# so a flaky tunnel converges across attempts:
#   for i in $(seq 8); do python tools/tpu_validation.py && break; sleep 300; done
# Set CHUNKFLOW_REVALIDATE=1 to force every step to rerun.
# tpu_validation.json is a gitignored per-run artifact (it doubles as this
# resume cache, so a tracked copy would skip steps against stale results);
# completed batteries are committed as frozen tpu_validation_r{N}.json
# snapshots that nothing reads back.
if (os.path.exists(RESULTS_PATH)
        and os.environ.get("CHUNKFLOW_REVALIDATE", "") != "1"):
    try:
        with open(RESULTS_PATH) as f:
            RESULTS = json.load(f)
    except Exception:
        RESULTS = {}


def record(name, value):
    RESULTS[name] = value
    with open(RESULTS_PATH, "w") as f:
        json.dump(RESULTS, f, indent=2)
        f.write("\n")  # frozen snapshots are committed text files
    print(f"[{name}] {value}", flush=True)


def _env_geometry_note():
    """Non-empty when geometry env overrides are active (CPU rehearsals):
    stamped into every row so a smoke-shape number can never pass for a
    production measurement — bench.py's cached-headline pick skips any
    row carrying a geometry_note."""
    names = ("CHUNKFLOW_BENCH_CHUNK", "CHUNKFLOW_BENCH_PATCH",
             "CHUNKFLOW_BENCH_OVERLAP", "CHUNKFLOW_BENCH_JUMBO")
    over = {n: os.environ[n] for n in names if os.environ.get(n)}
    if not over:
        return ""
    return "env geometry overrides: " + ", ".join(
        f"{k.rsplit('_', 1)[-1].lower()}={v}" for k, v in sorted(over.items()))


def step(name):
    def deco(fn):
        def run():
            prior = RESULTS.get(name)
            # "tunnel" is the cheap liveness gate for this attempt — a
            # prior success says nothing about the tunnel being up now
            if name != "tunnel" and isinstance(prior, dict) and prior.get("ok"):
                print(f"--- {name}: ok from prior run, skipping ---",
                      flush=True)
                return True
            print(f"--- starting {name} ---", flush=True)
            t0 = time.perf_counter()
            try:
                value = fn()
                geom = _env_geometry_note()
                if geom and isinstance(value, dict):
                    value.setdefault("geometry_note", geom)
                record(name, {"ok": True, "value": value,
                              "seconds": round(time.perf_counter() - t0, 1),
                              "commit": _commit(),
                              "platform": _platform()})
                return True
            except Exception:
                failure = {"ok": False,
                           "error": traceback.format_exc()[-2000:],
                           "seconds": round(time.perf_counter() - t0, 1),
                           "commit": _commit(),
                           "platform": _platform()}
                if name == "tunnel" and isinstance(prior, dict) \
                        and prior.get("ok"):
                    # ADVICE r5: the tunnel row must stay the one from
                    # the attempt that banked the measurements — a later
                    # failed retry overwriting it made the r05 snapshot
                    # claim the banked rows ran without a live tunnel.
                    # The retry failure banks under its own key instead.
                    record("tunnel_last_retry", failure)
                else:
                    record(name, failure)
                return False
        run.step_name = name
        return run
    return deco


def _platform() -> str:
    """Backend this row was measured on ('' if jax not yet imported).
    bench.py's cached-headline pick rejects non-TPU-class rows, so a CPU
    rehearsal pointed at a tools/tpu_validation*.json path can never pass
    for a real-chip number."""
    jaxmod = sys.modules.get("jax")
    if jaxmod is None:
        return ""
    try:
        return str(jaxmod.default_backend())
    except Exception:
        return ""


_COMMIT_CACHE: list = []


def _commit() -> str:
    if not _COMMIT_CACHE:
        _COMMIT_CACHE.append(_git_meta()["measured_at_commit"])
    return _COMMIT_CACHE[0]


def _git_meta() -> dict:
    """Provenance stamp for every measurement in this file (VERDICT r3
    weak#1: a cached number must carry the commit it was measured at so
    it can never be mistaken for current-code performance)."""
    import subprocess

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        commit = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=here,
            capture_output=True, text=True, timeout=10).stdout.strip()
        dirty = bool(subprocess.run(
            ["git", "status", "--porcelain"], cwd=here,
            capture_output=True, text=True, timeout=10).stdout.strip())
    except Exception:
        commit, dirty = "unknown", False
    meta = {
        "measured_at_commit": commit + ("-dirty" if dirty else ""),
        "measured_at_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "blend_default": "fold-or-scatter-auto (per-batch scatter unless "
                         "fold fits budget); stacked/pallas opt-in",
    }
    geom = _env_geometry_note()
    if geom:
        meta["geometry_note"] = geom
    return meta


@step("tunnel")
def check_tunnel():
    import bench

    bench._enable_compilation_cache()
    import jax
    import jax.numpy as jnp

    d = jax.devices()
    (jnp.ones((512, 512)) @ jnp.ones((512, 512))).block_until_ready()
    # stamp provenance the moment the tunnel answers: every bench_* row
    # written after this was measured at this commit
    record("_meta", _git_meta())
    return str(d)


@step("compile_split")
def compile_split():
    """Trace / compile / run split for the fused identity program at a
    medium shape — isolates whether round-1's ~25 min/config was XLA
    compile or execution."""
    import jax
    import jax.numpy as jnp

    from chunkflow_tpu.inference import engines
    from chunkflow_tpu.inference.bump import bump_map
    from chunkflow_tpu.inference.patching import (
        enumerate_patches,
        pad_to_batch,
    )
    from chunkflow_tpu.ops.blend import build_local_blend, normalize_blend

    pin = pout = (16, 128, 128)
    engine = engines.create_identity_engine(
        input_patch_size=pin, output_patch_size=pout,
        num_input_channels=1, num_output_channels=3,
    )
    local_blend = build_local_blend(
        engine.apply, 1, 3, pin, pout, 2, bump_map(pout))

    def program(chunk, s_in, s_out, valid, params):
        return normalize_blend(*local_blend(chunk, s_in, s_out, valid, params))

    shape = (1, 32, 256, 256)
    grid = enumerate_patches(shape, pin, pout, (4, 32, 32))
    s_in, s_out, valid = pad_to_batch(grid, 2)
    args = (jnp.zeros(shape, jnp.float32), jnp.asarray(s_in),
            jnp.asarray(s_out), jnp.asarray(valid), engine.params)
    t0 = time.perf_counter()
    lowered = jax.jit(program, donate_argnums=(0,)).lower(*args)
    t1 = time.perf_counter()
    compiled = lowered.compile()
    t2 = time.perf_counter()
    compiled(*args)[0].block_until_ready()
    t3 = time.perf_counter()
    return {"trace_s": round(t1 - t0, 2), "compile_s": round(t2 - t1, 2),
            "run_s": round(t3 - t2, 3)}



def _patch_shape():
    """Flagship fwd-step patch shape: bench.INPUT_PATCH, so the CPU
    rehearsal's smoke-geometry env overrides shrink these steps too
    (production default unchanged: 20x256x256)."""
    import bench

    return tuple(bench.INPUT_PATCH)


def _fwd_time(model, params, x, n=3):
    import jax

    f = jax.jit(lambda p, v: model.apply({"params": p}, v))
    t0 = time.perf_counter()
    f(params, x).block_until_ready()
    warmup = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(n):
        f(params, x).block_until_ready()
    dt = (time.perf_counter() - t0) / n
    print(f"  fwd warmup {warmup:.1f}s steady {dt * 1e3:.1f}ms", flush=True)
    return dt


def _fwd_step(batch, make_model):
    """Shared raw-forward timing body: one place owns the shape/metric
    math for every fwd_* A/B step."""
    import math

    import jax.numpy as jnp

    from chunkflow_tpu.models import unet3d

    ps = _patch_shape()
    model = make_model(unet3d)
    params = unet3d.init_params(model, ps, 1)
    x = jnp.zeros((batch,) + ps + (1,), jnp.float32)
    dt = _fwd_time(model, params, x)
    return {"ms": round(dt * 1e3, 1),
            "mvox_s": round(batch * math.prod(ps) / dt / 1e6, 2)}


@step("fwd_parity_f32")
def fwd_parity():
    return _fwd_step(2, lambda u: u.UNet3D(in_channels=1, out_channels=3))


def _bench(pallas: str, variant: str, dtype: str, batch: int, **extra):
    import bench

    os.environ["CHUNKFLOW_PALLAS"] = pallas
    cfg = {"model_variant": variant, "dtype": dtype,
           "batch_size": batch, "pallas": pallas, **extra}
    return {k: (round(v, 2) if isinstance(v, float) else v)
            for k, v in bench.run_config(cfg).items()}


@step("bench_parity_f32")
def bench_parity():
    return _bench("0", "parity", "float32", 2)


@step("fwd_tpu_bf16")
def fwd_tpu_variant():
    return _fwd_step(4, lambda u: u.create_tpu_optimized_model())


@step("bench_tpu_bf16_xla")
def bench_flagship_xla():
    return _bench("0", "tpu", "bfloat16", 4)


@step("fwd_tpu_mxu")
def fwd_tpu_mxu():
    """Conv-lowering A/B vs fwd_tpu_bf16: same flagship, same parameters,
    every conv lowered as z-decomposed 2D convs + GEMM upsampling
    (unet3d.MxuConv) instead of XLA's native Conv3D."""
    return _fwd_step(
        4, lambda u: u.create_tpu_optimized_model(conv_impl="mxu"))


@step("bench_tpu_mxu_fold_stream_u8")
def bench_mxu_fold_stream_u8():
    """The full production stack on the mxu lowering."""
    return _bench("0", "tpu_mxu", "bfloat16", 4, blend="fold", stream=5,
                  output_dtype="uint8")


@step("fwd_tpu_s2d4")
def fwd_tpu_s2d4():
    """Layout A/B vs fwd_tpu_bf16: aggressive (1,4,4) space-to-depth stem
    (112-256 channels at 1/16 positions, ~same per-voxel FLOPs) — does
    saturating the 128 MXU lanes beat the (1,2,2) flagship?"""
    return _fwd_step(
        4, lambda u: u.create_tpu_optimized_model(s2d_factor=(1, 4, 4)))


@step("fwd_tpu_bf16_b8")
def fwd_tpu_b8():
    """Raw-forward batch A/B: is the 28.5 Mvox/s forward starved at b4?"""
    return _fwd_step(8, lambda u: u.create_tpu_optimized_model())


@step("bench_tpu_s2d4_fold_stream_u8")
def bench_s2d4_fold_stream_u8():
    """The full production stack on the aggressive-stem variant."""
    return _bench("0", "tpu_s2d4", "bfloat16", 4, blend="fold", stream=5,
                  output_dtype="uint8")


@step("bench_tpu_tta8")
def bench_tta8():
    """8x test-time augmentation (the reference's production option,
    transform.py:114-156) on the full production stack: the scanned-TTA
    design compiles the UNet once — this row prices what TTA actually
    costs on chip (ideal: 1/8 the non-TTA throughput; better means the
    forward was launch-bound)."""
    return _bench("0", "tpu", "bfloat16", 4, blend="fold", stream=2,
                  output_dtype="uint8", tta=True)


@step("bench_tpu_prod_overlap")
def bench_prod_overlap():
    """Geometry A/B: the reference's own production tutorial runs overlap
    2x32x32 (docs/source/tutorial.rst 'complex example'), not the README's
    4x64x64 — patch redundancy drops from ~2.2x to ~1.5x. Honest row: the
    config name carries the overlap stamp, and geometry_note excludes this
    row from the cached-headline pick (the 1.66 baseline was measured at
    the 4x64x64 geometry; cross-geometry wins would misattribute)."""
    import bench

    # half the default overlap: (2, 32, 32) at production geometry, and
    # still valid under the CPU rehearsal's smoke-geometry env overrides
    ov = tuple(o // 2 for o in bench.OUTPUT_OVERLAP)
    r = _bench("0", "tpu", "bfloat16", 4, blend="fold", stream=5,
               output_dtype="uint8", overlap=ov)
    r["geometry_note"] = f"overlap {'x'.join(map(str, ov))} (non-default)"
    return r


@step("bench_tpu_bf16_stacked")
def bench_flagship_stacked():
    """A/B: the stacked single-trailing-scatter accumulation (round-2's
    shipped-unmeasured redesign, now opt-in via CHUNKFLOW_BLEND_STACKED
    after measuring 0.66 vs 1.48 Mvox/s for the per-batch default)."""
    return _bench("0", "tpu", "bfloat16", 4, stacked="1")


@step("bench_tpu_bf16_b8")
def bench_flagship_b8():
    """Batch-size A/B: deeper batches may fill the MXU better."""
    return _bench("0", "tpu", "bfloat16", 8)


@step("bench_parity_f32_fold")
def bench_parity_fold():
    """Scatter-free parity-class fold blend (ops/fold_blend.py)."""
    return _bench("0", "parity", "float32", 2, blend="fold")


@step("bench_tpu_bf16_fold")
def bench_flagship_fold():
    return _bench("0", "tpu", "bfloat16", 4, blend="fold")


@step("bench_tpu_bf16_fold_stream_bf16out")
def bench_flagship_fold_stream():
    """Everything on: fold blend + pipelined D2H + bf16 results."""
    return _bench("0", "tpu", "bfloat16", 4, blend="fold", stream=5,
                  output_dtype="bfloat16")


@step("pallas_oracle")
def check_pallas_oracle():
    import numpy as np

    os.environ["CHUNKFLOW_PALLAS"] = "1"
    # the stacked A/B step sets CHUNKFLOW_BLEND_STACKED via
    # bench.run_config; clear it so the oracle vets the same (per-batch
    # default) path bench_tpu_bf16_pallas measures
    os.environ.pop("CHUNKFLOW_BLEND_STACKED", None)
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference.inferencer import Inferencer

    inferencer = Inferencer(
        input_patch_size=(4, 16, 16),
        output_patch_overlap=(2, 8, 8),
        num_output_channels=3,
        framework="identity",
        batch_size=2,
        crop_output_margin=False,
    )
    rng = np.random.default_rng(1)
    chunk = rng.random((8, 32, 32)).astype(np.float32)
    out = np.asarray(inferencer(Chunk(chunk)).array)
    mse = float(((out - chunk[None]) ** 2).mean())
    assert mse < 1e-8, f"pallas oracle MSE={mse}"
    return {"mse": mse}


@step("bench_tpu_bf16_pallas")
def bench_flagship_pallas():
    return _bench("1", "tpu", "bfloat16", 4)


@step("bench_blend_fused")
def bench_blend_fused():
    """Fused-vs-scatter ON-CHIP A/B (ISSUE 14): the fused Pallas kernel
    (weighting + aligned-window placement + HBM read-modify-write in one
    VMEM pass, ops/pallas_blend.py) against the XLA per-batch scatter
    default on the flagship config — both legs banked in ONE row so the
    comparison is atomic. This is the row that RETIRES the stale
    1.79 Mvox/s/chip cached headline (BENCH_r03-r05: the identical
    pre-rework row replayed three rounds): a fresh fused-vs-scatter pair
    supersedes it the first tunnel window that has a chip. A CPU-only
    window records an honest skip — the structural win is gated on CPU
    by ``bench.py blend_fused`` and correctness by the interpret-mode
    parity matrix in tier-1, but neither is an on-chip number."""
    plat = _platform()
    if plat not in ("tpu", "axon"):
        return {
            "skipped": True,
            "platform": plat,
            "note": (
                "CPU-only window: the fused-vs-scatter A/B needs a "
                "chip; bench.py blend_fused gates the data-movement "
                "structure on CPU and tests/ops/test_pallas_blend.py "
                "pins interpret-mode bit-identity in tier-1 — re-run "
                "when the tunnel has a chip to stamp the row that "
                "retires the 1.79 cached headline"
            ),
        }
    scatter = _bench("0", "tpu", "bfloat16", 4)
    fused = _bench("1", "tpu", "bfloat16", 4)
    speedup = (fused["mvox_s"] / scatter["mvox_s"]
               if scatter.get("mvox_s") else None)
    return {
        "mvox_s": fused.get("mvox_s"),
        "scatter_mvox_s": scatter.get("mvox_s"),
        "speedup": round(speedup, 3) if speedup else None,
        "note": (
            "fused Pallas blend (one VMEM pass: weighting + placement "
            "+ RMW; no weighted/padded stacks) vs the XLA per-batch "
            "scatter default, same flagship config — supersedes the "
            "BENCH_r03-r05 cached 1.79 row (pre-rework code)"
        ),
    }


@step("bench_front_half")
def bench_front_half():
    """Device-gather vs host-gather ON-CHIP A/B (ISSUE 15): the
    device-resident front half (raw chunk uploaded once, convert+gather
    in-program — ops/pallas_gather.py) against the CHUNKFLOW_GATHER=off
    host front on the flagship config — both legs banked in ONE row so
    the comparison is atomic. On a real tunnel the delta is PCIe bytes:
    the host front re-converts and the per-chunk path pays an eager
    whole-chunk f32 materialization before the program. A CPU-only
    window records an honest skip — the structural win is gated on CPU
    by ``bench.py front_half`` and correctness by the gather parity
    matrix in tier-1, but neither is an on-chip number."""
    plat = _platform()
    if plat not in ("tpu", "axon"):
        return {
            "skipped": True,
            "platform": plat,
            "note": (
                "CPU-only window: the device-vs-host front-half A/B "
                "needs a chip; bench.py front_half gates the "
                "H2D/data-movement structure on CPU and "
                "tests/ops/test_pallas_gather.py pins bitwise parity "
                "in tier-1 — re-run when the tunnel has a chip"
            ),
        }
    prev = os.environ.get("CHUNKFLOW_GATHER")
    try:
        os.environ["CHUNKFLOW_GATHER"] = "off"
        host = _bench("0", "tpu", "bfloat16", 4)
        os.environ["CHUNKFLOW_GATHER"] = "on"
        device = _bench("0", "tpu", "bfloat16", 4)
    finally:
        if prev is None:
            os.environ.pop("CHUNKFLOW_GATHER", None)
        else:
            os.environ["CHUNKFLOW_GATHER"] = prev
    speedup = (device["mvox_s"] / host["mvox_s"]
               if host.get("mvox_s") else None)
    return {
        "mvox_s": device.get("mvox_s"),
        "host_mvox_s": host.get("mvox_s"),
        "speedup": round(speedup, 3) if speedup else None,
        "note": (
            "device-resident front half (raw chunk resident, in-program "
            "convert+gather) vs the CHUNKFLOW_GATHER=off host front, "
            "same flagship config, one atomic row"
        ),
    }


@step("bench_fused_pipeline")
def bench_fused_pipeline():
    """Fused patch pipeline ON-CHIP A/B (ISSUE 17): the whole per-bucket
    step as one device program chain (CHUNKFLOW_FUSED_PIPELINE=on —
    device gather + fused blend + device-resident weighted stacks, no
    host round-trip between stages) against the default separate-stage
    path, flagship config, both legs in ONE row. On a real tunnel the
    delta is the inter-stage HBM/PCIe traffic the fusion deletes —
    profiling's hbm_intermediate_bytes column itemizes it. A CPU-only
    window records an honest skip — the structural win is gated on CPU
    by ``bench.py fused_pipeline`` and f32 bit-identity by the fused
    pipeline parity matrix in tier-1, but neither is an on-chip
    number."""
    plat = _platform()
    if plat not in ("tpu", "axon"):
        return {
            "skipped": True,
            "platform": plat,
            "note": (
                "CPU-only window: the fused-pipeline-vs-separate A/B "
                "needs a chip; bench.py fused_pipeline gates the "
                "serving structure (device-resident stacks vs host "
                "round-trip) on CPU and "
                "tests/inference/test_fused_pipeline.py pins f32 "
                "bitwise parity in tier-1 — re-run when the tunnel "
                "has a chip"
            ),
        }
    prev = os.environ.get("CHUNKFLOW_FUSED_PIPELINE")
    try:
        os.environ.pop("CHUNKFLOW_FUSED_PIPELINE", None)
        separate = _bench("0", "tpu", "bfloat16", 4)
        os.environ["CHUNKFLOW_FUSED_PIPELINE"] = "on"
        fused = _bench("1", "tpu", "bfloat16", 4)
    finally:
        if prev is None:
            os.environ.pop("CHUNKFLOW_FUSED_PIPELINE", None)
        else:
            os.environ["CHUNKFLOW_FUSED_PIPELINE"] = prev
    speedup = (fused["mvox_s"] / separate["mvox_s"]
               if separate.get("mvox_s") else None)
    return {
        "mvox_s": fused.get("mvox_s"),
        "separate_mvox_s": separate.get("mvox_s"),
        "speedup": round(speedup, 3) if speedup else None,
        "note": (
            "one fused patch program (CHUNKFLOW_FUSED_PIPELINE=on: "
            "device gather + fused blend + device-resident weighted "
            "stacks) vs the default separate-stage path, same flagship "
            "config, one atomic row"
        ),
    }


@step("e2e_split")
def e2e_split():
    """Where does the flagship config's wall time go? Separate H2D,
    on-device program, and D2H so the pipelining upside is quantified."""
    import numpy as np

    import jax.numpy as jnp

    import bench
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference import Inferencer

    os.environ["CHUNKFLOW_PALLAS"] = "0"
    # defensive: this split is attributed to the default flagship config,
    # so pin the default blend selection regardless of what ran before
    os.environ.pop("CHUNKFLOW_BLEND_STACKED", None)
    inferencer = Inferencer(
        input_patch_size=bench.INPUT_PATCH,
        output_patch_overlap=bench.OUTPUT_OVERLAP,
        num_output_channels=bench.NUM_OUT,
        framework="flax",
        batch_size=4,
        dtype="bfloat16",
        model_variant="tpu",
        crop_output_margin=False,
    )
    rng = np.random.default_rng(0)
    host = rng.random(bench.CHUNK_SIZE, dtype=np.float32)
    # warmup (compile)
    out = inferencer(Chunk(host))
    np.asarray(out.array)

    t0 = time.perf_counter()
    dev = jnp.asarray(host)
    dev.block_until_ready()
    h2d_s = time.perf_counter() - t0

    dchunk = Chunk(dev)
    t0 = time.perf_counter()
    out = inferencer(dchunk)  # blocks on compute; input already resident
    compute_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    np.asarray(out.array)
    d2h_s = time.perf_counter() - t0
    return {"h2d_s": round(h2d_s, 3), "compute_s": round(compute_s, 3),
            "d2h_s": round(d2h_s, 3)}


@step("bench_tpu_bf16_stream")
def bench_flagship_stream():
    """Steady-state pipelined throughput (Inferencer.stream)."""
    return _bench("0", "tpu", "bfloat16", 4, stream=5)


@step("bench_tpu_bf16_stream_bf16out")
def bench_flagship_stream_bf16out():
    """Pipelined + bfloat16 results off the device (half the D2H bytes)."""
    return _bench("0", "tpu", "bfloat16", 4, stream=5,
                  output_dtype="bfloat16")


@step("bench_tpu_fold_stream_u8")
def bench_flagship_fold_stream_u8():
    """Fold + pipeline + on-device uint8 quantization (quarter the D2H
    bytes; exactly the reference's save-time conversion)."""
    return _bench("0", "tpu", "bfloat16", 4, blend="fold", stream=5,
                  output_dtype="uint8")


@step("profile_flagship")
def profile_flagship():
    """VERDICT r2 item 3: committed profiler evidence for the forward
    pass. Captures (a) XLA's own cost analysis of the compiled flagship
    forward (FLOPs + bytes -> MXU utilization bound) and (b) a
    jax.profiler perfetto trace of three steady-state forwards under
    tools/profile_r03/ for offline op-level analysis."""
    import jax
    import jax.numpy as jnp

    from chunkflow_tpu.models import unet3d

    ps = _patch_shape()
    model = unet3d.create_tpu_optimized_model()
    params = unet3d.init_params(model, ps, 1)
    x = jnp.zeros((4,) + ps + (1,), jnp.float32)
    f = jax.jit(lambda p, v: model.apply({"params": p}, v))
    compiled = f.lower(params, x).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, list):  # older jax returns [dict]
        cost = cost[0] if cost else {}
    keep = {k: v for k, v in cost.items()
            if k in ("flops", "bytes accessed", "bytes accessed0{}",
                     "bytes accessed1{}", "bytes accessedout{}",
                     "optimal_seconds")}
    compiled(params, x).block_until_ready()  # warm
    t0 = time.perf_counter()
    for _ in range(3):
        compiled(params, x).block_until_ready()
    dt = (time.perf_counter() - t0) / 3
    # tools/profile_r03 is ON-CHIP trace evidence (VERDICT r3 item 2):
    # a CPU rehearsal must not write there, or a host trace could pass
    # for the real thing
    if _platform() in ("tpu", "axon"):
        trace_dir = os.path.join(os.path.dirname(__file__), "profile_r03")
    else:
        import tempfile

        trace_dir = tempfile.mkdtemp(prefix="chunkflow_profile_rehearsal_")
    with jax.profiler.trace(trace_dir):
        for _ in range(3):
            compiled(params, x).block_until_ready()
    # v5e peak: 197 TFLOP/s bf16, 819 GB/s HBM
    flops = float(keep.get("flops", 0.0))
    util = flops / dt / 197e12 if dt > 0 else 0.0
    return {"steady_ms": round(dt * 1e3, 1), "cost": keep,
            "mxu_util_bf16_peak": round(util, 4),
            "trace_dir": os.path.relpath(trace_dir)}


@step("bench_pipeline_seg")
def bench_pipeline_seg():
    """BASELINE config 3 / VERDICT r3 item 8: the full segmentation
    pipeline — flagship affinity inference on chip, then native watershed
    agglomeration + connected components on host (the reference's
    plugins/agglomerate.py:35-43 + flow.py:1803-1826 split). Untrained
    weights give narrow-range sigmoids, so affinities are min-max
    normalized before post-processing (standard normalize-op semantics);
    the reported number is end-to-end output Mvox/s with sub-splits."""
    import numpy as np

    import bench
    from chunkflow_tpu import native
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference import Inferencer

    os.environ["CHUNKFLOW_PALLAS"] = "0"
    os.environ.pop("CHUNKFLOW_BLEND_STACKED", None)
    inferencer = Inferencer(
        input_patch_size=bench.INPUT_PATCH,
        output_patch_overlap=bench.OUTPUT_OVERLAP,
        num_output_channels=bench.NUM_OUT,
        framework="flax",
        batch_size=4,
        dtype="bfloat16",
        model_variant="tpu",
        crop_output_margin=False,
    )
    rng = np.random.default_rng(0)
    host = rng.random(bench.CHUNK_SIZE, dtype=np.float32)
    np.asarray(inferencer(Chunk(host)).array)  # warm (compile)

    t0 = time.perf_counter()
    affs = np.asarray(inferencer(Chunk(host)).array, dtype=np.float32)
    t_inf = time.perf_counter() - t0
    lo, hi = float(affs.min()), float(affs.max())
    affs = (affs - lo) / max(hi - lo, 1e-9)
    t1 = time.perf_counter()
    seg, n_seg = native.watershed_agglomerate(
        affs, t_high=0.9999, t_low=0.0001, merge_threshold=0.7)
    t_agg = time.perf_counter() - t1
    t2 = time.perf_counter()
    _, n_cc = native.connected_components(seg)
    t_cc = time.perf_counter() - t2
    total = time.perf_counter() - t0
    nvox = float(np.prod(bench.CHUNK_SIZE))
    return {
        "mvox_s": round(nvox / total / 1e6, 3),
        "inference_s": round(t_inf, 2),
        "agglomerate_s": round(t_agg, 2),
        "cc_s": round(t_cc, 2),
        "segments": n_seg, "components": n_cc,
    }


@step("bench_pipeline_seg_streamed")
def bench_pipeline_seg_streamed():
    """The segmentation pipeline with the host stage OVERLAPPED
    (VERDICT r4 #3): stream(postprocess=...) runs chunk i's normalize +
    watershed agglomeration + connected components in a worker thread
    while chunk i+1's fused program executes on device. Done-criterion
    evidence: steady-state Mvox/s vs the sequential bench_pipeline_seg
    row, plus hidden_host_s = how much host time left the critical path:
    sum(host stages) minus how much the post-enabled run extended the
    device-only stream over the same chunks."""
    import numpy as np

    import bench
    from chunkflow_tpu import native
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference import Inferencer

    os.environ["CHUNKFLOW_PALLAS"] = "0"
    os.environ.pop("CHUNKFLOW_BLEND_STACKED", None)
    inferencer = Inferencer(
        input_patch_size=bench.INPUT_PATCH,
        output_patch_overlap=bench.OUTPUT_OVERLAP,
        num_output_channels=bench.NUM_OUT,
        framework="flax",
        batch_size=4,
        dtype="bfloat16",
        model_variant="tpu",
        crop_output_margin=False,
    )
    rng = np.random.default_rng(0)
    n_chunks = 3
    chunks = [
        Chunk(rng.random(bench.CHUNK_SIZE, dtype=np.float32),
              voxel_offset=(bench.CHUNK_SIZE[0] * i, 0, 0))
        for i in range(n_chunks)
    ]
    np.asarray(inferencer(chunks[0]).array)  # warm (compile)

    # device-only baseline over the SAME chunks: what the pipeline costs
    # with no host stage at all — the overlap evidence is how little the
    # post-enabled run exceeds this
    t0 = time.perf_counter()
    for _ in inferencer.stream(iter(chunks)):
        pass
    device_only_s = time.perf_counter() - t0

    host_s = []

    def post(out_chunk):
        t0 = time.perf_counter()
        affs = np.asarray(out_chunk.array, dtype=np.float32)
        lo, hi = float(affs.min()), float(affs.max())
        affs = (affs - lo) / max(hi - lo, 1e-9)
        seg, n_seg = native.watershed_agglomerate(
            affs, t_high=0.9999, t_low=0.0001, merge_threshold=0.7)
        _, n_cc = native.connected_components(seg)
        host_s.append(time.perf_counter() - t0)
        return n_seg, n_cc

    t0 = time.perf_counter()
    results = list(inferencer.stream(iter(chunks), postprocess=post))
    elapsed = time.perf_counter() - t0
    nvox = float(np.prod(bench.CHUNK_SIZE)) * n_chunks
    # host wall time that did NOT extend the pipeline: total host work
    # minus the amount by which adding it stretched the device-only run
    return {
        "mvox_s": round(nvox / elapsed / 1e6, 3),
        "elapsed_s": round(elapsed, 2),
        "device_only_s": round(device_only_s, 2),
        "stretch_s": round(elapsed - device_only_s, 2),
        "host_post_s": [round(s, 2) for s in host_s],
        "hidden_host_s": round(
            max(0.0, sum(host_s) - max(0.0, elapsed - device_only_s)), 2),
        "chunks": n_chunks,
        "segments": [int(r[0]) for r in results],
        "native_threads": os.environ.get("CHUNKFLOW_NATIVE_THREADS",
                                         "auto"),
    }


@step("bench_cli_task_loop")
def bench_cli_task_loop():
    """The reference's canonical production path, end to end through the
    CLI runtime: generate-tasks over a local precomputed volume ->
    load-precomputed -> flagship inference -> save-precomputed
    --async-write, with per-task timing-log sidecars. Metric = the
    reference's own log-summary semantics
    (/root/reference/chunkflow/flow/log_summary.py:69-71): per-task
    voxels / per-task seconds from the logs, steady-state = mean over
    tasks excluding the compile-carrying slowest one (a single
    invocation, so no cross-invocation retrace is misattributed as
    runtime overhead)."""
    import glob
    import shutil
    import tempfile

    import numpy as np
    from click.testing import CliRunner

    import bench
    from chunkflow_tpu.flow.cli import main as cli_main
    from chunkflow_tpu.volume.precomputed import PrecomputedVolume

    cz, cy, cx = bench.CHUNK_SIZE
    n_tasks = 4
    vol_size = (n_tasks * cz, cy, cx)  # tasks stacked along z
    tmp = tempfile.mkdtemp(prefix="chunkflow_cli_bench_")
    try:
        src = os.path.join(tmp, "src")
        dst = os.path.join(tmp, "dst")
        PrecomputedVolume.create(
            src, volume_size=vol_size, dtype="uint8",
            voxel_size=(40, 4, 4), block_size=(min(cz, 64),) * 3,
        )
        PrecomputedVolume.create(
            dst, volume_size=vol_size, dtype="uint8", num_channels=3,
            voxel_size=(40, 4, 4), block_size=(min(cz, 64),) * 3,
        )
        vol = PrecomputedVolume(src)
        from chunkflow_tpu.chunk.base import Chunk

        rng = np.random.default_rng(0)
        vol.save(Chunk(rng.integers(0, 256, vol_size, dtype=np.uint8)))

        runner = CliRunner()
        args = [
            "generate-tasks", "-v", src,
            "--chunk-size", str(cz), str(cy), str(cx),
            "load-precomputed", "-v", src,
            "inference",
            "--input-patch-size", *map(str, bench.INPUT_PATCH),
            "--output-patch-overlap", *map(str, bench.OUTPUT_OVERLAP),
            "--num-output-channels", "3",
            "--framework", "flax", "--model-variant", "tpu",
            "--dtype", "bfloat16", "--batch-size", "4",
            "--output-dtype", "uint8",
            "save-precomputed", "-v", dst, "--async-write",
        ]
        t0 = time.perf_counter()
        r = runner.invoke(cli_main, args, catch_exceptions=False)
        wall = time.perf_counter() - t0
        assert r.exit_code == 0, r.output[-2000:]
        logs = sorted(glob.glob(os.path.join(dst, "log", "*.json")))
        assert len(logs) == n_tasks, (len(logs), n_tasks)
        totals = []
        for path in logs:
            with open(path) as f:
                rec = json.load(f)
            totals.append(sum(rec["timer"].values()))
        totals.sort()
        steady = totals[:-1]  # drop the compile-carrying slowest task
        nvox_task = float(np.prod(bench.CHUNK_SIZE))
        return {
            "mvox_s": round(nvox_task / (sum(steady) / len(steady)) / 1e6, 3),
            "tasks": n_tasks,
            "wall_s": round(wall, 1),
            "task_seconds": [round(t, 2) for t in totals],
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


@step("bench_jumbo_bf16_u8")
def bench_jumbo():
    """Apples-to-apples with the reference's own headline task: its
    1.66 Mvoxel/s TITAN X number is a 108x2048x2048 affinity cutout
    (tests/data/log/*.json). Production configuration: per-batch scan
    accumulate (the stack budget gates the stacked/fold paths off at this
    size — the OOM-guard path this step exists to exercise), pipelined
    across 2 jumbo chunks, uint8 EM input riding the narrow H2D path
    (1/4 the transfer bytes of float32; device-side normalize), and
    on-device uint8 results (the reference's own save-time conversion)."""
    import bench

    jumbo = bench._env_triple("CHUNKFLOW_BENCH_JUMBO", (108, 2048, 2048))
    return _bench("0", "tpu", "bfloat16", 4,
                  chunk_size=jumbo, stream=2,
                  output_dtype="uint8", input_dtype="uint8")


@step("bench_multichip")
def bench_multichip():
    """The unified sharded engine (parallel/engine.py, ISSUE 13) on the
    real device(s): sharded-vs-single Mvox/s through the production
    Inferencer with the flagship config, plus a bitwise-identity check
    between the legs — the row that RETIRES the dry-run-only
    MULTICHIP_r0* entries. On a single-chip tunnel the row records the
    skip (an honest "needs a slice"), so the next tunnel window with a
    slice stamps the first real multi-chip throughput number."""
    import numpy as np

    import jax

    import bench
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference import Inferencer

    os.environ["CHUNKFLOW_PALLAS"] = "0"
    os.environ.pop("CHUNKFLOW_BLEND_STACKED", None)
    n_dev = jax.local_device_count()
    if n_dev < 2:
        return {
            "skipped": True,
            "n_devices": n_dev,
            "note": (
                "single-chip tunnel: unified-engine speedup needs a "
                "slice; bitwise parity is covered on the 8-device "
                "virtual mesh in tier-1 (tests/parallel/test_engine.py "
                "+ bench.py multichip_overlap)"
            ),
        }
    mesh_spec = f"data={n_dev}"
    rng = np.random.default_rng(0)
    chunk = Chunk(rng.random(bench.CHUNK_SIZE, dtype=np.float32))

    def leg(mesh):
        inferencer = Inferencer(
            input_patch_size=bench.INPUT_PATCH,
            output_patch_overlap=bench.OUTPUT_OVERLAP,
            num_output_channels=bench.NUM_OUT,
            framework="flax",
            batch_size=4,
            dtype="bfloat16",
            model_variant="tpu",
            mesh=mesh,
            crop_output_margin=False,
        )
        out = np.asarray(inferencer(chunk).array)  # warm (compile)
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            out = np.asarray(inferencer(chunk).array)
            times.append(time.perf_counter() - t0)
        mvox = float(np.prod(bench.CHUNK_SIZE)) / min(times) / 1e6
        return mvox, out

    single_mvox, ref = leg("1")
    sharded_mvox, out = leg(mesh_spec)
    return {
        "mvox_s": round(sharded_mvox, 3),
        "single_mvox_s": round(single_mvox, 3),
        "speedup": round(sharded_mvox / single_mvox, 2),
        "mesh": mesh_spec,
        "n_devices": n_dev,
        "bit_identical": bool(np.array_equal(ref, out)),
    }


@step("bench_sharded_replay")
def bench_sharded_replay():
    """Sharded blend replay (ISSUE 19) on a real slice: replicated vs
    sharded replay Mvox/s on a spatial ``y=<n_dev>`` mesh through the
    production Inferencer with the flagship config, plus a
    bitwise-identity check of both legs against each other. On a
    single-chip tunnel the row records the skip (an honest "needs a
    slice"); bitwise parity is already covered on the 8-device virtual
    mesh in tier-1 and bench.py multichip_sharded_replay measures the
    replay-work ratio on the CPU proxy."""
    import numpy as np

    import jax

    import bench
    from chunkflow_tpu.chunk.base import Chunk
    from chunkflow_tpu.inference import Inferencer

    os.environ["CHUNKFLOW_PALLAS"] = "0"
    os.environ.pop("CHUNKFLOW_BLEND_STACKED", None)
    n_dev = jax.local_device_count()
    if n_dev < 2:
        return {
            "skipped": True,
            "n_devices": n_dev,
            "note": (
                "single-chip tunnel: sharded-vs-replicated replay needs "
                "a slice; bitwise parity is covered on the 8-device "
                "virtual mesh in tier-1 (tests/parallel/test_engine.py) "
                "and the replay-work ratio on the CPU proxy (bench.py "
                "multichip_sharded_replay)"
            ),
        }
    mesh_spec = f"y={n_dev}"
    rng = np.random.default_rng(0)
    chunk = Chunk(rng.random(bench.CHUNK_SIZE, dtype=np.float32))
    prev_replay = os.environ.get("CHUNKFLOW_SHARD_REPLAY")

    def leg(replay_mode):
        os.environ["CHUNKFLOW_SHARD_REPLAY"] = replay_mode
        inferencer = Inferencer(
            input_patch_size=bench.INPUT_PATCH,
            output_patch_overlap=bench.OUTPUT_OVERLAP,
            num_output_channels=bench.NUM_OUT,
            framework="flax",
            batch_size=4,
            dtype="bfloat16",
            model_variant="tpu",
            mesh=mesh_spec,
            crop_output_margin=False,
        )
        out = np.asarray(inferencer(chunk).array)  # warm (compile)
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            out = np.asarray(inferencer(chunk).array)
            times.append(time.perf_counter() - t0)
        mvox = float(np.prod(bench.CHUNK_SIZE)) / min(times) / 1e6
        return mvox, out

    try:
        replicated_mvox, ref = leg("replicated")
        sharded_mvox, out = leg("sharded")
    finally:
        if prev_replay is None:
            os.environ.pop("CHUNKFLOW_SHARD_REPLAY", None)
        else:
            os.environ["CHUNKFLOW_SHARD_REPLAY"] = prev_replay
    return {
        "mvox_s": round(sharded_mvox, 3),
        "replicated_mvox_s": round(replicated_mvox, 3),
        "speedup": round(sharded_mvox / replicated_mvox, 2),
        "mesh": mesh_spec,
        "n_devices": n_dev,
        "bit_identical": bool(np.array_equal(ref, out)),
    }


@step("entry_compile")
def entry_compile():
    # pin the blend-kernel selection to auto (platform default) so the
    # certified program doesn't depend on which earlier bench steps ran
    # (they leak CHUNKFLOW_PALLAS into os.environ) — auto is also what the
    # driver's own entry() compile-check sees
    os.environ.pop("CHUNKFLOW_PALLAS", None)
    import jax

    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    out.block_until_ready()
    return {"shape": list(out.shape)}


def freeze_snapshot(dest, src=None):
    """Commit the live resume cache as a frozen ``tpu_validation_r{N}``
    snapshot: ``python tools/tpu_validation.py freeze tools/..._r06.json``.

    ADVICE r5 hardening — a frozen snapshot must be internally
    consistent: the r05 freeze shipped a tunnel row from a later failed
    retry (different commit, empty platform) next to bench rows banked
    under a live tunnel, inviting the reading "these numbers ran with no
    tunnel". The freeze now stamps ``_meta.tunnel_row_note`` whenever
    the tunnel row is not from the same attempt (commit) as the banked
    ``bench_*`` rows — or is an outright failure — and always writes a
    trailing newline."""
    src = src or RESULTS_PATH
    with open(src) as f:
        data = json.load(f)
    meta = data.get("_meta")
    if not isinstance(meta, dict):
        meta = {}
        data["_meta"] = meta
    for key, value in _git_meta().items():
        meta.setdefault(key, value)
    tunnel = data.get("tunnel")
    banked = sorted({
        str(row.get("commit"))
        for name, row in data.items()
        if name.startswith("bench_") and isinstance(row, dict)
        and row.get("ok")
    })
    if isinstance(tunnel, dict) and banked and (
            not tunnel.get("ok") or tunnel.get("commit") not in banked):
        meta["tunnel_row_note"] = (
            "tunnel row is the LAST RETRY (commit "
            f"{tunnel.get('commit') or '?'}, "
            f"ok={bool(tunnel.get('ok'))}), not the liveness check of "
            "the measurement window that banked the bench_* rows "
            f"(commit(s) {', '.join(banked)}); the banked rows ran "
            "under a live tunnel — a bench_* row cannot succeed "
            "without one"
        )
    with open(dest, "w") as f:
        json.dump(data, f, indent=2)
        f.write("\n")
    print(f"froze {src} -> {dest}"
          + (" (tunnel_row_note stamped)"
             if "tunnel_row_note" in meta else ""))
    return dest


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "freeze":
        if len(sys.argv) != 3:
            print("usage: python tools/tpu_validation.py freeze "
                  "tools/tpu_validation_r{N}.json", file=sys.stderr)
            return 2
        freeze_snapshot(sys.argv[2])
        return 0
    # Fail malformed geometry env up front (battery start, clear message)
    # rather than hours in: bench's module import parses CHUNK/PATCH/
    # OVERLAP; JUMBO is otherwise only parsed inside bench_jumbo, whose
    # SystemExit would escape the step decorator and kill the battery
    # with no failure row.
    import bench

    bench._env_triple("CHUNKFLOW_BENCH_JUMBO", (108, 2048, 2048))

    # A/B-first (VERDICT r2 item 2): the blend-default decision — per-batch
    # scatter (default) vs fold vs fold+stream+uint8 vs stacked — must bank
    # inside the first ~10 minutes of a tunnel window; diagnostics and the
    # riskiest steps (pallas, jumbo) come after.
    steps = [check_tunnel,
             bench_flagship_fold_stream_u8,  # production pipeline — the
             # expected headline banks FIRST: observed windows fit only
             # 2-3 compiles, and the scatter baseline is already banked
             # from the 03:47 window (1.07 Mvox/s)
             fwd_tpu_variant,  # raw forward: tunnel-speed control — r2
             # measured 28.5 Mvox/s on identical code-path; a matching
             # number pins today's 1.07-vs-1.79 scatter gap on the blend
             # rework, a lower one on the tunnel itself
             bench_flagship_xla,            # per-batch scatter default
             bench_flagship_fold,           # fold blend A/B
             bench_flagship_fold_stream,    # fold+stream, bf16 out
             bench_flagship_stream_bf16out,  # scatter+stream A/B partner
             check_pallas_oracle,  # VERDICT r4 #7: cheap compile+oracle
             # probe EARLY so "does pallas compile on hardware" banks
             # even if the window dies before the full pallas bench (kept
             # riskiest-last below); Mosaic rejections error loudly
             # without wedging the tunnel (observed round 1)
             bench_flagship_stacked,        # round-2 regression check
             fwd_tpu_mxu,  # conv-lowering A/B (baseline arm is
             # fwd_tpu_variant, moved early above as the tunnel control)
             fwd_tpu_s2d4, fwd_tpu_b8,      # layout / batch A/Bs
             bench_mxu_fold_stream_u8, bench_s2d4_fold_stream_u8,
             bench_prod_overlap, bench_tta8,
             profile_flagship, bench_flagship_b8,
             fwd_parity, bench_parity, bench_parity_fold,
             e2e_split, bench_flagship_stream, compile_split,
             bench_pipeline_seg, bench_pipeline_seg_streamed,
             bench_cli_task_loop, bench_jumbo,
             bench_flagship_pallas,
             bench_blend_fused,  # fused-vs-scatter A/B in ONE row
             # (ISSUE 14): the measurement that retires the stale 1.79
             # cached headline; cheap skip on a CPU-only window
             bench_front_half,  # device-vs-host front-half A/B in ONE
             # row (ISSUE 15): the PCIe-bytes measurement; cheap skip
             # on a CPU-only window
             bench_fused_pipeline,  # fused-vs-separate patch pipeline
             # A/B in ONE row (ISSUE 17): the inter-stage-HBM
             # measurement; cheap skip on a CPU-only window
             bench_multichip,  # unified-engine slice row (ISSUE 13):
             # cheap skip on a single-chip tunnel, the first real
             # multi-chip throughput number when a slice window opens
             bench_sharded_replay,  # sharded-vs-replicated replay A/B
             # in ONE row (ISSUE 19): the per-chip blend-HBM + replay-
             # work measurement; cheap skip on a single-chip tunnel
             entry_compile]
    # NOTE: jax caches backend-init failure in-process, so a failed tunnel
    # cannot be retried here — rerun the whole script (fresh process) after
    # a cool-down, e.g.:
    #   for i in $(seq 8); do python tools/tpu_validation.py && break; \
    #       sleep 300; done
    if not steps[0]():
        print("tunnel unavailable; aborting", file=sys.stderr)
        return 1
    ok = True
    for s in steps[1:]:
        good = s()
        ok = good and ok
        if not good and _tunnel_lost(s.step_name):
            # each further step would hang ~25-50 min inside the axon
            # client's retry loop before failing the same way; bail so the
            # outer retry loop gets a fresh process sooner
            print("tunnel lost mid-battery; aborting remaining steps",
                  file=sys.stderr)
            return 1
    return 0 if ok else 2


def _tunnel_lost(step_name: str) -> bool:
    """Did THIS step's failure look like a dead tunnel? (Checking the
    named entry, not the last dict entry: RESULTS also carries stale
    errors loaded from a prior run's JSON.) Matches bench.py's mark list:
    a mid-battery drop surfaces as UNAVAILABLE backend/compile errors,
    not only connection refusals."""
    entry = RESULTS.get(step_name)
    err = entry.get("error", "") if isinstance(entry, dict) else ""
    marks = ("Connection refused", "Connection Failed", "UNAVAILABLE",
             "Unable to initialize backend")
    return any(m in err for m in marks)


if __name__ == "__main__":
    raise SystemExit(main())
