"""Render tools/tpu_validation*.json into a markdown table (docs aid).

Usage: python tools/summarize_validation.py [path ...]
Defaults to tools/tpu_validation.json.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import BASELINE_MVOX_S as BASELINE  # noqa: E402


def summarize(path: str) -> None:
    with open(path) as f:
        data = json.load(f)
    print(f"### {os.path.basename(path)}\n")
    meta = data.get("_meta")
    if isinstance(meta, dict) and meta.get("measured_at_commit"):
        print(f"measured at: `{meta['measured_at_commit']}`"
              f" ({meta.get('measured_at_utc', '?')})\n")
    print("| step | result |")
    print("|---|---|")
    for step, payload in data.items():
        if step.startswith("_") or not isinstance(payload, dict):
            continue  # _meta is provenance, not a battery step
        if not payload.get("ok"):
            err = (payload.get("error") or "").strip().splitlines()
            tail = err[-1][:80] if err else "?"
            print(f"| {step} | FAILED ({tail}) |")
            continue
        value = payload.get("value")
        if isinstance(value, dict) and "mvox_s" in value:
            mv = value["mvox_s"]
            extra = ", ".join(
                f"{k}={v}" for k, v in value.items() if k != "mvox_s"
            )
            print(
                f"| {step} | **{mv} Mvox/s** ({mv / BASELINE:.2f}x baseline"
                f"{'; ' + extra if extra else ''}) |"
            )
        else:
            print(f"| {step} | {json.dumps(value)[:100]} |")
    print()


if __name__ == "__main__":
    paths = sys.argv[1:] or [
        os.path.join(os.path.dirname(__file__), "tpu_validation.json")
    ]
    for p in paths:
        summarize(p)
