"""Host-side watershed/agglomeration throughput bench (VERDICT r4 #3).

Generates a synthetic Voronoi affinity volume at the inference bench
geometry (64x512x512, overridable via BENCH_SHAPE=z,y,x) and times
`native.watershed_agglomerate` end-to-end plus per-phase (set
CHUNKFLOW_WATERSHED_TIMING=1 when invoking).  The reference runs this
stage through the waterz wheel on dedicated CPU fleets
(reference plugins/agglomerate.py:35-43); here it shares the worker, so
its throughput must keep up with the on-chip inference target
(>= 6.64 Mvox/s).

Run CPU-only:  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    CHUNKFLOW_WATERSHED_TIMING=1 python tools/bench_watershed.py
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def voronoi_affinity(shape, n_objects=600, noise=0.1, inside=0.9,
                     boundary=0.1, seed=0):
    """Analytic Voronoi ground truth -> 3-channel affinity. Labels come
    from a cKDTree nearest-seed query over the full voxel grid (~800 MB
    of int64 temporaries at 64x512x512 — watch BENCH_SHAPE upscaling)."""
    from scipy.spatial import cKDTree

    from chunkflow_tpu.chunk import AffinityMap

    rng = np.random.default_rng(seed)
    seeds = np.stack([rng.uniform(0, s, n_objects) for s in shape], axis=1)
    tree = cKDTree(seeds)
    zz, yy, xx = np.meshgrid(*(np.arange(s) for s in shape), indexing="ij")
    pts = np.stack([zz.ravel(), yy.ravel(), xx.ravel()], 1)
    _, nearest = tree.query(pts, workers=-1)
    gt = (nearest + 1).reshape(shape).astype(np.uint32)
    aff = np.asarray(
        AffinityMap.from_segmentation(gt, inside=inside, boundary=boundary)
        .array
    )
    aff = aff + rng.normal(0, noise, aff.shape).astype(np.float32)
    return np.clip(aff, 0, 1).astype(np.float32), gt


def main():
    shape = tuple(
        int(v) for v in os.environ.get("BENCH_SHAPE", "64,512,512").split(",")
    )
    from chunkflow_tpu import native

    t0 = time.perf_counter()
    aff, gt = voronoi_affinity(shape)
    gen_s = time.perf_counter() - t0

    native.load()  # build outside the timed region
    # warmup on a small block so page faults/alloc paths are primed
    native.watershed_agglomerate(aff[:, :8, :64, :64], 0.9, 0.3, 0.5)

    repeats = int(os.environ.get("BENCH_REPEATS", "3"))
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        seg, count = native.watershed_agglomerate(aff, 0.9, 0.3, 0.5)
        best = min(best, time.perf_counter() - t0)

    nvox = int(np.prod(shape))
    from chunkflow_tpu.chunk.segmentation import Segmentation

    m = Segmentation(seg).evaluate(gt)
    out = {
        "metric": "watershed_agglomerate_mvox_per_s",
        "shape": list(shape),
        "value": round(nvox / best / 1e6, 3),
        "seconds": round(best, 3),
        "segments": int(count),
        "fixture_gen_s": round(gen_s, 2),
        "adjusted_rand_index": round(float(m["adjusted_rand_index"]), 4),
        "voi": round(float(m["voi_split"] + m["voi_merge"]), 4),
        "threads": os.environ.get("CHUNKFLOW_NATIVE_THREADS", "auto"),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
