#!/bin/bash
# Local graftlint gate: run the same check CI runs (run_tests.sh stage 2)
# before a commit ever leaves the machine. Wired either as a classic git
# hook —
#
#     ln -s ../../tools/pre-commit-graftlint.sh .git/hooks/pre-commit
#
# — or through the pre-commit framework (.pre-commit-config.yaml ships a
# `local` hook entry pointing here). The per-file result cache
# (.graftlint_cache/, keyed by content hash) makes the warm path ~20x
# faster than a cold run, so the hook costs well under 100ms when only a
# few files changed. GRAFTLINT_PRECOMMIT_SKIP=1 bypasses (matching
# CHUNKFLOW_SKIP_LINT for the CI stage).
set -u
cd "$(dirname "$0")/.."

if [ "${GRAFTLINT_PRECOMMIT_SKIP:-0}" = "1" ]; then
    echo "graftlint pre-commit: skipped (GRAFTLINT_PRECOMMIT_SKIP=1)"
    exit 0
fi

# Lint the full configured include set, not just the staged files: a
# staged edit can create a NEW finding in an unstaged neighbor (the
# thread model and traced-function analysis are module-wide), and the
# cache makes whole-tree reruns cheap anyway.
python -m tools.graftlint --stats
rc=$?
if [ $rc -ne 0 ]; then
    echo >&2
    echo "graftlint pre-commit: new findings (or parse error) — fix them" >&2
    echo "or suppress with an inline justification (docs/linting.md)." >&2
fi
exit $rc
