"""Offline op-level analysis of a jax.profiler trace.

The battery's ``profile_flagship`` step writes a perfetto trace under
``tools/profile_r03/`` on the real chip; tensorboard's profile plugin is
not installed in this image, so this parser extracts the op-level story
directly from the ``*.trace.json.gz`` event files: top ops by total
device time, grouped by XLA op category (convolution / fusion / copy /
all-reduce / ...), with per-category totals. That attribution is what
decides the next forward-pass lever (VERDICT r2 item 3).

Since PR 8 this is also the summarizer for the device-performance
plane's bounded captures (core/profiling.py: windowed ``--profile-dir``
runs, anomaly captures, the ``POST /profile`` route): importable
(:func:`summarize_trace_dir`), machine-readable (``--json``), and an
empty or missing trace dir is a warning, not a crash — ``log-summary``
calls through here for every ``profile-*`` dir it finds under a
metrics dir.

Usage: python tools/analyze_trace.py [trace_dir] [--top N] [--json]
"""
from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import re
import sys


def find_trace_files(trace_dir: str):
    pattern = os.path.join(
        trace_dir, "**", "*.trace.json.gz"
    )
    return sorted(glob.glob(pattern, recursive=True))


def load_events(path: str):
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    return data.get("traceEvents", [])


_CATEGORY_RULES = (
    ("convolution", re.compile(r"conv", re.I)),
    ("matmul", re.compile(r"dot|gemm|matmul", re.I)),
    ("copy/transpose", re.compile(r"copy|transpose|reshape|bitcast", re.I)),
    ("scatter", re.compile(r"scatter", re.I)),
    ("gather/slice", re.compile(r"gather|slice", re.I)),
    ("reduce", re.compile(r"reduce|all-reduce|psum", re.I)),
    ("fusion", re.compile(r"fusion", re.I)),
    ("infeed/outfeed", re.compile(r"infeed|outfeed|transfer", re.I)),
)


def categorize(name: str) -> str:
    for cat, rx in _CATEGORY_RULES:
        if rx.search(name):
            return cat
    return "other"


def device_op_durations(events):
    """name -> total device-lane microseconds. Device lanes are the pids
    whose process_name metadata mentions TPU/device; fall back to 'every
    complete event with a duration' when metadata is absent."""
    device_pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            name = str(e.get("args", {}).get("name", ""))
            if re.search(r"tpu|device|/device:", name, re.I):
                device_pids.add(e.get("pid"))
    durations = collections.Counter()
    counts = collections.Counter()
    host_rx = re.compile(r"\.py:|PjitFunction|^trace$")
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        if device_pids:
            if e.get("pid") not in device_pids:
                continue
        elif host_rx.search(e.get("name", "")):
            # no device metadata (CPU traces): drop python-frame events
            continue
        name = e.get("name", "?")
        durations[name] += e["dur"]
        counts[name] += 1
    return durations, counts


def summarize_trace_dir(trace_dir: str, top: int = 25) -> dict:
    """Aggregate every ``*.trace.json.gz`` under ``trace_dir`` (an
    empty or missing dir yields ``files == 0``, never raises)::

        {"trace_dir": ..., "files": n, "total_device_us": x,
         "categories": [{"category", "us", "share"}, ...],   # sorted
         "top_ops": [{"name", "us", "share", "count"}, ...]}
    """
    files = find_trace_files(trace_dir)
    durations = collections.Counter()
    counts = collections.Counter()
    for path in files:
        try:
            d, c = device_op_durations(load_events(path))
        except (OSError, ValueError):
            continue  # a torn/corrupt trace file is skippable evidence
        durations.update(d)
        counts.update(c)
    total_us = sum(durations.values())
    by_cat = collections.Counter()
    for name, dur in durations.items():
        by_cat[categorize(name)] += dur
    return {
        "trace_dir": trace_dir,
        "files": len(files),
        "total_device_us": total_us,
        "categories": [
            {"category": cat, "us": dur,
             "share": dur / total_us if total_us else 0.0}
            for cat, dur in by_cat.most_common()
        ],
        "top_ops": [
            {"name": name, "us": dur,
             "share": dur / total_us if total_us else 0.0,
             "count": counts[name]}
            for name, dur in durations.most_common(top)
        ],
    }


def print_summary(summary: dict) -> None:
    """Human rendering of a :func:`summarize_trace_dir` result."""
    print(f"{summary['files']} trace file(s); total device-op time "
          f"{summary['total_device_us'] / 1e3:.2f} ms\n")
    print("== by category ==")
    for row in summary["categories"]:
        print(f"{row['us'] / 1e3:10.2f} ms  {100 * row['share']:5.1f}%"
              f"  {row['category']}")
    print(f"\n== top {len(summary['top_ops'])} ops ==")
    for row in summary["top_ops"]:
        print(f"{row['us'] / 1e3:10.2f} ms  {100 * row['share']:5.1f}%"
              f"  x{row['count']:<5d} {row['name'][:90]}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "trace_dir", nargs="?",
        default=os.path.join(os.path.dirname(__file__), "profile_r03"),
    )
    parser.add_argument("--top", type=int, default=25)
    parser.add_argument(
        "--json", action="store_true",
        help="emit the summary as one JSON object (log-summary "
             "consumption) instead of the human tables",
    )
    args = parser.parse_args(argv)

    summary = summarize_trace_dir(args.trace_dir, top=args.top)
    if summary["files"] == 0:
        # a missing/empty dir is an answer (nothing captured here), not
        # a crash: log-summary sweeps every profile-* candidate dir
        print(f"warning: no *.trace.json.gz under {args.trace_dir}",
              file=sys.stderr)
        if args.json:
            print(json.dumps(summary))
        return 0
    if args.json:
        print(json.dumps(summary))
    else:
        print_summary(summary)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
