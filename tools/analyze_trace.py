"""Offline op-level analysis of a jax.profiler trace.

The battery's ``profile_flagship`` step writes a perfetto trace under
``tools/profile_r03/`` on the real chip; tensorboard's profile plugin is
not installed in this image, so this parser extracts the op-level story
directly from the ``*.trace.json.gz`` event files: top ops by total
device time, grouped by XLA op category (convolution / fusion / copy /
all-reduce / ...), with per-category totals. That attribution is what
decides the next forward-pass lever (VERDICT r2 item 3).

Usage: python tools/analyze_trace.py [trace_dir] [--top N]
"""
from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import re


def find_trace_files(trace_dir: str):
    pattern = os.path.join(
        trace_dir, "**", "*.trace.json.gz"
    )
    return sorted(glob.glob(pattern, recursive=True))


def load_events(path: str):
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    return data.get("traceEvents", [])


_CATEGORY_RULES = (
    ("convolution", re.compile(r"conv", re.I)),
    ("matmul", re.compile(r"dot|gemm|matmul", re.I)),
    ("copy/transpose", re.compile(r"copy|transpose|reshape|bitcast", re.I)),
    ("scatter", re.compile(r"scatter", re.I)),
    ("gather/slice", re.compile(r"gather|slice", re.I)),
    ("reduce", re.compile(r"reduce|all-reduce|psum", re.I)),
    ("fusion", re.compile(r"fusion", re.I)),
    ("infeed/outfeed", re.compile(r"infeed|outfeed|transfer", re.I)),
)


def categorize(name: str) -> str:
    for cat, rx in _CATEGORY_RULES:
        if rx.search(name):
            return cat
    return "other"


def device_op_durations(events):
    """name -> total device-lane microseconds. Device lanes are the pids
    whose process_name metadata mentions TPU/device; fall back to 'every
    complete event with a duration' when metadata is absent."""
    device_pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            name = str(e.get("args", {}).get("name", ""))
            if re.search(r"tpu|device|/device:", name, re.I):
                device_pids.add(e.get("pid"))
    durations = collections.Counter()
    counts = collections.Counter()
    host_rx = re.compile(r"\.py:|PjitFunction|^trace$")
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        if device_pids:
            if e.get("pid") not in device_pids:
                continue
        elif host_rx.search(e.get("name", "")):
            # no device metadata (CPU traces): drop python-frame events
            continue
        name = e.get("name", "?")
        durations[name] += e["dur"]
        counts[name] += 1
    return durations, counts


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "trace_dir", nargs="?",
        default=os.path.join(os.path.dirname(__file__), "profile_r03"),
    )
    parser.add_argument("--top", type=int, default=25)
    args = parser.parse_args()

    files = find_trace_files(args.trace_dir)
    if not files:
        raise SystemExit(f"no *.trace.json.gz under {args.trace_dir}")

    durations = collections.Counter()
    counts = collections.Counter()
    for path in files:
        d, c = device_op_durations(load_events(path))
        durations.update(d)
        counts.update(c)

    total_us = sum(durations.values())
    print(f"{len(files)} trace file(s); total device-op time "
          f"{total_us / 1e3:.2f} ms\n")

    by_cat = collections.Counter()
    for name, dur in durations.items():
        by_cat[categorize(name)] += dur
    print("== by category ==")
    for cat, dur in by_cat.most_common():
        print(f"{dur / 1e3:10.2f} ms  {100 * dur / max(total_us, 1):5.1f}%"
              f"  {cat}")

    print(f"\n== top {args.top} ops ==")
    for name, dur in durations.most_common(args.top):
        print(f"{dur / 1e3:10.2f} ms  {100 * dur / max(total_us, 1):5.1f}%"
              f"  x{counts[name]:<5d} {name[:90]}")


if __name__ == "__main__":
    main()
