#!/bin/bash
# Cooldown then retry loop for the TPU validation battery (resumable:
# completed steps skip; a tunnel drop only costs the failed step).
sleep "${BATTERY_COOLDOWN:-600}"
rc=1
for i in $(seq 12); do
    echo "=== battery attempt $i $(date -u +%H:%M:%S) ===" >> tools/tpu_validation.log
    python tools/tpu_validation.py >> tools/tpu_validation.log 2>&1
    rc=$?
    [ "$rc" -eq 0 ] && break
    sleep 300
done
echo "=== battery loop done rc=$rc $(date -u +%H:%M:%S) ===" >> tools/tpu_validation.log
