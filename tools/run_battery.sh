#!/bin/bash
# Cooldown then retry loop for the TPU validation battery (resumable:
# completed steps skip; a tunnel drop only costs the failed step).
cd "$(dirname "$0")/.." || exit 2
sleep "${BATTERY_COOLDOWN:-600}"
attempts="${BATTERY_ATTEMPTS:-12}"
case "$attempts" in
    ''|*[!0-9]*|0) echo "invalid BATTERY_ATTEMPTS='$attempts'" >&2; exit 2;;
esac
rc=1
for i in $(seq "$attempts"); do
    echo "=== battery attempt $i $(date -u +%H:%M:%S) ===" >> tools/tpu_validation.log
    python tools/tpu_validation.py >> tools/tpu_validation.log 2>&1
    rc=$?
    [ "$rc" -eq 0 ] && break
    sleep 300
done
echo "=== battery loop done rc=$rc $(date -u +%H:%M:%S) ===" >> tools/tpu_validation.log
