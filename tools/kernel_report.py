"""Per-kernel analytic cost report: VMEM footprint vs device budget,
bytes per grid step, whole-grid traffic and FLOPs — for the shipping
Pallas kernels, at the geometry you ask for.

The numbers are the BUILDERS' own arithmetic
(``chunkflow_tpu.ops.pallas_blend.fused_kernel_cost`` /
``chunkflow_tpu.ops.pallas_gather.gather_kernel_cost``) — the same
model the GL021 lint rule applies statically and the same stamps
``profiling.stamp_cost`` folds into the programs.json catalog's
``vmem_bytes`` column, so the three planes (lint, ledger, this report)
can never drift apart: all read one formula that lives next to the
kernel it describes.

With ``--programs path/to/programs.json`` the report cross-checks the
stamped catalog against the analytic model and flags any drift
(a stamp site that fell behind a kernel change).

Usage:
  python tools/kernel_report.py [--patch Z,Y,X] [--batch N]
      [--channels-in N] [--channels-out N]
      [--dtypes uint8,uint16,float32] [--programs programs.json]
      [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _triple(text: str):
    parts = [int(p) for p in text.split(",")]
    if len(parts) != 3:
        raise argparse.ArgumentTypeError(f"want Z,Y,X — got {text!r}")
    return tuple(parts)


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for scale, suffix in ((2**30, "G"), (2**20, "M"), (2**10, "K")):
        if n >= scale:
            return f"{n / scale:.2f}{suffix}"
    return f"{n:.0f}B"


def build_report(patch, batch: int, ci: int, co: int,
                 dtypes) -> list:
    """One row per kernel flavor: name, geometry, and the analytic cost
    dict, plus the share of the device VMEM budget the footprint
    claims (the GL021 denominator — ``CHUNKFLOW_VMEM_BUDGET`` /
    ``CHUNKFLOW_VMEM_DEVICE`` aware)."""
    from chunkflow_tpu.ops import pallas_blend, pallas_gather
    from tools.graftlint.pallas import vmem_budget_bytes

    budget = vmem_budget_bytes()
    rows = []
    for dtype in dtypes:
        cost = pallas_gather.gather_kernel_cost(batch, ci, patch, dtype)
        rows.append({
            "kernel": "gather_patches",
            "geometry": f"B={batch} ci={ci} pin={patch} {dtype}",
            **cost,
            "vmem_budget": budget,
            "vmem_frac": cost["vmem_bytes"] / budget,
        })
    cost = pallas_blend.fused_kernel_cost(batch, co, patch)
    rows.append({
        "kernel": "fused_accumulate_patches",
        "geometry": f"B={batch} co={co} pout={patch} float32",
        **cost,
        "vmem_budget": budget,
        "vmem_frac": cost["vmem_bytes"] / budget,
    })
    # the composed fused-pipeline step (ISSUE 17): gather + blend as
    # sequential stages of one program — VMEM is the max stage
    # footprint; hbm_intermediate is what the SEPARATE-programs
    # composition would pay in inter-stage stack traffic (~0 fused)
    from chunkflow_tpu.ops import blend

    for dtype in dtypes:
        cost = blend.pipeline_kernel_cost(batch, ci, co, patch, patch,
                                          dtype)
        rows.append({
            "kernel": "patch_pipeline",
            "geometry": f"B={batch} ci={ci} co={co} p={patch} {dtype}",
            **cost,
            "vmem_budget": budget,
            "vmem_frac": cost["vmem_bytes"] / budget,
        })
    return rows


def check_programs(path: str, rows: list) -> list:
    """Cross-check a programs.json catalog's stamped ``vmem_bytes``
    against the analytic model: families whose stamp disagrees with any
    reported row's kernel (same formula, so equality is exact when the
    bench geometry matches) come back as drift notes; families without
    a stamp are skipped — XLA reference legs carry no VMEM story."""
    with open(path) as f:
        payload = json.load(f)
    notes = []
    analytic = {r["kernel"]: r["vmem_bytes"] for r in rows}
    stamped_families = {
        "blend_fused": "fused_accumulate_patches",
        "front_dev": "gather_patches",
        "pipe_fused": "patch_pipeline",
    }
    for entry in payload.get("programs", []):
        kernel = stamped_families.get(entry.get("family"))
        vmem = entry.get("vmem_bytes")
        if kernel is None or vmem is None:
            continue
        want = analytic.get(kernel)
        if want is not None and float(vmem) != float(want):
            notes.append(
                f"{entry['family']}: stamped vmem {_fmt_bytes(vmem)} != "
                f"analytic {_fmt_bytes(want)} at the reported geometry "
                f"(bench geometry differs, or a stamp site fell behind "
                f"a kernel change)"
            )
    return notes


def print_report(rows: list) -> None:
    print("kernel cost report (analytic — the GL021/stamp_cost model):")
    print(
        f"  {'kernel':<26} {'geometry':<34} {'vmem':>8} {'of budget':>9} "
        f"{'B/step':>8} {'grid':>6} {'bytes':>9} {'flops':>9} "
        f"{'sep hbm_i':>9}"
    )
    for r in rows:
        # sep hbm_i: the inter-stage stack traffic a SEPARATE-programs
        # composition of this row's stages would pay ('-' for single
        # kernels — only the composed pipeline row carries it)
        print(
            f"  {r['kernel']:<26} {r['geometry']:<34} "
            f"{_fmt_bytes(r['vmem_bytes']):>8} {r['vmem_frac']:>9.1%} "
            f"{_fmt_bytes(r['bytes_per_step']):>8} "
            f"{r['grid_steps']:>6} "
            f"{_fmt_bytes(r['bytes_accessed']):>9} "
            f"{r['flops'] / 1e9:>8.2f}G "
            f"{_fmt_bytes(r.get('hbm_intermediate_bytes')):>9}"
        )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Analytic VMEM / traffic report for the shipping "
                    "Pallas kernels")
    parser.add_argument("--patch", type=_triple, default=(4, 64, 64),
                        help="patch Z,Y,X (default 4,64,64)")
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--channels-in", type=int, default=1)
    parser.add_argument("--channels-out", type=int, default=3)
    parser.add_argument("--dtypes", default="uint8,uint16,float32",
                        help="gather chunk dtypes (comma-separated)")
    parser.add_argument("--programs", default=None,
                        help="programs.json to cross-check stamps against")
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)

    rows = build_report(args.patch, args.batch, args.channels_in,
                        args.channels_out, args.dtypes.split(","))
    notes = check_programs(args.programs, rows) if args.programs else []
    if args.json:
        json.dump({"rows": rows, "drift": notes}, sys.stdout, indent=2)
        print()
    else:
        print_report(rows)
        for note in notes:
            print(f"  DRIFT: {note}")
    over = [r for r in rows if r["vmem_frac"] > 1.0]
    if over:
        for r in over:
            print(f"  OVER BUDGET: {r['kernel']} at {r['geometry']} — "
                  f"{_fmt_bytes(r['vmem_bytes'])} of "
                  f"{_fmt_bytes(r['vmem_budget'])}", file=sys.stderr)
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
